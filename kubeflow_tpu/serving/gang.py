"""Multi-host serving: the predictor as a gang.

The reference serves multi-accelerator models by giving the predictor pod
N GPUs and letting vLLM/Triton span them inside one container [upstream:
kserve/kserve -> python/huggingfaceserver; SURVEY.md §2.2 per-framework
runtimes, §3.3 predictor hot path].  A TPU pod slice is different: a
v5e-4x4 is 4 HOSTS x 4 chips — no single process addresses all 16 chips,
so a TP=16 predictor is necessarily a *gang* of cooperating host
processes executing the same SPMD programs in lockstep (the multi-host
jit contract, SURVEY.md §2.6) — exactly the shape this platform already
launches for training (runtime/bootstrap.py env triple ->
``jax.distributed.initialize`` -> global mesh).

Design — rank 0 decides, everyone dispatches:

- every gang member loads the same snapshot, builds the same
  ``ContinuousEngine`` programs over the same global serving mesh
  (``engine_kwargs`` keeps the knobs byte-identical), and contributes its
  addressable shards of the weights (serving/sharded.py
  ``place_params``);
- rank 0 additionally owns the HTTP frontend (``ModelServer``) and the
  engine's scheduler thread.  The scheduler's *decisions* — which
  requests admit into which slots, the decode schedule, sampling keys —
  are host-side numpy scalars/arrays; :class:`GangChannel` streams them
  to the followers as length-prefixed pickles over TCP **before** rank 0
  dispatches, so every host issues the identical dispatch sequence and
  XLA's collectives line up;
- device data never crosses the channel: weights, the KV slot pool and
  logits live sharded across the gang's chips; the only host fetch is
  rank 0's sampled-token read, which the decode program replicates
  (``constrain_replicated``) so rank 0 can read it locally.

The dispatched programs are the SAME ones the single-process engine (and
the AOT artifact, scripts/aot_7b_serving.py) compiles — the gang changes
where processes sit, not what runs.  ``__graft_entry__.dryrun_multichip``'s
serving leg therefore covers the gang's data plane.

Failure semantics are layered (ISSUE 1):

- the control stream heals itself first: rank 0 heartbeats the stream
  and keeps a bounded replay log; a follower whose socket drops (the
  process is alive — only the TCP link died) reconnects with exponential
  backoff, re-authenticates, reports the last sequence it applied, and
  rank 0 replays exactly the missed frames.  Rank 0 *evicts* a dead
  connection instead of wedging the scheduler, and re-admits the same
  rank on reconnect (an extra token-valid connection replaces its
  predecessor — it never consumes another follower slot);
- only when a follower stays gone past the re-attach grace (its process
  is actually dead) or falls off the replay log does the failure
  escalate to the JaxJob machinery: rank 0's engine goes fatal, the pod
  exits non-zero, the JaxJob controller gang-restarts (with jittered
  backoff), and rank 0 re-binds the same frontend port.  While the gang
  re-forms, the InferenceService controller marks the revision Degraded
  and keeps routing to its healthy replicas.

Chaos testing: every socket the channel creates passes through an
injectable ``sock_wrap`` (``kubeflow_tpu.chaos.FaultPlan.socket_wrapper``),
so drops/delays can be injected at exact protocol points.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import signal
import socket
import struct
import threading
import time
from typing import Any, Optional

import numpy as np

from . import continuous as contlib
from . import programs as programslib
from ..runtime import bootstrap

log = logging.getLogger("kubeflow_tpu.serving")

#: pod-env key holding the JSON serving config (engine knobs +
#: storage_path + serve_port + gang_port) the ISvc controller freezes at
#: gang-placement time
ENV_SERVE_CONFIG = "KFT_SERVE_CONFIG"

_LEN = struct.Struct("!Q")


class ChannelClosed(RuntimeError):
    """The control stream died (a peer crashed or shut down)."""


class GangChannel:
    """Rank-0 -> followers control stream: length-prefixed pickles over
    TCP.  Carries ONLY host-side scheduler decisions (op tag + numpy
    args) between mutually-trusting gang members of one job — never
    request payloads to the outside world and never device data.

    Trust boundary: the stream is pickle between processes of ONE JaxJob,
    so admission is guarded by a per-job shared ``token`` (delivered to
    the gang's pods through a side channel — a 0600 token file, the
    Secret-mount analog — NOT the cluster-readable env).  A follower must
    present it before it may occupy a slot; a token-valid connection for
    an already-connected rank REPLACES that rank's connection (reconnect
    semantics) rather than consuming another slot, and rank 0 closes
    anything that fails the handshake.  Deserialization still trusts
    rank 0, which is the same trust a follower already extends to the
    process that chose its dispatch stream.

    Liveness + recovery (module docstring): rank 0 heartbeats every
    ``hb_interval`` and keeps the last ``replay_log`` published frames;
    followers ack their applied sequence.  A follower socket that errors
    or goes silent past ``dead_peer_timeout`` is EVICTED (publishing
    continues into the log); a follower that reconnects within
    ``reattach_timeout`` re-auths, reports its last applied seq and has
    exactly the missed frames replayed.  Past the grace — or off the end
    of the replay log — the channel goes fatal and the JaxJob gang
    restart takes over.

    ``sock_wrap`` wraps every socket the channel creates (chaos
    injection seam, kubeflow_tpu.chaos).
    """

    #: wire frame tags (leader->follower: msg/hb/gone; follower->leader:
    #: hello/ack)
    _MSG, _HB, _GONE, _HELLO, _ACK = "msg", "hb", "gone", "hello", "ack"

    def __init__(self, rank: int, *, token: str = "",
                 hb_interval: float = 0.5, dead_peer_timeout: float = 3.0,
                 reattach_timeout: float = 10.0,
                 reconnect_timeout: float = 10.0, replay_log: int = 1024,
                 sock_wrap=None) -> None:
        self.rank = rank
        self._token = token
        self._hb_interval = hb_interval
        self._dead_peer_timeout = dead_peer_timeout
        self._reattach_timeout = reattach_timeout
        self._reconnect_timeout = reconnect_timeout
        self._sock_wrap = sock_wrap or (lambda s: s)
        self._lock = threading.Lock()
        self._joined = threading.Condition(self._lock)
        self._closing = threading.Event()
        # leader state
        self._srv: Optional[socket.socket] = None
        self._want = 0
        self._followers: dict[int, Any] = {}
        self._last_ack: dict[int, float] = {}
        self._lost: dict[int, float] = {}
        from collections import deque

        self._log: "deque[tuple[int, bytes]]" = deque(maxlen=max(replay_log, 1))
        self._seq = 0
        self._dead: Optional[Exception] = None
        #: ranks admitted with no shared history (elastic fresh joins) —
        #: the supervisor drains this and rebuilds them via a resize
        self._fresh_joins: set[int] = set()
        # follower state
        self._sock: Optional[Any] = None
        self._addr: Optional[tuple[str, int]] = None
        #: highest sequence this follower has returned from next()
        self.last_seq = 0

    # -- construction ------------------------------------------------------

    @classmethod
    def listen(cls, port: int, num_followers: int, token: str = "",
               timeout: float = 60.0, **kw) -> "GangChannel":
        """Rank 0: accept every follower (they dial after the gang
        barrier, so all are alive or the job already failed), then keep
        the listener open for re-attaches.  A connection that fails the
        token handshake is dropped without consuming a follower slot."""
        ch = cls(0, token=token, **kw)
        ch._want = num_followers
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", port))
        srv.listen(max(num_followers, 1))
        srv.settimeout(0.2)
        ch._srv = srv
        threading.Thread(
            target=ch._accept_loop, name="gang-accept", daemon=True).start()
        threading.Thread(
            target=ch._hb_loop, name="gang-hb", daemon=True).start()
        deadline = time.monotonic() + timeout
        with ch._lock:
            while len(ch._followers) < num_followers:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                ch._joined.wait(remaining)
            got = len(ch._followers)
        if got < num_followers:
            ch.close()
            raise TimeoutError(
                f"only {got}/{num_followers} followers "
                "passed the gang handshake")
        return ch

    @classmethod
    def connect(cls, host: str, port: int, rank: int, token: str = "",
                timeout: float = 60.0, fresh: bool = False,
                **kw) -> "GangChannel":
        """``fresh=True`` marks an ELASTIC join (ISSUE 10): this member
        has no shared dispatch history, so it asks for no replay
        (last_seq = -1) and starts at the stream's current position —
        the grow-back resize rebuilds its pool state from scratch, so
        the missed frames are genuinely irrelevant, not a gap."""
        ch = cls(rank, token=token, **kw)
        if fresh:
            ch.last_seq = -1
        ch._addr = (host, port)
        ch._dial(timeout)
        threading.Thread(
            target=ch._ack_loop, name=f"gang-ack-{rank}", daemon=True).start()
        return ch

    # -- leader: accept / admit / evict / heartbeat ------------------------

    #: handshake frames are JSON (never pickle: they arrive from
    #: UNAUTHENTICATED peers — pre-auth pickle.loads would be arbitrary
    #: code execution) and length-capped before the body is even read
    _HELLO_MAX = 4096

    def _accept_loop(self) -> None:
        import hmac

        while not self._closing.is_set():
            srv = self._srv
            if srv is None:  # close() raced us and nulled the listener
                return
            try:
                raw, _addr = srv.accept()
            except (socket.timeout, TimeoutError):
                continue
            except OSError:
                return
            c = self._sock_wrap(raw)
            try:
                c.settimeout(5.0)
                (n,) = _LEN.unpack(self._read_exact(c, _LEN.size))
                if n > self._HELLO_MAX:
                    raise ChannelClosed("oversized handshake")
                hello = json.loads(self._read_exact(c, n).decode())
                if not isinstance(hello, dict) or hello.get("t") != self._HELLO:
                    raise ChannelClosed("bad handshake")
                if not hmac.compare_digest(
                        str(hello.get("token", "")), self._token):
                    raise ChannelClosed("bad gang token")
                rank = int(hello.get("rank", -1))
                last_seq = int(hello.get("last_seq", 0))
                # _want == 0 means UNCAPPED (the PR 1 contract: quota
                # is enforced by token + rank-slot replacement, not a
                # bound) — under elastic resize that is also the
                # designed behavior: a shrunk-away member that returns
                # SHOULD be admitted and trigger the grow-back
                if rank < 1 or (self._want and rank > self._want):
                    raise ChannelClosed(f"rank {rank} out of range")
                # bounded sends from here on: a wedged-but-alive follower
                # whose receive buffer fills must stall the leader for at
                # most dead_peer_timeout, not forever (see publish)
                c.settimeout(self._dead_peer_timeout)
                try:
                    c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except OSError:
                    pass
                self._admit(c, rank, last_seq)
            except (OSError, ChannelClosed, EOFError, struct.error,
                    ValueError):
                try:
                    c.close()
                except OSError:
                    pass

    def _admit(self, c, rank: int, last_seq: int) -> None:
        """Install (or re-install) a follower connection after a valid
        handshake, replaying exactly the frames it missed."""
        with self._lock:
            if last_seq < 0:
                # fresh elastic member (ISSUE 10): no shared history to
                # replay — it enters at the stream's current position
                # and the grow-back resize rebuilds its state
                self._fresh_joins.add(rank)
            elif last_seq < self._seq:
                oldest = self._log[0][0] if self._log else self._seq + 1
                if last_seq + 1 < oldest:
                    # the gap rolled off the replay log: this follower can
                    # no longer be resynced at the channel layer — tell it
                    # to die so the JaxJob gang restart takes over
                    try:
                        # send bounded by dead_peer_timeout by design
                        # analysis: ok lock-order — bounded send
                        c.sendall(self._frame(
                            (self._GONE, "replay log exhausted")))
                    except OSError:
                        pass
                    try:
                        c.close()
                    except OSError:
                        pass
                    return
                for s, fb in list(self._log):
                    if s > last_seq:
                        # analysis: ok lock-order — bounded by dead_peer_timeout
                        c.sendall(fb)  # OSError -> caller drops the conn
            old = self._followers.pop(rank, None)
            self._followers[rank] = c
            self._lost.pop(rank, None)
            self._last_ack[rank] = time.monotonic()
            self._joined.notify_all()
        if old is not None:
            # an extra token-valid connection REPLACES its rank's slot
            try:
                old.close()
            except OSError:
                pass
        threading.Thread(
            target=self._ack_reader, args=(rank, c),
            name=f"gang-ackr-{rank}", daemon=True).start()

    def _ack_reader(self, rank: int, c) -> None:
        """Per-follower reader: acks refresh liveness; EOF/error evicts.
        A recv timeout alone is NOT an eviction — the socket carries
        dead_peer_timeout so leader SENDS stay bounded, and ack staleness
        is judged by _hb_loop against _last_ack."""
        while not self._closing.is_set():
            try:
                frame = self._recv_frame(c)
            except (socket.timeout, TimeoutError):
                continue
            except (ChannelClosed, OSError, EOFError, struct.error,
                    pickle.UnpicklingError):
                self._evict(rank, c)
                return
            if (isinstance(frame, tuple) and len(frame) == 3
                    and frame[0] == self._ACK):
                with self._lock:
                    if self._followers.get(rank) is c:
                        self._last_ack[rank] = time.monotonic()

    def _evict(self, rank: int, c=None) -> None:
        with self._lock:
            self._evict_locked(rank, c)

    def _evict_locked(self, rank: int, c=None) -> None:
        cur = self._followers.get(rank)
        if cur is None or (c is not None and cur is not c):
            return
        del self._followers[rank]
        self._last_ack.pop(rank, None)
        self._lost[rank] = time.monotonic()
        try:
            cur.close()
        except OSError:
            pass

    def _hb_loop(self) -> None:
        """Leader liveness pump: heartbeat every interval (so an idle
        stream still proves rank 0 alive), evict silent followers, and go
        fatal when an evicted rank overstays the re-attach grace."""
        while not self._closing.wait(self._hb_interval):
            now = time.monotonic()
            with self._lock:
                frame = self._frame((self._HB, self._seq))
                for rank, c in list(self._followers.items()):
                    if now - self._last_ack.get(rank, now) > self._dead_peer_timeout:
                        self._evict_locked(rank)
                        continue
                    try:
                        # analysis: ok lock-order — bounded by dead_peer_timeout
                        c.sendall(frame)
                    except OSError:
                        self._evict_locked(rank)
                if self._dead is None:
                    for rank, t in self._lost.items():
                        if now - t > self._reattach_timeout:
                            self._dead = ChannelClosed(
                                f"follower rank {rank} gone for "
                                f"{self._reattach_timeout:.1f}s; "
                                "gang must restart")
                            break

    @property
    def missing_ranks(self) -> list[int]:
        """Evicted followers awaiting re-attach (leader side)."""
        with self._lock:
            return sorted(self._lost)

    def lost_since(self) -> dict[int, float]:
        """Evicted rank -> monotonic eviction time (leader side): the
        elastic supervisor's escalation input (ISSUE 10)."""
        with self._lock:
            return dict(self._lost)

    def follower_ranks(self) -> list[int]:
        """Currently connected follower ranks (leader side)."""
        with self._lock:
            return sorted(self._followers)

    def set_want(self, n: int) -> None:
        """Adjust the handshake ADMISSION CAP (the max rank a hello may
        carry): an elastic grow raises it so new ranks can join; 0
        removes the cap entirely (the PR 1 contract — admission is then
        guarded by the token alone, and a returning member can always
        rejoin and grow the gang back).  It is a bound, not a member
        count — a shrink must NOT lower it below surviving ranks, or
        they would be refused at their next reconnect (rank ids are
        stable)."""
        with self._lock:
            self._want = max(int(n), 0)

    def forget_rank(self, rank: int) -> None:
        """Drop an evicted rank from the re-attach ledger (the elastic
        shrink path, ISSUE 10): its absence becomes a PLANNED degree
        change instead of a ticking fatality — the hb loop stops
        counting it toward ``reattach_timeout``."""
        with self._lock:
            self._lost.pop(rank, None)

    def touch_lost(self, ranks) -> None:
        """Restart the re-attach fatality clock for evicted ranks: the
        elastic supervisor touches them when it COMMITS to a shrink, so
        a rebuild that outlives the remaining grace (weight reshard +
        new-degree warmup) cannot kill the gang mid-resize.  The
        supervisor bounds its touches (max attempts), so the
        JaxJob-restart backstop stays reachable when resizes keep
        failing."""
        with self._lock:
            now = time.monotonic()
            for r in ranks:
                if r in self._lost:
                    self._lost[r] = now

    def take_fresh_joins(self) -> list[int]:
        """Drain the fresh-join ledger (leader side): ranks admitted
        with no shared dispatch history since the last call.  A fresh
        member SKIPS ops until a resize rebuilds its pool, so the
        elastic supervisor must answer every entry here with a resize —
        even a same-degree one (resync-by-rebuild for a member that
        died and returned inside the resize deadline)."""
        with self._lock:
            out = sorted(self._fresh_joins)
            self._fresh_joins.clear()
            return out

    # -- follower: dial / reconnect / ack ----------------------------------

    def _dial(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        delay = 0.05
        while True:
            try:
                raw = socket.create_connection(self._addr, timeout=5.0)
                c = self._sock_wrap(raw)
                try:
                    c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except OSError:
                    pass
                hello = json.dumps({
                    "t": self._HELLO, "token": self._token,
                    "rank": self.rank, "last_seq": self.last_seq,
                }).encode()
                c.sendall(_LEN.pack(len(hello)) + hello)
                c.settimeout(self._dead_peer_timeout)
                with self._lock:
                    old, self._sock = self._sock, c
                if old is not None:
                    try:
                        old.close()
                    except OSError:
                        pass
                return
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 1.0)

    def _reconnect(self) -> None:
        with self._lock:
            old, self._sock = self._sock, None
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        try:
            self._dial(self._reconnect_timeout)
        except OSError as e:
            raise ChannelClosed(
                f"rank 0 unreachable after {self._reconnect_timeout:.1f}s "
                f"of reconnect attempts: {e}") from e

    def _ack_loop(self) -> None:
        while not self._closing.wait(self._hb_interval):
            with self._lock:
                c = self._sock
            if c is None:
                continue
            try:
                c.sendall(self._frame((self._ACK, self.rank, self.last_seq)))
            except OSError:
                pass  # next() notices the dead socket and reconnects

    # -- wire --------------------------------------------------------------

    def publish(self, msg: tuple) -> None:
        """Leader: sequence, log, and fan out one control frame.  A send
        failure evicts that follower (the frame is in the replay log for
        its re-attach); the call only raises once a follower has
        overstayed the re-attach grace — the point where the gang can no
        longer heal at this layer."""
        with self._lock:
            if self._dead is not None:
                raise self._dead
            self._seq += 1
            frame = self._frame((self._MSG, self._seq, msg))
            self._log.append((self._seq, frame))
            for rank, c in list(self._followers.items()):
                try:
                    # bounded sends: the conn carries dead_peer_timeout,
                    # a wedged follower stalls publish at most that long
                    # analysis: ok lock-order — bounded send, then evict
                    c.sendall(frame)
                except OSError:
                    self._evict_locked(rank)

    def next(self) -> tuple:
        """Follower: the next control message, transparently surviving
        socket drops (reconnect + leader-side replay) and filtering
        liveness frames."""
        while True:
            with self._lock:
                c = self._sock
            if c is None:
                self._reconnect()
                continue
            try:
                frame = self._recv_frame(c)
            except (socket.timeout, TimeoutError):
                # no data and no heartbeat for dead_peer_timeout: the
                # leader is silent — treat as a dead link and re-dial
                self._reconnect()
                continue
            except (ChannelClosed, OSError, EOFError, struct.error,
                    pickle.UnpicklingError):
                if self._closing.is_set():
                    raise ChannelClosed("channel closed")
                self._reconnect()
                continue
            tag = frame[0] if isinstance(frame, tuple) and frame else None
            if tag == self._HB:
                continue
            if tag == self._MSG:
                _, seq, payload = frame
                if seq <= self.last_seq:
                    continue  # replay overlap after a reconnect race
                self.last_seq = seq
                return payload
            if tag == self._GONE:
                raise ChannelClosed(f"rank 0 rejected re-attach: {frame[1]}")
            raise ChannelClosed(f"unknown control frame {tag!r}")

    @classmethod
    def _frame(cls, obj) -> bytes:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        return _LEN.pack(len(payload)) + payload

    @classmethod
    def _recv_frame(cls, c):
        (n,) = _LEN.unpack(cls._read_exact(c, _LEN.size))
        return pickle.loads(cls._read_exact(c, n))

    @staticmethod
    def _read_exact(c, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = c.recv(n - len(buf))
            if not chunk:
                raise ChannelClosed("peer closed the control stream")
            buf += chunk
        return buf

    def close(self) -> None:
        self._closing.set()
        with self._lock:
            socks = list(self._followers.values())
            self._followers.clear()
            if self._sock is not None:
                socks.append(self._sock)
                self._sock = None
            srv, self._srv = self._srv, None
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# kv_migrate: live paged-KV migration between engines (ISSUE 8)
# ---------------------------------------------------------------------------
#
# The transfer stream reuses GangChannel's trust shape — a per-deployment
# shared token, a length-capped JSON handshake (never pre-auth pickle) —
# but NOT its pickle body: every kv_migrate frame is a length-framed
# JSON header plus RAW numpy bytes, so the analyzer's unsafe-pickle
# allowlist stays exactly one entry.  Protocol (client = the SOURCE
# engine's migration worker, server = the DESTINATION):
#
#   client -> kv_hello {token, mid}          server -> kv_ready
#   client -> kv_begin {meta, leaf specs}    (no allocation yet)
#   client -> kv_block {i} + leaf bytes      (buffered host-side)
#   client -> kv_logits + row bytes
#   client -> kv_commit                      server imports, -> kv_ack
#
# The destination allocates blocks ONLY at kv_commit (inside
# import_sequence), so a socket death mid-stream leaks nothing on either
# side — the source still holds the sequence (copy-then-cutover) and the
# buffered frames are garbage-collected host memory.

#: per-frame hard caps: a kv_migrate peer is authenticated, but a
#: corrupted length prefix must cost a closed connection, not an OOM
KV_HELLO_MAX = 4096
KV_HEADER_MAX = 1 << 20
KV_FRAME_MAX = 1 << 30

_HDR = struct.Struct("!I")

#: migration-id registry: the front server keeps the REQUEST HANDLE when
#: a sequence moves between co-hosted replicas — the source registers
#: the handle under a fresh mid, the destination's KvMigrationServer
#: resolves it, and the SSE stream keeps reading the same object (slot
#: re-targeting, no client reconnect).  Cross-process imports simply
#: never resolve and build a fresh Request from the snapshot.
_MIGRATION_HANDLES: dict[str, Any] = {}
_MIGRATION_LOCK = threading.Lock()


def register_migration_handle(req) -> str:
    import uuid

    mid = uuid.uuid4().hex
    with _MIGRATION_LOCK:
        _MIGRATION_HANDLES[mid] = req
    return mid


def resolve_migration_handle(mid: str):
    with _MIGRATION_LOCK:
        return _MIGRATION_HANDLES.pop(mid, None)


def unregister_migration_handle(mid: str) -> bool:
    """Withdraw a handle after a failed transfer.  True = the handle was
    still pending, so the destination never reached kv_commit and the
    source may resume immediately.  False = the destination consumed it
    (commit arrived; only the ACK was lost) — the classic two-generals
    tail of copy-then-cutover.  The orchestrator then polls destination
    ownership instead of resuming blind: resuming while the destination
    installs the same request handle would DOUBLE-decode it (duplicate
    client tokens), the one corruption the cutover discipline exists to
    prevent."""
    with _MIGRATION_LOCK:
        return _MIGRATION_HANDLES.pop(mid, None) is not None


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bf16/f8 names register through ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _kv_send(c, header: dict, payload: bytes = b"") -> None:
    hb = json.dumps(header).encode()
    c.sendall(_LEN.pack(_HDR.size + len(hb) + len(payload))
              + _HDR.pack(len(hb)) + hb + payload)


def _kv_recv(c, max_len: int = KV_FRAME_MAX) -> tuple[dict, bytes]:
    (n,) = _LEN.unpack(GangChannel._read_exact(c, _LEN.size))
    if n < _HDR.size or n > max_len:
        raise ChannelClosed(f"kv_migrate frame length {n} out of range")
    (hn,) = _HDR.unpack(GangChannel._read_exact(c, _HDR.size))
    if hn > min(n - _HDR.size, KV_HEADER_MAX):
        raise ChannelClosed(f"kv_migrate header length {hn} out of range")
    header = json.loads(GangChannel._read_exact(c, hn).decode())
    payload = GangChannel._read_exact(c, n - _HDR.size - hn)
    if not isinstance(header, dict):
        raise ChannelClosed("kv_migrate header is not an object")
    return header, payload


def _leaf_specs(snapshot: dict) -> list[dict]:
    if not snapshot.get("blocks"):
        return []
    return [{"dtype": str(np.asarray(x).dtype),
             "shape": list(np.shape(x))}
            for x in snapshot["blocks"][0]]


def _pack_leaves(leaves) -> bytes:
    return b"".join(np.ascontiguousarray(np.asarray(x)).tobytes()
                    for x in leaves)


def _unpack_leaves(payload: bytes, specs: list[dict]) -> list[np.ndarray]:
    out, off = [], 0
    for s in specs:
        dt = _np_dtype(s["dtype"])
        n = int(np.prod(s["shape"], dtype=np.int64)) * dt.itemsize
        out.append(np.frombuffer(
            payload[off:off + n], dtype=dt).reshape(s["shape"]).copy())
        off += n
    if off != len(payload):
        raise ChannelClosed(
            f"kv_block payload {len(payload)}B != leaf specs {off}B")
    return out


def migrate_sequence(snapshot: dict, host: str, port: int, *,
                     token: str = "", mid: Optional[str] = None,
                     timeout: float = 30.0,
                     sock_wrap=None) -> Optional[bool]:
    """Source side of a kv_migrate transfer: stream one exported
    snapshot (``ContinuousEngine.export_sequence``) to a destination
    :class:`KvMigrationServer`.  Tri-state result, because the
    cutover decision needs to distinguish how a transfer ended:

    - ``True``  — the destination acked the commit: CUTOVER (release).
    - ``False`` — DEFINITIVELY not installed: the failure happened
      before ``kv_commit`` went out, or the destination answered an
      explicit rejection ack — the source may resume immediately.
    - ``None``  — INDETERMINATE: the socket died after ``kv_commit``
      was sent (the two-generals tail) — the destination may or may
      not install; the orchestrator must consult the migration-handle
      registry / destination ownership before resuming, or it risks
      double-decoding the request.

    Runs on a migration worker thread, never an engine scheduler (the
    analyzer's blocking-socket rule)."""
    meta = {k: v for k, v in snapshot.items()
            if k not in ("blocks", "logits", "blocks_dev", "logits_dev")}
    blocks = snapshot.get("blocks", [])
    logits = snapshot.get("logits")
    try:
        raw = socket.create_connection((host, port), timeout=timeout)
    except OSError:
        return False
    c = (sock_wrap or (lambda s: s))(raw)
    committed = False
    try:
        try:
            c.settimeout(timeout)
        except OSError:
            pass
        # the trace context rides the handshake header too (ISSUE 13):
        # a destination can correlate even a transfer that dies before
        # kv_begin with the source's request trace
        _kv_send(c, {"t": "kv_hello", "token": token, "mid": mid,
                     "trace": snapshot.get("trace")})
        ready, _ = _kv_recv(c, KV_HELLO_MAX)
        if ready.get("t") != "kv_ready":
            return False
        _kv_send(c, {"t": "kv_begin", "meta": meta,
                     "nblocks": len(blocks),
                     "leaves": _leaf_specs(snapshot),
                     "logits": (None if logits is None else
                                {"dtype": str(logits.dtype),
                                 "shape": list(logits.shape)})})
        for i, blk in enumerate(blocks):
            _kv_send(c, {"t": "kv_block", "i": i}, _pack_leaves(blk))
        if logits is not None:
            _kv_send(c, {"t": "kv_logits"}, _pack_leaves([logits]))
        _kv_send(c, {"t": "kv_commit"})
        committed = True
        ack, _ = _kv_recv(c, KV_HELLO_MAX)
        if ack.get("t") == "kv_ack":
            return bool(ack.get("ok"))  # explicit reject = definitive
        return None
    except (OSError, ChannelClosed, ValueError, struct.error):
        return None if committed else False
    finally:
        try:
            c.close()
        except OSError:
            pass


def fetch_kv_prefix(host: str, port: int, tokens, *, token: str = "",
                    timeout: float = 30.0,
                    sock_wrap=None) -> tuple[list, list]:
    """Cluster prefix fetch (ISSUE 12): ask a peer replica's
    :class:`KvMigrationServer` for its longest block-registered prefix
    of ``tokens`` — live slots or the free-list-as-cache registry.
    Returns ``(covered_tokens, host block leaf-lists)``; ``([], [])``
    on any miss or failure (the caller just prefills — a registry
    fetch is an optimization, never a correctness dependency).

    The cold side installs the result with
    ``engine.install_prefix(covered, blocks)`` so the next same-prefix
    admission shares it: prefill-once-per-cluster, the vLLM free-list
    economy lifted to fleet scope.  Same trust shape as kv_migrate
    (token hmac, length-framed JSON + raw numpy, never pickle); runs on
    router/worker threads, never an engine scheduler."""
    try:
        raw = socket.create_connection((host, port), timeout=timeout)
    except OSError:
        return [], []
    c = (sock_wrap or (lambda s: s))(raw)
    try:
        try:
            c.settimeout(timeout)
        except OSError:
            pass
        _kv_send(c, {"t": "kv_hello", "token": token, "mid": None})
        ready, _ = _kv_recv(c, KV_HELLO_MAX)
        if ready.get("t") != "kv_ready":
            return [], []
        _kv_send(c, {"t": "kv_fetch",
                     "tokens": [int(t) for t in tokens]})
        head, _ = _kv_recv(c)
        if head.get("t") != "kv_prefix":
            return [], []
        specs = list(head.get("leaves") or [])
        nblocks = int(head.get("nblocks", 0))
        covered = int(head.get("covered", 0))
        blocks = []
        for _i in range(nblocks):
            hdr, payload = _kv_recv(c)
            if hdr.get("t") != "kv_block":
                return [], []
            blocks.append(_unpack_leaves(payload, specs))
        return [int(t) for t in tokens][:covered], blocks
    except (OSError, ChannelClosed, ValueError, struct.error):
        return [], []
    finally:
        try:
            c.close()
        except OSError:
            pass


class KvMigrationServer:
    """Destination side of the kv_migrate message family: authenticated
    acceptor that assembles streamed snapshots and installs them through
    ``engine.import_sequence`` at commit time.

    One thread per transfer connection; the engine's scheduler is only
    touched through its migration mailbox (import runs between decode
    dispatches).  ``resolve_request`` maps a migration id to a live
    Request handle (co-hosted replica handoff — the front server keeps
    streaming the same object); default = the module registry."""

    def __init__(self, engine, port: Optional[int] = None,
                 token: str = "", sock_wrap=None, resolve_request=None,
                 host: str = "127.0.0.1"):
        from ..utils.net import allocate_port

        self.engine = engine
        self.port = port or allocate_port()
        self._token = token
        self._sock_wrap = sock_wrap or (lambda s: s)
        self._resolve = resolve_request or resolve_migration_handle
        self._closing = threading.Event()
        self.imports_total = 0
        self.rejects_total = 0
        self.prefix_serves_total = 0
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # loopback by DEFAULT: a cross-host deployment opts into
        # host="0.0.0.0" explicitly AND must set a non-empty token —
        # an empty-token listener on all interfaces would let any
        # network peer allocate real KV blocks (the gang-token rule,
        # ADVICE r5)
        if host != "127.0.0.1" and not token:
            raise ValueError(
                "a non-loopback KvMigrationServer requires a token")
        srv.bind((host, self.port))
        srv.listen(8)
        srv.settimeout(0.2)
        self._srv = srv
        threading.Thread(target=self._accept_loop, name="kv-migrate-srv",
                         daemon=True).start()

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            srv = self._srv
            if srv is None:
                return
            try:
                raw, _addr = srv.accept()
            except (socket.timeout, TimeoutError):
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve_one, args=(self._sock_wrap(raw),),
                name="kv-migrate-conn", daemon=True).start()

    def _serve_one(self, c) -> None:
        import hmac

        try:
            c.settimeout(30.0)
            hello, _ = _kv_recv(c, KV_HELLO_MAX)
            if hello.get("t") != "kv_hello" or not hmac.compare_digest(
                    str(hello.get("token", "")), self._token):
                raise ChannelClosed("bad kv_migrate handshake")
            mid = hello.get("mid")
            if hello.get("trace"):
                # correlation for operators tailing both sides of a
                # migration: the hello's trace id matches the source
                # request's /traces row
                log.debug("kv_migrate transfer for trace %s",
                          (hello["trace"] or {}).get("id"))
            _kv_send(c, {"t": "kv_ready"})
            meta: Optional[dict] = None
            specs: list[dict] = []
            nblocks = 0
            logits_spec = None
            blocks: list[list[np.ndarray]] = []
            logits = None
            while True:
                header, payload = _kv_recv(c)
                t = header.get("t")
                if t == "kv_begin":
                    meta = dict(header.get("meta") or {})
                    specs = list(header.get("leaves") or [])
                    nblocks = int(header.get("nblocks", 0))
                    logits_spec = header.get("logits")
                elif t == "kv_block":
                    if meta is None or len(blocks) >= nblocks:
                        raise ChannelClosed("kv_block outside transfer")
                    blocks.append(_unpack_leaves(payload, specs))
                elif t == "kv_logits":
                    if meta is None or logits_spec is None:
                        raise ChannelClosed("unexpected kv_logits")
                    logits = _unpack_leaves(payload, [logits_spec])[0]
                elif t == "kv_fetch":
                    # cluster prefix fetch (ISSUE 12): serve the
                    # longest block-registered prefix of the peer's
                    # tokens — the engine dispatches gathers on its
                    # scheduler, the fetch materializes HERE on this
                    # connection thread, then streams kv_block frames
                    toks = [int(x) for x in (header.get("tokens") or [])]
                    try:
                        covered, pblocks = \
                            self.engine.export_prefix_blocks(toks)
                    except (RuntimeError, TimeoutError):
                        # stopping/wedged engine: a registry fetch is
                        # an optimization — answer "nothing" instead of
                        # killing the connection thread
                        covered, pblocks = [], []
                    _kv_send(c, {
                        "t": "kv_prefix", "covered": len(covered),
                        "nblocks": len(pblocks),
                        "leaves": ([
                            {"dtype": str(np.asarray(x).dtype),
                             "shape": list(np.shape(x))}
                            for x in pblocks[0]] if pblocks else [])})
                    for i, blk in enumerate(pblocks):
                        _kv_send(c, {"t": "kv_block", "i": i},
                                 _pack_leaves(blk))
                    self.prefix_serves_total += 1
                elif t == "kv_commit":
                    break
                else:
                    raise ChannelClosed(f"unknown kv_migrate frame {t!r}")
            if meta is None or len(blocks) != nblocks:
                raise ChannelClosed(
                    f"kv_commit with {len(blocks)}/{nblocks} blocks")
            snapshot = dict(meta)
            snapshot["blocks"] = blocks
            if logits is not None:
                snapshot["logits"] = logits
            req = self._resolve(mid) if mid else None
            try:
                self.engine.import_sequence(snapshot, req=req)
                self.imports_total += 1
                _kv_send(c, {"t": "kv_ack", "ok": True})
            except Exception as e:  # noqa: BLE001 — rejection (pool
                # exhausted, mismatched config) is a protocol answer,
                # not a server death: the source resumes in place
                self.rejects_total += 1
                _kv_send(c, {"t": "kv_ack", "ok": False,
                             "error": f"{type(e).__name__}: {e}"[:500]})
        except (OSError, ChannelClosed, ValueError, struct.error,
                EOFError) as e:
            log.debug("kv_migrate transfer aborted: %s", e)
        finally:
            try:
                c.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closing.set()
        srv, self._srv = self._srv, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass


class GangEngine(contlib.ContinuousEngine):
    """Rank-0 engine: every compiled-program call publishes its host args
    before dispatching, so follower hosts replay the identical SPMD
    dispatch stream against their shards (see :func:`follow`).

    The wrap happens at the program-getter layer — the scheduler, the
    admission batching, prefix-cache routing, chunked-prefill budgeting
    and warmup all run UNMODIFIED; only the dispatch sites gain a
    publish (prefill/merge/decode/prefix, the segment ops, and the
    chunked-admission ops ``chunk_prefill``/``fused`` when
    ``prefill_budget`` > 0 — the follower replays the identical chunked
    schedule, budget boundaries included).  Host args are normalized to
    numpy on both sides of the wire (a process-local device array cannot
    feed a global-mesh jit).
    """

    def __init__(self, cfg, params, *, channel: GangChannel, **kw) -> None:
        if not kw.get("mesh_axes"):
            raise ValueError("a serving gang needs mesh_axes")
        self._channel = channel
        #: an elastic resize (serving/resize.py) replaces this engine
        #: but keeps the channel + follower processes alive for the
        #: successor — the resizer flips this before stop()
        self.keep_channel_open = False
        super().__init__(cfg, params, **kw)

    def _fatal(self, e: Exception) -> Exception:
        """A failed publish OR a rank-0-only dispatch failure after a
        successful publish both mean the gang's replicated pool state can
        no longer be trusted (followers may have applied an update rank 0
        skipped).  Mark the engine dead — the scheduler's per-request
        exception handling must not paper over it — so serve_main's
        watchdog exits non-zero and the JaxJob controller restarts the
        whole gang.

        Deliberately lock-free: warmup() holds the engine gate while
        calling the wrapped programs, so taking it here would deadlock
        rank 0 on a mid-warmup follower death.  The assignment is a
        single store read by the watchdog/submit; losing a first-error
        race to the scheduler thread is benign."""
        if self._error is None:
            self._error = e
        return e

    def _build_programs(self) -> None:
        super()._build_programs()
        ch = self._channel
        prefill_inner = self._prefill_for
        decode_inner = self._decode_for
        prefix_inner = self._prefix_admit_for
        merge_inner = self._merge

        def prefill_for(bucket: int):
            prog = prefill_inner(bucket)

            def call(params, toks, lengths):
                try:
                    toks = np.asarray(toks)
                    lengths = np.asarray(lengths)
                    ch.publish(("prefill", int(bucket), toks, lengths))
                    return prog(params, toks, lengths)
                except Exception as e:  # noqa: BLE001 — see _fatal
                    raise self._fatal(e)

            return call

        def decode_for(needed: int):
            prog = decode_inner(needed)

            def call(params, cache, logits, positions, active, temps,
                     top_ps, top_ks, key):
                try:
                    positions = np.asarray(positions)
                    active = np.asarray(active)
                    temps = np.asarray(temps)
                    top_ps = np.asarray(top_ps)
                    top_ks = np.asarray(top_ks)
                    key = np.asarray(key)
                    ch.publish(
                        ("decode", int(needed), positions, active, temps,
                         top_ps, top_ks, key))
                    return prog(params, cache, logits, positions, active,
                                temps, top_ps, top_ks, key)
                except Exception as e:  # noqa: BLE001
                    raise self._fatal(e)

            return call

        def prefix_admit_for(total: int, suffix_bucket: int):
            prog = prefix_inner(total, suffix_bucket)

            def call(params, cache, logits, src, dst, lp, suffix, slen):
                try:
                    suffix = np.asarray(suffix)
                    ch.publish(("prefix", int(total), int(suffix_bucket),
                                int(src), int(dst), int(lp), suffix,
                                int(slen)))
                    return prog(params, cache, logits, np.int32(src),
                                np.int32(dst), np.int32(lp), suffix,
                                np.int32(slen))
                except Exception as e:  # noqa: BLE001
                    raise self._fatal(e)

            return call

        def merge(pool_cache, pool_logits, row_cache, row_logits, slots):
            try:
                slots = np.asarray(slots)
                ch.publish(("merge", slots))
                return merge_inner(
                    pool_cache, pool_logits, row_cache, row_logits, slots)
            except Exception as e:  # noqa: BLE001
                raise self._fatal(e)

        self._prefill_for = prefill_for
        self._decode_for = decode_for
        self._prefix_admit_for = prefix_admit_for
        self._merge = merge

        if self.prefill_budget > 0:
            # chunked admission (stall-free continuous batching): the
            # fused prefill+decode step and the standalone chunk join the
            # control stream — followers replay the identical chunked
            # schedule, budget boundaries and all
            chunk_inner = self._chunk_prefill_for
            fused_inner = self._fused_for

            def chunk_prefill_for(needed: int):
                prog = chunk_inner(needed)

                def call(params, cache, logits, slot, toks, start, length,
                         write_slot):
                    try:
                        toks = np.asarray(toks)
                        ch.publish(("chunk_prefill", int(needed), int(slot),
                                    toks, int(start), int(length),
                                    int(write_slot)))
                        return prog(params, cache, logits, np.int32(slot),
                                    toks, np.int32(start), np.int32(length),
                                    np.int32(write_slot))
                    except Exception as e:  # noqa: BLE001 — see _fatal
                        raise self._fatal(e)

                return call

            def fused_for(needed: int):
                prog = fused_inner(needed)

                def call(params, cache, logits, slot, toks, start, length,
                         write_slot, positions, active, temps, top_ps,
                         top_ks, key):
                    try:
                        toks = np.asarray(toks)
                        positions = np.asarray(positions)
                        active = np.asarray(active)
                        temps = np.asarray(temps)
                        top_ps = np.asarray(top_ps)
                        top_ks = np.asarray(top_ks)
                        key = np.asarray(key)
                        ch.publish(("fused", int(needed), int(slot), toks,
                                    int(start), int(length),
                                    int(write_slot), positions, active,
                                    temps, top_ps, top_ks, key))
                        return prog(params, cache, logits, np.int32(slot),
                                    toks, np.int32(start), np.int32(length),
                                    np.int32(write_slot), positions, active,
                                    temps, top_ps, top_ks, key)
                    except Exception as e:  # noqa: BLE001
                        raise self._fatal(e)

                return call

            self._chunk_prefill_for = chunk_prefill_for
            self._fused_for = fused_for

        if self.spec_k > 0:
            # speculative decoding (ISSUE 4): the verify dispatch joins
            # the control stream carrying the proposals (drafts) and the
            # residual bans — acceptance is computed ON DEVICE by the
            # same deterministic program, so replaying the identical
            # inputs leaves follower pool state bit-identical without
            # accept lengths ever crossing the wire
            verify_inner = self._verify_for

            def verify_for(needed: int):
                prog = verify_inner(needed)

                def call(params, cache, logits, drafts, banned, positions,
                         active, temps, top_ps, top_ks, key):
                    try:
                        drafts = np.asarray(drafts)
                        banned = np.asarray(banned)
                        positions = np.asarray(positions)
                        active = np.asarray(active)
                        temps = np.asarray(temps)
                        top_ps = np.asarray(top_ps)
                        top_ks = np.asarray(top_ks)
                        key = np.asarray(key)
                        ch.publish(("verify", int(needed), drafts, banned,
                                    positions, active, temps, top_ps,
                                    top_ks, key))
                        return prog(params, cache, logits, drafts, banned,
                                    positions, active, temps, top_ps,
                                    top_ks, key)
                    except Exception as e:  # noqa: BLE001 — see _fatal
                        raise self._fatal(e)

                return call

            self._verify_for = verify_for

            if self.prefill_budget > 0:
                fverify_inner = self._fused_verify_for

                def fused_verify_for(needed: int):
                    prog = fverify_inner(needed)

                    def call(params, cache, logits, slot, toks, start,
                             length, write_slot, drafts, banned, positions,
                             active, temps, top_ps, top_ks, key):
                        try:
                            toks = np.asarray(toks)
                            drafts = np.asarray(drafts)
                            banned = np.asarray(banned)
                            positions = np.asarray(positions)
                            active = np.asarray(active)
                            temps = np.asarray(temps)
                            top_ps = np.asarray(top_ps)
                            top_ks = np.asarray(top_ks)
                            key = np.asarray(key)
                            ch.publish(("fused_verify", int(needed),
                                        int(slot), toks, int(start),
                                        int(length), int(write_slot),
                                        drafts, banned, positions, active,
                                        temps, top_ps, top_ks, key))
                            return prog(params, cache, logits,
                                        np.int32(slot), toks,
                                        np.int32(start), np.int32(length),
                                        np.int32(write_slot), drafts,
                                        banned, positions, active, temps,
                                        top_ps, top_ks, key)
                        except Exception as e:  # noqa: BLE001
                            raise self._fatal(e)

                    return call

                self._fused_verify_for = fused_verify_for

        if self.paged:
            # paged-KV ops (ISSUE 6): every block-table-carrying
            # dispatch joins the control stream with its table — the
            # followers never run the allocator; they replay rank 0's
            # host decisions (tables, COW src/dst) verbatim, so the
            # block pools stay bit-identical without allocator state
            # ever crossing the wire
            pdecode_inner = self._paged_decode_for
            pchunk_inner = self._paged_chunk_for
            pcopy_inner = self._block_copy

            def paged_decode_for(needed: int):
                prog = pdecode_inner(needed)

                def call(params, cache, logits, bt, positions, active,
                         temps, top_ps, top_ks, key):
                    try:
                        bt = np.asarray(bt)
                        positions = np.asarray(positions)
                        active = np.asarray(active)
                        temps = np.asarray(temps)
                        top_ps = np.asarray(top_ps)
                        top_ks = np.asarray(top_ks)
                        key = np.asarray(key)
                        ch.publish(("paged_decode", int(needed), bt,
                                    positions, active, temps, top_ps,
                                    top_ks, key))
                        return prog(params, cache, logits, bt, positions,
                                    active, temps, top_ps, top_ks, key)
                    except Exception as e:  # noqa: BLE001 — see _fatal
                        raise self._fatal(e)

                return call

            def paged_chunk_for(needed: int, budget: int):
                prog = pchunk_inner(needed, budget)

                def call(params, cache, logits, bt_row, toks, start,
                         length, write_slot):
                    try:
                        bt_row = np.asarray(bt_row)
                        toks = np.asarray(toks)
                        ch.publish(("paged_chunk", int(needed),
                                    int(budget), bt_row, toks,
                                    int(start), int(length),
                                    int(write_slot)))
                        return prog(params, cache, logits, bt_row, toks,
                                    np.int32(start), np.int32(length),
                                    np.int32(write_slot))
                    except Exception as e:  # noqa: BLE001
                        raise self._fatal(e)

                return call

            def block_copy(cache, src, dst):
                try:
                    ch.publish(("block_copy", int(src), int(dst)))
                    return pcopy_inner(cache, np.int32(src),
                                       np.int32(dst))
                except Exception as e:  # noqa: BLE001
                    raise self._fatal(e)

            self._paged_decode_for = paged_decode_for
            self._paged_chunk_for = paged_chunk_for
            self._block_copy = block_copy

            # kv_migrate (ISSUE 8): an IMPORT mutates the replicated
            # pool (scatter + logits row), so followers must replay it
            # with the incoming host bytes; the export gather is
            # read-only and stays leader-local
            pkvimp_inner = self._kv_import
            plogset_inner = self._logits_set

            def kv_import(cache, bt_row, leaves):
                try:
                    bt_row = np.asarray(bt_row)
                    leaves = tuple(np.asarray(x) for x in leaves)
                    ch.publish(("kv_import", bt_row, leaves))
                    return pkvimp_inner(cache, bt_row, leaves)
                except Exception as e:  # noqa: BLE001 — see _fatal
                    raise self._fatal(e)

            def logits_set(logits, row, slot):
                try:
                    row = np.asarray(row)
                    ch.publish(("logits_set", row, int(slot)))
                    return plogset_inner(logits, row, np.int32(slot))
                except Exception as e:  # noqa: BLE001
                    raise self._fatal(e)

            self._kv_import = kv_import
            self._logits_set = logits_set

            if self.prefill_budget > 0:
                pfused_inner = self._paged_fused_for

                def paged_fused_for(needed: int):
                    prog = pfused_inner(needed)

                    def call(params, cache, logits, bt, slot, toks,
                             start, length, write_slot, positions,
                             active, temps, top_ps, top_ks, key):
                        try:
                            bt = np.asarray(bt)
                            toks = np.asarray(toks)
                            positions = np.asarray(positions)
                            active = np.asarray(active)
                            temps = np.asarray(temps)
                            top_ps = np.asarray(top_ps)
                            top_ks = np.asarray(top_ks)
                            key = np.asarray(key)
                            ch.publish(("paged_fused", int(needed), bt,
                                        int(slot), toks, int(start),
                                        int(length), int(write_slot),
                                        positions, active, temps,
                                        top_ps, top_ks, key))
                            return prog(params, cache, logits, bt,
                                        np.int32(slot), toks,
                                        np.int32(start),
                                        np.int32(length),
                                        np.int32(write_slot), positions,
                                        active, temps, top_ps, top_ks,
                                        key)
                        except Exception as e:  # noqa: BLE001
                            raise self._fatal(e)

                    return call

                self._paged_fused_for = paged_fused_for

            if self.spec_k > 0:
                pverify_inner = self._paged_verify_for

                def paged_verify_for(needed: int):
                    prog = pverify_inner(needed)

                    def call(params, cache, logits, bt, drafts, banned,
                             positions, active, temps, top_ps, top_ks,
                             key):
                        try:
                            bt = np.asarray(bt)
                            drafts = np.asarray(drafts)
                            banned = np.asarray(banned)
                            positions = np.asarray(positions)
                            active = np.asarray(active)
                            temps = np.asarray(temps)
                            top_ps = np.asarray(top_ps)
                            top_ks = np.asarray(top_ks)
                            key = np.asarray(key)
                            ch.publish(("paged_verify", int(needed), bt,
                                        drafts, banned, positions,
                                        active, temps, top_ps, top_ks,
                                        key))
                            return prog(params, cache, logits, bt,
                                        drafts, banned, positions,
                                        active, temps, top_ps, top_ks,
                                        key)
                        except Exception as e:  # noqa: BLE001
                            raise self._fatal(e)

                    return call

                self._paged_verify_for = paged_verify_for

                if self.prefill_budget > 0:
                    pfv_inner = self._paged_fused_verify_for

                    def paged_fused_verify_for(needed: int):
                        prog = pfv_inner(needed)

                        def call(params, cache, logits, bt, slot, toks,
                                 start, length, write_slot, drafts,
                                 banned, positions, active, temps,
                                 top_ps, top_ks, key):
                            try:
                                bt = np.asarray(bt)
                                toks = np.asarray(toks)
                                drafts = np.asarray(drafts)
                                banned = np.asarray(banned)
                                positions = np.asarray(positions)
                                active = np.asarray(active)
                                temps = np.asarray(temps)
                                top_ps = np.asarray(top_ps)
                                top_ks = np.asarray(top_ks)
                                key = np.asarray(key)
                                ch.publish(("paged_fused_verify",
                                            int(needed), bt, int(slot),
                                            toks, int(start),
                                            int(length),
                                            int(write_slot), drafts,
                                            banned, positions, active,
                                            temps, top_ps, top_ks, key))
                                return prog(params, cache, logits, bt,
                                            np.int32(slot), toks,
                                            np.int32(start),
                                            np.int32(length),
                                            np.int32(write_slot),
                                            drafts, banned, positions,
                                            active, temps, top_ps,
                                            top_ks, key)
                            except Exception as e:  # noqa: BLE001
                                raise self._fatal(e)

                        return call

                    self._paged_fused_verify_for = paged_fused_verify_for

        if self.prefix_segments > 0:
            # shared-prefix segment ops join the control stream: segment
            # creation (prefill + merge into the segment pool), batched
            # suffix admission, and the prefix-aware decode — all
            # replayed by follow() against each host's segment shards
            seg_prefill_inner = self._seg_prefill_for
            seg_merge_inner = self._seg_merge
            suffix_inner = self._suffix_admit_for
            pdecode_inner = self._prefix_decode_for

            def seg_prefill_for(bucket: int):
                prog = seg_prefill_inner(bucket)

                def call(params, toks, lengths):
                    try:
                        toks = np.asarray(toks)
                        lengths = np.asarray(lengths)
                        ch.publish(("seg_prefill", int(bucket), toks,
                                    lengths))
                        return prog(params, toks, lengths)
                    except Exception as e:  # noqa: BLE001
                        raise self._fatal(e)

                return call

            def seg_merge(seg_cache, row_cache, rows):
                try:
                    rows = np.asarray(rows)
                    ch.publish(("seg_merge", rows))
                    return seg_merge_inner(seg_cache, row_cache, rows)
                except Exception as e:  # noqa: BLE001
                    raise self._fatal(e)

            def suffix_admit_for(attend: int, seg_att: int, bucket: int):
                prog = suffix_inner(attend, seg_att, bucket)

                def call(params, seg_cache, toks, seg_ids, plens, slens):
                    try:
                        toks = np.asarray(toks)
                        seg_ids = np.asarray(seg_ids)
                        plens = np.asarray(plens)
                        slens = np.asarray(slens)
                        ch.publish(("suffix_admit", int(attend),
                                    int(seg_att), int(bucket), toks,
                                    seg_ids, plens, slens))
                        return prog(params, seg_cache, toks, seg_ids,
                                    plens, slens)
                    except Exception as e:  # noqa: BLE001
                        raise self._fatal(e)

                return call

            def prefix_decode_for(needed: int, seg_att: int):
                prog = pdecode_inner(needed, seg_att)

                def call(params, cache, logits, seg_cache, positions,
                         plens, seg_ids, active, temps, top_ps, top_ks,
                         key):
                    try:
                        positions = np.asarray(positions)
                        plens = np.asarray(plens)
                        seg_ids = np.asarray(seg_ids)
                        active = np.asarray(active)
                        temps = np.asarray(temps)
                        top_ps = np.asarray(top_ps)
                        top_ks = np.asarray(top_ks)
                        key = np.asarray(key)
                        ch.publish(("prefix_decode", int(needed),
                                    int(seg_att), positions, plens,
                                    seg_ids, active, temps, top_ps,
                                    top_ks, key))
                        return prog(params, cache, logits, seg_cache,
                                    positions, plens, seg_ids, active,
                                    temps, top_ps, top_ks, key)
                    except Exception as e:  # noqa: BLE001
                        raise self._fatal(e)

                return call

            self._seg_prefill_for = seg_prefill_for
            self._seg_merge = seg_merge
            self._suffix_admit_for = suffix_admit_for
            self._prefix_decode_for = prefix_decode_for

    def stop(self) -> None:
        super().stop()
        if self.keep_channel_open:
            return
        try:
            self._channel.publish(("stop",))
        except ChannelClosed:
            pass
        self._channel.close()


def _follower_resize(engine, channel: GangChannel, conf: dict):
    """Rebuild this follower's engine at a new TP degree (ISSUE 10):
    fetch the repartitioned weights over the ``reshard`` wire family
    (length-framed JSON headers + raw numpy bytes, never pickle — the
    kv_migrate trust shape), build the new-degree engine only at commit,
    ack on the same connection, and hand the engine back to
    :func:`follow`.  A failed rebuild acks the failure and KEEPS the old
    engine — the leader aborts the resize (``resize_abort``) and the
    old-degree stream continues; copy-then-cutover means nothing was
    lost."""
    from .resize import ReshardClient, unflatten_params

    rs = dict(conf.get("reshard") or {})
    client = None
    try:
        client = ReshardClient(
            rs.get("host", "127.0.0.1"), int(rs["port"]),
            token=str(rs.get("token", "")), rank=channel.rank,
            sock_wrap=channel._sock_wrap)
        _plan, leaves = client.receive()
        params = unflatten_params(leaves)
        kw = dict(conf.get("kwargs") or {})
        # the wire kwargs are JSON-safe by design: the artifact cache
        # carries over from the engine being replaced instead
        kw["program_cache"] = getattr(engine, "program_cache", None)
        # allocation only at commit: the new-degree pool buffers exist
        # only once every leaf arrived intact
        new = contlib.ContinuousEngine(
            engine.cfg, params, mesh_axes=conf.get("mesh_axes"), **kw)
        client.ack(True)
        return new
    except Exception as e:  # noqa: BLE001 — a follower that cannot
        # rebuild must answer, not die: the leader aborts the resize on
        # the failed ack and the old-degree gang keeps serving
        log.warning("follower resize failed: %s", e)
        if client is not None:
            try:
                client.ack(False, f"{type(e).__name__}: {e}")
            except (OSError, ChannelClosed):
                pass
        return engine
    finally:
        if client is not None:
            client.close()


def follow(engine: contlib.ContinuousEngine, channel: GangChannel,
           fresh: bool = False, on_engine=None) -> None:
    """Follower executor: replay rank 0's dispatch stream.

    ``engine`` is a plain ContinuousEngine constructed from the same
    config — its scheduler never starts (that thread is lazy on submit,
    which followers never call); only its compiled programs and pool
    buffers are used.  Returns cleanly on the ``stop`` message; raises
    :class:`ChannelClosed` if rank 0 dies, which fails this pod and
    triggers the gang restart.
    """
    params = engine.params
    row: Optional[tuple] = None
    seg_row = None
    #: elastic resize (ISSUE 10): the previous-degree engine is kept
    #: until the next op proves the cutover happened — a published
    #: ``resize_abort`` rolls back to it.  A ``fresh`` joiner (grow-back
    #: member with no shared history) SKIPS every op until its first
    #: resize rebuilds real state — replaying mid-stream ops against an
    #: empty pool could trip sequencing asserts (merge before prefill).
    prev_engine = None
    prev_skipping = False
    skipping = fresh
    while True:
        msg = channel.next()
        op = msg[0]
        if op == "stop":
            return
        if op == "resize":
            new_engine = _follower_resize(engine, channel, msg[1])
            if new_engine is not engine:
                prev_engine, engine = engine, new_engine
                params = engine.params
                row = seg_row = None
                prev_skipping, skipping = skipping, False
                if on_engine is not None:
                    on_engine(engine)
            continue
        if op == "resize_abort":
            if prev_engine is not None:
                engine, prev_engine = prev_engine, None
                params = engine.params
                row = seg_row = None
                # a fresh joiner rolled back to its never-initialized
                # engine must resume SKIPPING — replaying mid-stream ops
                # against an empty pool is exactly what fresh guards
                skipping = prev_skipping
                if on_engine is not None:
                    on_engine(engine)
            continue
        if op == "resize_commit":
            # the leader cut over: the abort window is closed, so the
            # previous-degree engine (a full weight + pool device copy)
            # can be freed instead of living until the next resize
            prev_engine = None
            continue
        if skipping:
            continue
        if op == "prefill":
            _, bucket, toks, lengths = msg
            row = engine._prefill_for(bucket)(params, toks, lengths)
        elif op == "merge":
            (_, slots) = msg
            assert row is not None, "merge before prefill in gang stream"
            row_logits, row_cache = row
            engine._pool_cache, engine._pool_logits = engine._merge(
                engine._pool_cache, engine._pool_logits,
                row_cache, row_logits, slots)
            row = None
        elif op == "decode":
            _, needed, positions, active, temps, top_ps, top_ks, key = msg
            engine._pool_cache, engine._pool_logits, _toks = (
                engine._decode_for(needed)(
                    params, engine._pool_cache, engine._pool_logits,
                    positions, active, temps, top_ps, top_ks, key))
        elif op == "chunk_prefill":
            _, needed, slot, toks, start, length, write_slot = msg
            engine._pool_cache, engine._pool_logits = (
                engine._chunk_prefill_for(needed)(
                    params, engine._pool_cache, engine._pool_logits,
                    np.int32(slot), toks, np.int32(start),
                    np.int32(length), np.int32(write_slot)))
        elif op == "fused":
            (_, needed, slot, toks, start, length, write_slot, positions,
             active, temps, top_ps, top_ks, key) = msg
            engine._pool_cache, engine._pool_logits, _toks = (
                engine._fused_for(needed)(
                    params, engine._pool_cache, engine._pool_logits,
                    np.int32(slot), toks, np.int32(start),
                    np.int32(length), np.int32(write_slot), positions,
                    active, temps, top_ps, top_ks, key))
        elif op == "verify":
            (_, needed, drafts, banned, positions, active, temps, top_ps,
             top_ks, key) = msg
            engine._pool_cache, engine._pool_logits, _toks, _acc = (
                engine._verify_for(needed)(
                    params, engine._pool_cache, engine._pool_logits,
                    drafts, banned, positions, active, temps, top_ps,
                    top_ks, key))
        elif op == "fused_verify":
            (_, needed, slot, toks, start, length, write_slot, drafts,
             banned, positions, active, temps, top_ps, top_ks, key) = msg
            engine._pool_cache, engine._pool_logits, _toks, _acc = (
                engine._fused_verify_for(needed)(
                    params, engine._pool_cache, engine._pool_logits,
                    np.int32(slot), toks, np.int32(start),
                    np.int32(length), np.int32(write_slot), drafts,
                    banned, positions, active, temps, top_ps, top_ks,
                    key))
        elif op == "paged_decode":
            (_, needed, bt, positions, active, temps, top_ps, top_ks,
             key) = msg
            engine._pool_cache, engine._pool_logits, _toks = (
                engine._paged_decode_for(needed)(
                    params, engine._pool_cache, engine._pool_logits, bt,
                    positions, active, temps, top_ps, top_ks, key))
        elif op == "paged_chunk":
            (_, needed, budget, bt_row, toks, start, length,
             write_slot) = msg
            engine._pool_cache, engine._pool_logits = (
                engine._paged_chunk_for(needed, budget)(
                    params, engine._pool_cache, engine._pool_logits,
                    bt_row, toks, np.int32(start), np.int32(length),
                    np.int32(write_slot)))
        elif op == "paged_fused":
            (_, needed, bt, slot, toks, start, length, write_slot,
             positions, active, temps, top_ps, top_ks, key) = msg
            engine._pool_cache, engine._pool_logits, _toks = (
                engine._paged_fused_for(needed)(
                    params, engine._pool_cache, engine._pool_logits, bt,
                    np.int32(slot), toks, np.int32(start),
                    np.int32(length), np.int32(write_slot), positions,
                    active, temps, top_ps, top_ks, key))
        elif op == "paged_verify":
            (_, needed, bt, drafts, banned, positions, active, temps,
             top_ps, top_ks, key) = msg
            engine._pool_cache, engine._pool_logits, _toks, _acc = (
                engine._paged_verify_for(needed)(
                    params, engine._pool_cache, engine._pool_logits, bt,
                    drafts, banned, positions, active, temps, top_ps,
                    top_ks, key))
        elif op == "paged_fused_verify":
            (_, needed, bt, slot, toks, start, length, write_slot,
             drafts, banned, positions, active, temps, top_ps, top_ks,
             key) = msg
            engine._pool_cache, engine._pool_logits, _toks, _acc = (
                engine._paged_fused_verify_for(needed)(
                    params, engine._pool_cache, engine._pool_logits, bt,
                    np.int32(slot), toks, np.int32(start),
                    np.int32(length), np.int32(write_slot), drafts,
                    banned, positions, active, temps, top_ps, top_ks,
                    key))
        elif op == "block_copy":
            _, src, dst = msg
            engine._pool_cache = engine._block_copy(
                engine._pool_cache, np.int32(src), np.int32(dst))
        elif op == "kv_import":
            _, bt_row, leaves = msg
            engine._pool_cache = engine._kv_import(
                engine._pool_cache, bt_row, tuple(leaves))
        elif op == "logits_set":
            _, row, slot = msg
            engine._pool_logits = engine._logits_set(
                engine._pool_logits, row, np.int32(slot))
        elif op == "prefix":
            _, total, sb, src, dst, lp, suffix, slen = msg
            engine._pool_cache, engine._pool_logits = (
                engine._prefix_admit_for(total, sb)(
                    params, engine._pool_cache, engine._pool_logits,
                    np.int32(src), np.int32(dst), np.int32(lp),
                    suffix, np.int32(slen)))
        elif op == "seg_prefill":
            _, bucket, toks, lengths = msg
            seg_row = engine._seg_prefill_for(bucket)(
                params, toks, lengths)
        elif op == "seg_merge":
            (_, rows) = msg
            assert seg_row is not None, "seg_merge before seg_prefill"
            engine._seg_cache = engine._seg_merge(
                engine._seg_cache, seg_row[1], rows)
            seg_row = None
        elif op == "suffix_admit":
            _, attend, seg_att, bucket, toks, seg_ids, plens, slens = msg
            row = engine._suffix_admit_for(attend, seg_att, bucket)(
                params, engine._seg_cache, toks, seg_ids, plens, slens)
        elif op == "prefix_decode":
            (_, needed, seg_att, positions, plens, seg_ids, active,
             temps, top_ps, top_ks, key) = msg
            engine._pool_cache, engine._pool_logits, _toks = (
                engine._prefix_decode_for(needed, seg_att)(
                    params, engine._pool_cache, engine._pool_logits,
                    engine._seg_cache, positions, plens, seg_ids,
                    active, temps, top_ps, top_ks, key))
        else:
            raise RuntimeError(f"unknown gang op {op!r}")


# ---------------------------------------------------------------------------
# Gang entrypoint (what the ISvc controller's JaxJob runs in every pod)
# ---------------------------------------------------------------------------


def _resolve_gang_token(conf: dict) -> str:
    """The gang token arrives over a side channel — ``gang_token_file``,
    a 0600 file the ISvc controller writes (the Secret-mount analog) —
    NOT the JaxJob env: JaxJobs are cluster-readable through the API
    server, and an inline token would let any tenant who can GET the job
    join the control stream (ADVICE r5).  Inline ``gang_token`` is kept
    for hand-rolled/test configs."""
    path = conf.get("gang_token_file")
    if path:
        # a missing/unreadable file must fail the pod loudly — falling
        # back to an empty token would silently open the gang
        with open(path) as f:
            return f.read().strip()
    return str(conf.get("gang_token", ""))


def serve_main(ctx: bootstrap.PodContext) -> None:
    """Entrypoint for every member of a serving gang (via pod_main:
    ``jax.distributed`` is already initialized and the gang barrier
    passed when this runs).

    Config (``KFT_SERVE_CONFIG`` json): engine knobs per ``engine_kwargs``
    plus ``mesh_axes`` (the global serving mesh), ``storage_path`` or
    ``params_ref`` (every member loads the same weights), ``serve_port``
    (rank 0's HTTP frontend — stable across gang restarts) and
    ``gang_port`` (the control stream).
    """
    conf = json.loads(os.environ[ENV_SERVE_CONFIG])
    if conf.get("short_pool_len") or conf.get("tier_lens"):
        raise ValueError(
            "tiered pools (short_pool_len / tier_lens) are not "
            "gang-capable yet: the control stream drives ONE engine's "
            "dispatch order")
    cfg, params = contlib.resolve_model_source(
        conf, name=conf.get("model_name", "model"))
    cfg, params = contlib.apply_serving_quant(cfg, params, conf)
    kw = contlib.engine_kwargs(conf, default_eos=conf.get("eos_id"))
    kw["seq_buckets"] = conf.get("seq_buckets")
    # AOT artifact cache: EVERY rank consults the same root (the config
    # is identical gang-wide), so followers load the same artifacts the
    # leader does — the publish rename is atomic, concurrent ranks race
    # safely and the losers verify the winner's entry
    kw["program_cache"] = programslib.build_program_cache(conf)
    gang_port = int(conf["gang_port"])
    token = _resolve_gang_token(conf)
    elastic = conf.get("elastic") or {}
    chan_kw = dict(
        hb_interval=float(conf.get("gang_hb_interval", 0.5)),
        dead_peer_timeout=float(conf.get("gang_dead_peer_timeout", 3.0)),
        reattach_timeout=float(conf.get("gang_reattach_timeout", 10.0)),
        reconnect_timeout=float(conf.get("gang_reconnect_timeout", 10.0)),
    )
    if elastic:
        # the elastic supervisor must escalate a permanent loss into a
        # shrink BEFORE the channel's reattach clock goes fatal — widen
        # the grace so resize_deadline_s always fires first
        chan_kw["reattach_timeout"] = max(
            chan_kw["reattach_timeout"],
            float(elastic.get("resize_deadline_s", 2.0)) * 4)
    followers = ctx.num_processes - 1

    if ctx.process_id == 0:
        from .server import ModelServer

        channel = GangChannel.listen(
            gang_port, followers, token=token, **chan_kw)
        engine = GangEngine(cfg, params, channel=channel, **kw)
        groups = conf.get("warmup_groups")
        if groups != []:
            engine.warmup([tuple(g) for g in groups] if groups else None)
        if conf.get("runtime") == "text":
            # OpenAI completions on a multi-host predictor: rank 0 owns
            # the tokenizer + /openai/v1/completions surface; set eos_id
            # in the config for stop-token behavior (the engine is built
            # before the tokenizer here)
            from .text import TextGenerator

            model = TextGenerator(
                conf.get("model_name", "model"), conf, engine=engine)
        else:
            model = contlib.ContinuousLlamaGenerator(
                conf.get("model_name", "model"), conf, engine=engine)
        server = ModelServer(port=int(conf["serve_port"]))
        server.register(model)
        if conf.get("logger_url"):
            # payload logging on the gang frontend (rank 0 sees every
            # request), same CloudEvents contract as in-process replicas
            server.set_logger(conf["logger_url"],
                              conf.get("logger_mode", "all"),
                              service=conf.get("model_name", "model"))
        # the frontend port is stable across gang restarts; the previous
        # incarnation's listener may need its SIGTERM grace to vacate it
        deadline = time.monotonic() + 15.0
        while True:
            try:
                server.start()
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        supervisor = None
        resizer = None
        if elastic:
            # elastic gang (ISSUE 10): a member evicted past
            # resize_deadline_s shrinks the gang to the surviving
            # degree instead of the channel going fatal; a returned or
            # added member grows it back.  The resizer re-points the
            # runtime's engine on every cutover.
            from .resize import ElasticGangSupervisor, GangResizer, degree_of

            degree = degree_of(conf.get("mesh_axes"))
            resizer = GangResizer(
                engine, reshard_token=token,
                # runtimes with a traffic plane re-attach preemptors on
                # swap (TextGenerator.swap_engine); plain generators
                # just re-point
                set_engine=lambda e: (
                    model.swap_engine(e)
                    if hasattr(model, "swap_engine")
                    else setattr(model, "engine", e)))
            supervisor = ElasticGangSupervisor(
                resizer, channel,
                degree_per_member=max(degree // ctx.num_processes, 1),
                max_degree=degree,
                min_degree=int(elastic.get("min_degree", 1)),
                resize_deadline_s=float(
                    elastic.get("resize_deadline_s", 2.0)))
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        try:
            while not stop.is_set():
                # a dead follower surfaces as a ChannelClosed publish
                # failure inside the scheduler -> engine error; an IDLE
                # gang publishes nothing, so also watch the channel's own
                # fatal flag (a follower past its re-attach grace).  Exit
                # non-zero so the JaxJob controller gang-restarts.  Under
                # elastic resize the LIVE engine is whatever the resizer
                # last installed.
                live = resizer.engine if resizer is not None else engine
                if live._error is not None or channel._dead is not None:
                    raise SystemExit(1)
                stop.wait(0.2)
        finally:
            if supervisor is not None:
                supervisor.stop()
            server.stop()
            (resizer.engine if resizer is not None else engine).stop()
    else:
        host, _, _ = bootstrap.resolve_coordinator(
            ctx.coordinator_address or "127.0.0.1:0").rpartition(":")
        fresh = False
        while True:
            channel = GangChannel.connect(
                host, gang_port, rank=ctx.process_id, token=token,
                fresh=fresh, **chan_kw)
            engine = contlib.ContinuousEngine(cfg, params, **kw)
            try:
                follow(engine, channel, fresh=fresh)
                break
            except ChannelClosed as e:
                # elastic grow-back (ISSUE 10): a RESTARTED member's
                # replay gap has usually rolled off the log — instead of
                # crash-looping on GONE, rejoin as a FRESH member (no
                # replay, ops skipped until the supervisor's grow resize
                # rebuilds its state)
                if elastic and not fresh and "re-attach" in str(e):
                    fresh = True
                    continue
                raise
            finally:
                channel.close()
