"""JAX serving runtimes: the ``tpu`` ServingRuntime family.

The north star's serving requirement [local: BASELINE.json]: "give KServe a
``tpu`` ServingRuntime that loads JAX/XLA-compiled predictors instead of
the Triton/GPU path".  These are those predictors:

- ``JaxFunctionModel``: any jittable fn + params, AOT-compiled at load for
  the fixed batch shapes the micro-batcher produces (pad-to-bucket, so XLA
  never sees a new shape at serve time).
- ``LlamaGenerator``: Llama checkpoint -> greedy/temperature decode with a
  KV cache; prefill and per-token decode are separate compiled programs,
  the standard TPU serving split.
- ``EchoModel``: trivial runtime for smoke tests and protocol conformance.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama as llamalib
from .model import Model
from .storage import download, fetch_mem

#: batch buckets compiled ahead of time; requests pad up to the next bucket
DEFAULT_BUCKETS = (1, 2, 4, 8)


class EchoModel(Model):
    def predict_batch(self, instances):
        return instances


class JaxFunctionModel(Model):
    """Serve ``fn(params, batch_array) -> batch_array`` as an XLA program.

    config:
      fn_ref:      "mem://key" holding (fn, params)  [or set via attributes]
      buckets:     batch buckets to AOT-compile (default 1/2/4/8)
    """

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None):
        super().__init__(name, config)
        self.fn = self.config.get("fn")
        self.params = self.config.get("params")
        self.buckets = tuple(self.config.get("buckets", DEFAULT_BUCKETS))
        self._compiled: dict[int, Any] = {}

    def load(self) -> None:
        ref = self.config.get("fn_ref")
        if ref:
            self.fn, self.params = fetch_mem(ref[len("mem://"):])
        if self.fn is None:
            raise RuntimeError(f"model {self.name}: no fn/fn_ref configured")
        self._jitted = jax.jit(self.fn)
        self.ready = True

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def predict_batch(self, instances):
        x = np.asarray(instances, dtype=np.float32)
        out: list = []
        # chunk by the largest bucket, pad the tail to a bucket size
        cap = self.buckets[-1]
        for i in range(0, len(x), cap):
            chunk = x[i : i + cap]
            b = self._bucket(len(chunk))
            padded = np.zeros((b, *chunk.shape[1:]), dtype=chunk.dtype)
            padded[: len(chunk)] = chunk
            y = np.asarray(jax.device_get(self._jitted(self.params, jnp.asarray(padded))))
            out.extend(y[: len(chunk)].tolist())
        return out


class LlamaGenerator(Model):
    """Greedy/temperature text-token generation over a Llama checkpoint.

    config:
      params_ref:   "mem://key" holding (LlamaConfig, params)
      max_new_tokens (default 16), temperature (default 0 = greedy)

    Instances are token-id lists; predictions are continuation token lists.
    Prefill is one chunked decode=True forward (specialized per distinct
    prompt length — a plain forward, so the per-length compile is small);
    the sampling scan compiles ONCE per batch size and is reused across
    all prompt lengths.  Padding prompts into shared-length buckets is not
    possible with the single shared cache cursor (pad rows would enter the
    cache); per-row cursors (paged caches) are the known next step if
    ragged production traffic makes per-length prefill compiles matter.
    """

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None):
        super().__init__(name, config)
        self.max_new_tokens = int(self.config.get("max_new_tokens", 16))
        self.temperature = float(self.config.get("temperature", 0.0))
        self._cache_protos: dict[int, Any] = {}

    def load(self) -> None:
        ref = self.config["params_ref"]
        self.cfg, self.params = fetch_mem(ref[len("mem://"):])
        self.model = llamalib.Llama(self.cfg)
        temperature = self.temperature
        n_new = self.max_new_tokens

        def decode_step(params, cache, tok, pos):
            logits, mutated = self.model.apply(
                {"params": params, "cache": cache}, tok, pos,
                decode=True, mutable=["cache"])
            return logits[:, -1, :], mutated["cache"]

        def prefill(params, cache, prompt):
            """Chunked prefill: the WHOLE prompt in one decode=True forward
            (the cache's per-query mask makes multi-token writes correct).
            This is the only prompt-length-specialized program, and it is a
            plain forward — no per-token loop, no per-length scan."""
            b, length = prompt.shape
            positions = jnp.broadcast_to(
                jnp.arange(length, dtype=jnp.int32)[None, :], (b, length))
            return decode_step(params, cache, prompt, positions)

        def sample(params, cache, logits, start_pos):
            """n_new single-token decode steps as one lax.scan — compiled
            ONCE per batch size, independent of prompt length (start_pos is
            a traced scalar).  One dispatch + one host fetch per generate;
            a per-token Python loop with per-element int() fetches paid
            ~one host round trip per token (~100ms each on the
            remote-dispatch tunnel: the r3 serving-bench finding)."""
            b = logits.shape[0]

            def step(carry, key):
                cache, logits, pos = carry
                if temperature > 0:
                    tok = jax.random.categorical(
                        key, logits.astype(jnp.float32) / temperature, axis=-1)
                else:
                    tok = jnp.argmax(logits, axis=-1)
                tok = tok.astype(jnp.int32)
                l, cache = decode_step(
                    params, cache, tok[:, None],
                    jnp.broadcast_to(pos[None, None], (b, 1)))
                return (cache, l, pos + 1), tok

            keys = jax.random.split(jax.random.PRNGKey(0), n_new)
            (_, _, _), toks = jax.lax.scan(
                step, (cache, logits, start_pos), keys)
            return toks.T  # [b, n_new]

        self._prefill = jax.jit(prefill)
        self._sample = jax.jit(sample)
        self.ready = True

    def _init_cache(self, batch: int):
        # eval_shape traces WITHOUT executing: an eager model.init here
        # would dispatch hundreds of tiny ops per request (on a remote
        # PJRT backend that alone was ~40s/call); instead derive the cache
        # pytree abstractly and allocate zeros in one jitted program
        proto = self._cache_protos.get(batch)
        if proto is None:
            shapes = jax.eval_shape(
                lambda k, t, p: self.model.init(k, t, p, decode=True),
                jax.ShapeDtypeStruct((2,), jnp.uint32),
                jax.ShapeDtypeStruct((batch, 1), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1), jnp.int32),
            )["cache"]
            proto = jax.jit(lambda: jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), shapes))()
            self._cache_protos[batch] = proto
        return proto

    def predict_batch(self, instances):
        """The decode cache cursor is shared across a batch, so only
        equal-length prompts batch together; mixed lengths (normal under
        the micro-batcher) are grouped by length and each group runs
        batched — never padded, which would poison the KV cache."""
        prompts = [list(map(int, inst)) for inst in instances]
        by_len: dict[int, list[int]] = {}
        for i, p in enumerate(prompts):
            by_len.setdefault(len(p), []).append(i)
        outs: list[Optional[list[int]]] = [None] * len(prompts)
        for length, idxs in by_len.items():
            group = [prompts[i] for i in idxs]
            for i, o in zip(idxs, self._generate_group(group, length)):
                outs[i] = o
        return outs

    def _generate_group(self, prompts: list[list[int]], length: int) -> list[list[int]]:
        batch = len(prompts)
        cache = self._init_cache(batch)
        toks = jnp.asarray(np.asarray(prompts, dtype=np.int32))
        logits, cache = self._prefill(self.params, cache, toks)
        out = self._sample(self.params, cache, logits, jnp.int32(length))
        return np.asarray(jax.device_get(out)).tolist()


#: server_class registry for ServingRuntime.spec.server_class resolution
BUILTIN_RUNTIMES = {
    "kubeflow_tpu.serving.runtimes:EchoModel": EchoModel,
    "kubeflow_tpu.serving.runtimes:JaxFunctionModel": JaxFunctionModel,
    "kubeflow_tpu.serving.runtimes:LlamaGenerator": LlamaGenerator,
}


class BertClassifierModel(Model):
    """BERT sequence classification — baseline config 3's predictor
    ("KServe BERT-base InferenceService" -> the ``tpu`` runtime).

    config:
      params_ref:   "mem://key" holding (BertConfig, params)
      seq_buckets:  sequence-length buckets AOT-visible to XLA (pad-up),
                    default (32, 64, 128, 512-capped-to-max_position)

    Instances are token-id lists (ragged); predictions are per-class
    probability lists.  Padding tokens are masked out of attention, so a
    padded batch scores identically to per-instance evaluation.
    """

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None):
        super().__init__(name, config)
        self.batch_buckets = tuple(self.config.get("buckets", DEFAULT_BUCKETS))

    def load(self) -> None:
        from ..models import bert as bertlib

        ref = self.config["params_ref"]
        self.cfg, self.params = fetch_mem(ref[len("mem://"):])
        self.model = bertlib.BertClassifier(self.cfg)
        default_buckets = [b for b in (32, 64, 128, 512)
                           if b <= self.cfg.max_position] or [self.cfg.max_position]
        self.seq_buckets = tuple(self.config.get("seq_buckets", default_buckets))

        def forward(params, ids, mask):
            logits = self.model.apply(params, ids, mask)
            return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

        self._forward = jax.jit(forward)
        self.ready = True

    def _pad_to(self, n: int, buckets: tuple) -> int:
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    def predict_batch(self, instances):
        out: list = []
        cap = self.batch_buckets[-1]
        for i in range(0, len(instances), cap):
            chunk = instances[i : i + cap]
            b = self._pad_to(len(chunk), self.batch_buckets)
            s = self._pad_to(max(len(x) for x in chunk), self.seq_buckets)
            ids = np.zeros((b, s), np.int32)
            mask = np.zeros((b, s), np.bool_)
            for j, toks in enumerate(chunk):
                toks = toks[:s]
                ids[j, : len(toks)] = toks
                mask[j, : len(toks)] = True
            probs = np.asarray(jax.device_get(
                self._forward(self.params, jnp.asarray(ids), jnp.asarray(mask))))
            out.extend(probs[: len(chunk)].tolist())
        return out
