"""JAX serving runtimes: the ``tpu`` ServingRuntime family.

The north star's serving requirement [local: BASELINE.json]: "give KServe a
``tpu`` ServingRuntime that loads JAX/XLA-compiled predictors instead of
the Triton/GPU path".  These are those predictors:

- ``JaxFunctionModel``: any jittable fn + params, AOT-compiled at load for
  the fixed batch shapes the micro-batcher produces (pad-to-bucket, so XLA
  never sees a new shape at serve time).
- ``LlamaGenerator``: Llama checkpoint -> greedy/temperature decode with a
  KV cache; prefill and per-token decode are separate compiled programs,
  the standard TPU serving split.
- ``EchoModel``: trivial runtime for smoke tests and protocol conformance.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama as llamalib
from . import sharded as shardedlib
from .model import Model
from .storage import download, fetch_mem

#: batch buckets compiled ahead of time; requests pad up to the next bucket
DEFAULT_BUCKETS = (1, 2, 4, 8)


def pad_to_bucket(n: int, buckets) -> int:
    """Smallest bucket >= n; clamps to the largest (callers that must
    reject oversize inputs check against buckets[-1] themselves)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class EchoModel(Model):
    def predict_batch(self, instances):
        return instances


class JaxFunctionModel(Model):
    """Serve ``fn(params, batch_array) -> batch_array`` as an XLA program.

    config:
      fn_ref:      "mem://key" holding (fn, params)  [or set via attributes]
      buckets:     batch buckets to AOT-compile (default 1/2/4/8)
    """

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None):
        super().__init__(name, config)
        self.fn = self.config.get("fn")
        self.params = self.config.get("params")
        self.buckets = tuple(self.config.get("buckets", DEFAULT_BUCKETS))
        self._compiled: dict[int, Any] = {}

    def load(self) -> None:
        ref = self.config.get("fn_ref")
        if ref:
            self.fn, self.params = fetch_mem(ref[len("mem://"):])
        if self.fn is None:
            raise RuntimeError(f"model {self.name}: no fn/fn_ref configured")
        self._jitted = jax.jit(self.fn)
        self.ready = True


    def predict_batch(self, instances):
        x = np.asarray(instances, dtype=np.float32)
        out: list = []
        # chunk by the largest bucket, pad the tail to a bucket size
        cap = self.buckets[-1]
        for i in range(0, len(x), cap):
            chunk = x[i : i + cap]
            b = pad_to_bucket(len(chunk), self.buckets)
            padded = np.zeros((b, *chunk.shape[1:]), dtype=chunk.dtype)
            padded[: len(chunk)] = chunk
            y = np.asarray(jax.device_get(self._jitted(self.params, jnp.asarray(padded))))
            out.extend(y[: len(chunk)].tolist())
        return out


class LlamaGenerator(Model):
    """Greedy/temperature text-token generation over a Llama checkpoint.

    config:
      params_ref:   "mem://key" holding (LlamaConfig, params)
      max_new_tokens (default 16), temperature (default 0 = greedy)
      mesh_axes:    optional sharded-predictor mesh, e.g. {"model": 8} —
                    weights and KV cache shard over the chips (TP), which
                    is what serves models bigger than one chip's HBM
                    (serving/sharded.py; SURVEY §2.2 multi-accelerator
                    runtimes row)

    Instances are token-id lists; predictions are continuation token lists.
    Ragged prompts batch together: the KV cache tracks PER-ROW positions
    (models/llama.py _decode_attend), so a mixed-length micro-batch pads
    to a shared seq bucket and runs as ONE prefill forward + ONE sampling
    scan — XLA only ever compiles bucket shapes, and pad junk is masked
    out of attention per row until real decode writes overwrite it.
    """

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None):
        super().__init__(name, config)
        self.max_new_tokens = int(self.config.get("max_new_tokens", 16))
        self.temperature = float(self.config.get("temperature", 0.0))
        self.mesh = None  # set at load() when config carries mesh_axes
        self._cache_protos: dict[int, Any] = {}

    def load(self) -> None:
        ref = self.config.get("params_ref")
        if ref:
            self.cfg, self.params = fetch_mem(ref[len("mem://"):])
        elif self.config.get("storage_path"):
            # serve a published snapshot (config.json + weights.msgpack —
            # what save_pretrained writes and hf://-style storage_uri
            # resolves to): the train -> publish -> serve loop closes here
            self.cfg, self.params = llamalib.load_pretrained(
                self.config["storage_path"])
        else:
            raise RuntimeError(
                f"model {self.name}: need params_ref or storage_uri")
        self.model = llamalib.Llama(self.cfg)
        # decode is HBM-bound on weight reads (every parameter streams per
        # token); serving in bf16 halves that traffic.  Opt-in: training
        # checkpoints are f32 and greedy ties can flip under the cast.
        wd = self.config.get("weights_dtype")
        if wd:
            target = jnp.dtype(wd)
            self.params = jax.tree.map(
                lambda x: x.astype(target)
                if jnp.issubdtype(x.dtype, jnp.floating) else x,
                self.params)
        mesh_axes = self.config.get("mesh_axes")
        self.mesh = (
            shardedlib.build_serving_mesh(mesh_axes) if mesh_axes else None)
        if self.mesh is not None:
            # weights distribute TP-sharded at load: vocab/heads/mlp dims
            # split over the `model` axis per the shared logical-rule table
            self.params = shardedlib.place_params(
                self.cfg, self.params, self.mesh)
        mesh = self.mesh
        temperature = self.temperature
        n_new = self.max_new_tokens
        cfg = self.cfg

        def make_programs(attend: int):
            """(prefill, sample) jitted pair attending only over cache
            slots [0, attend) — the decode step streams the attended
            cache from HBM every token, so a 128-token prompt must not
            read max_seq_len slots.  One pair per window bucket."""
            model = llamalib.Llama(cfg, decode_attend_len=attend)

            def forward(params, cache, tok, positions):
                logits, mutated = model.apply(
                    {"params": params, "cache": cache}, tok, positions,
                    decode=True, mutable=["cache"])
                # keep the cache kv_heads-sharded across dispatches on a
                # serving mesh (no-op when mesh is None)
                return logits, shardedlib.constrain_cache(
                    mutated["cache"], mesh)

            def prefill(params, cache, prompt, lengths):
                """Chunked prefill of a RAGGED batch padded to one bucket:
                the whole padded prompt in one decode=True forward.  The
                cache's per-row position mask makes pad junk invisible;
                each row's next-token logits are gathered at its true last
                token."""
                b, length = prompt.shape
                positions = jnp.broadcast_to(
                    jnp.arange(length, dtype=jnp.int32)[None, :], (b, length))
                logits_all, cache = forward(params, cache, prompt, positions)
                last = jnp.take_along_axis(
                    logits_all, (lengths - 1)[:, None, None], axis=1)[:, 0]
                return last, cache

            def sample(params, cache, logits, lengths, key):
                """n_new single-token decode steps as one lax.scan —
                compiled per (batch, bucket)-shape, reused across requests.
                Per-row positions start at each row's true length, so
                ragged rows decode in lockstep without poisoning each
                other's cache.  One dispatch + one host fetch per generate;
                a per-token Python loop with per-element int() fetches paid
                ~one host round trip per token (~100ms each on the
                remote-dispatch tunnel: the r3 serving-bench finding)."""

                def step(carry, key):
                    cache, logits, pos = carry  # pos: [b] per-row positions
                    if temperature > 0:
                        tok = jax.random.categorical(
                            key, logits.astype(jnp.float32) / temperature,
                            axis=-1)
                    else:
                        tok = jnp.argmax(logits, axis=-1)
                    tok = tok.astype(jnp.int32)
                    l, cache = forward(params, cache, tok[:, None], pos[:, None])
                    return (cache, l[:, -1, :], pos + 1), tok

                keys = jax.random.split(key, n_new)
                (_, _, _), toks = jax.lax.scan(
                    step, (cache, logits, lengths), keys)
                return toks.T  # [b, n_new]

            return (shardedlib.mesh_jit(mesh, prefill),
                    shardedlib.mesh_jit(mesh, sample))

        self._programs: dict[int, tuple] = {}

        def programs_for(bucket: int):
            # prefill positions < bucket; decode positions < bucket + n_new
            attend = min(bucket + n_new, cfg.max_seq_len)
            if attend not in self._programs:
                self._programs[attend] = make_programs(attend)
            return self._programs[attend]

        self._programs_for = programs_for
        cap = self.cfg.max_seq_len - n_new
        if cap < 1:
            raise ValueError(
                f"max_new_tokens {n_new} leaves no room in max_seq_len "
                f"{self.cfg.max_seq_len}")
        default_buckets = [
            s for s in (32, 64, 128, 256, 512, 1024, 2048, 4096) if s < cap
        ] + [cap]
        raw = self.config.get("seq_buckets", default_buckets)
        # user buckets: sorted, deduped, clamped to what the cache can hold
        # (an oversized bucket would silently drop KV writes past max_seq)
        valid = sorted({int(b) for b in raw if 1 <= int(b) <= cap})
        if not valid:
            raise ValueError(
                f"no usable seq bucket <= {cap} in {raw!r}")
        self.seq_buckets = tuple(valid)
        self._base_key = jax.random.PRNGKey(
            int.from_bytes(os.urandom(4), "little"))
        self.ready = True

    def _init_cache(self, batch: int):
        # eval_shape traces WITHOUT executing: an eager model.init here
        # would dispatch hundreds of tiny ops per request (on a remote
        # PJRT backend that alone was ~40s/call); instead derive the cache
        # pytree abstractly and allocate zeros in one jitted program
        proto = self._cache_protos.get(batch)
        if proto is None:
            shapes = jax.eval_shape(
                lambda k, t, p: self.model.init(k, t, p, decode=True),
                jax.ShapeDtypeStruct((2,), jnp.uint32),
                jax.ShapeDtypeStruct((batch, 1), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1), jnp.int32),
            )["cache"]
            proto = shardedlib.mesh_jit(
                self.mesh,
                lambda: shardedlib.constrain_cache(
                    jax.tree.map(
                        lambda s: jnp.zeros(s.shape, s.dtype), shapes),
                    self.mesh))()
            self._cache_protos[batch] = proto
        return proto

    def predict_batch(self, instances):
        """Ragged prompts batch together: pad to a shared seq bucket (the
        cache's per-row positions keep pad junk out of attention), so one
        micro-batch is ONE prefill + ONE sampling scan regardless of the
        length mix, and XLA only ever sees bucket shapes."""
        cap = self.seq_buckets[-1]
        # left-truncate over-long prompts (keep the tail — it conditions
        # the next token) instead of raising: one client's oversize prompt
        # must not fail the co-batched requests of others
        prompts = [list(map(int, inst))[-cap:] for inst in instances]
        # empty prompts get an EMPTY continuation: raising would fail the
        # co-batched requests of other clients, and fabricating output
        # conditioned on an arbitrary token would be indistinguishable
        # from a real answer.  They ride the batch as placeholder rows.
        empty = [i for i, p in enumerate(prompts) if not p]
        if len(empty) == len(prompts):
            return [[] for _ in prompts]  # nothing to decode: skip dispatch
        prompts = [p if p else [0] for p in prompts]
        lengths = np.array([len(p) for p in prompts], np.int32)
        bucket = pad_to_bucket(int(lengths.max()), self.seq_buckets)
        batch = len(prompts)
        toks = np.zeros((batch, bucket), np.int32)
        for i, p in enumerate(prompts):
            toks[i, : len(p)] = p
        cache = self._init_cache(batch)
        prefill, sample = self._programs_for(bucket)
        logits, cache = prefill(
            self.params, cache, jnp.asarray(toks), jnp.asarray(lengths))
        # per-request sampling key: temperature>0 must differ across
        # requests AND across replicas/restarts (a fixed key made every
        # "random" continuation identical; a bare counter would replay the
        # same sequence on every replica)
        self._req_counter = getattr(self, "_req_counter", 0) + 1
        out = sample(
            self.params, cache, logits, jnp.asarray(lengths),
            jax.random.fold_in(self._base_key, self._req_counter))
        rows = np.asarray(jax.device_get(out)).tolist()
        for i in empty:
            rows[i] = []
        return rows


#: server_class registry for ServingRuntime.spec.server_class resolution
#: (resolve_class imports by path; this dict documents the builtin set —
#: ContinuousLlamaGenerator lives in continuous.py to keep engine imports
#: out of the basic-runtime path)
BUILTIN_RUNTIMES = {
    "kubeflow_tpu.serving.runtimes:EchoModel": EchoModel,
    "kubeflow_tpu.serving.runtimes:JaxFunctionModel": JaxFunctionModel,
    "kubeflow_tpu.serving.runtimes:LlamaGenerator": LlamaGenerator,
}


class BertClassifierModel(Model):
    """BERT sequence classification — baseline config 3's predictor
    ("KServe BERT-base InferenceService" -> the ``tpu`` runtime).

    config:
      params_ref:   "mem://key" holding (BertConfig, params)
      seq_buckets:  sequence-length buckets AOT-visible to XLA (pad-up),
                    default (32, 64, 128, 512-capped-to-max_position)

    Instances are token-id lists (ragged); predictions are per-class
    probability lists.  Padding tokens are masked out of attention, so a
    padded batch scores identically to per-instance evaluation.

    Weights come from ``params_ref`` (mem://) or, when the storage
    initializer resolved a ``storage_uri`` (file:// or hf://), from the
    snapshot directory at ``storage_path`` (config.json +
    weights.msgpack, models/bert.py save_pretrained layout).
    """

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None):
        super().__init__(name, config)
        self.batch_buckets = tuple(self.config.get("buckets", DEFAULT_BUCKETS))

    def load(self) -> None:
        from ..models import bert as bertlib

        ref = self.config.get("params_ref")
        if ref:
            self.cfg, self.params = fetch_mem(ref[len("mem://"):])
        elif self.config.get("storage_path"):
            self.cfg, self.params = bertlib.load_pretrained(
                self.config["storage_path"])
        else:
            raise RuntimeError(
                f"model {self.name}: need params_ref or storage_uri")
        self.model = bertlib.BertClassifier(self.cfg)
        default_buckets = [b for b in (32, 64, 128, 512)
                           if b <= self.cfg.max_position] or [self.cfg.max_position]
        self.seq_buckets = tuple(self.config.get("seq_buckets", default_buckets))

        def forward(params, ids, mask):
            logits = self.model.apply(params, ids, mask)
            return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

        self._forward = jax.jit(forward)
        self.ready = True


    def predict_batch(self, instances):
        out: list = []
        cap = self.batch_buckets[-1]
        for i in range(0, len(instances), cap):
            chunk = instances[i : i + cap]
            b = pad_to_bucket(len(chunk), self.batch_buckets)
            s = pad_to_bucket(max(len(x) for x in chunk), self.seq_buckets)
            ids = np.zeros((b, s), np.int32)
            mask = np.zeros((b, s), np.bool_)
            for j, toks in enumerate(chunk):
                toks = toks[:s]
                ids[j, : len(toks)] = toks
                mask[j, : len(toks)] = True
            probs = np.asarray(jax.device_get(
                self._forward(self.params, jnp.asarray(ids), jnp.asarray(mask))))
            out.extend(probs[: len(chunk)].tolist())
        return out
