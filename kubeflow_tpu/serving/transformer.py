"""Transformer component: user pre/post-processing in front of a predictor.

[upstream: kserve/kserve -> python/kserve transformer examples]: a
Transformer is a Model whose predict step is an HTTP call to the predictor
service, with user preprocess/postprocess around it — the same composition
here, over the in-cluster replica URLs the controller injects.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Optional

from .model import Model


class Transformer(Model):
    """Base transformer: override preprocess/postprocess; predict proxies."""

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None):
        super().__init__(name, config)
        self.predictor_urls: list[str] = list(self.config.get("predictor_urls", []))
        self.model_name = self.config.get("model_name", name)
        self._rr = 0

    def load(self) -> None:
        if not self.predictor_urls:
            raise RuntimeError(f"transformer {self.name}: no predictor_urls")
        self.ready = True

    def predict_batch(self, instances):
        self._rr = (self._rr + 1) % len(self.predictor_urls)
        url = f"{self.predictor_urls[self._rr]}/v1/models/{self.model_name}:predict"
        body = json.dumps({"instances": instances}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())["predictions"]
