"""Multi-chip serving: mesh-sharded predictor support.

The reference's serving tier is multi-accelerator natively — its LLM
runtimes (Triton, vLLM behind huggingfaceserver) span GPUs with tensor
parallelism [upstream: kserve/kserve -> python/huggingfaceserver,
config/runtimes/*.yaml; SURVEY.md §2.2 per-framework runtimes row, §3.3
predictor hot path].  The r3 serving data plane here was single-device,
which cannot serve the north-star model at all: Llama-7B bf16 weights are
~13 GiB = 81% of one 16 GiB v5e chip before any KV pool exists.

TPU-first design: serving reuses the EXACT sharding machinery the trainer
uses (parallel/sharding.py logical rules) rather than growing a parallel
layout system —

- a serving mesh is ``{"model": N}`` tensor parallelism over ICI first
  (per-layer all-reduces are bandwidth-hungry and must not cross DCN;
  parallel/mesh.py placement policy), optionally ``x data`` for throughput
  replicas of the pool;
- weights land sharded straight from the checkpoint via the same
  ``param_shardings`` table (vocab/heads/mlp dims on ``model``) — a 7B
  predictor never materializes on one chip;
- the KV cache/pool shards its ``kv_heads`` axis on ``model``: per-chip
  pool HBM = pool bytes / TP degree, which is what makes a 7B KV pool fit
  (scripts/aot_7b_serving.py records the per-chip breakdown).

Decode quality note: all programs stay single-program-multiple-device —
one jit dispatch drives all chips; there is no per-chip host loop.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel import mesh as meshlib
from ..parallel import sharding as shardlib


def build_serving_mesh(
    mesh_axes: dict[str, int], devices: Optional[list] = None
) -> Mesh:
    """Mesh over the first ``prod(axes)`` local devices.

    Unlike the trainer (which owns every device of its gang), a serving
    replica may use a subset of the host's chips — the controller packs
    multiple replicas per host — so the axis product picks how many.
    """
    import math

    n = math.prod(mesh_axes.values())
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n:
        raise ValueError(
            f"serving mesh {mesh_axes} needs {n} devices, have {len(devices)}")
    return meshlib.build_mesh(mesh_axes, devices=devices[:n])


def logical_axis(mesh: Mesh, name: str) -> Optional[str]:
    """Mesh axis a logical axis name rides on this mesh (the shared rule
    table restricted to present axes) — None degrades to replication."""
    rules = dict(shardlib.rules_for_mesh(mesh))
    return rules.get(name)


def kv_heads_axis(mesh: Mesh) -> Optional[str]:
    """Mesh axis the cache's kv_heads dim rides."""
    return logical_axis(mesh, "kv_heads")


def cache_leaf_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Sharding for one KV-cache leaf.

    Leaves are ``cached_key``/``cached_value`` of shape [batch, seq,
    kv_heads, head_dim] (plus a leading layer axis under scan_layers),
    the int8-KV ``*_scale`` buffers [batch, kv_heads, seq] (seq MINOR —
    chosen in llama._decode_attend precisely so the kv dim lands at
    ndim-2 here too), and scalar/per-layer ``cache_index`` bookkeeping.
    The kv_heads dim — uniformly ndim-2 on every >=4-dim leaf — shards
    on the TP axis; everything else replicates.  (Batch/slot sharding
    would put *requests* on different chips, which serves throughput but
    not model size; the capability gap is model size.)
    """
    axis = kv_heads_axis(mesh)
    if ndim < 4 or axis is None:
        return NamedSharding(mesh, PartitionSpec())
    spec = [None] * ndim
    spec[ndim - 2] = axis
    return NamedSharding(mesh, PartitionSpec(*spec))


def constrain_cache(cache: Any, mesh: Optional[Mesh]) -> Any:
    """Apply cache-leaf shardings inside a traced program (jit body)."""
    if mesh is None:
        return cache
    return jax.tree.map(
        lambda x: jax.lax.with_sharding_constraint(
            x, cache_leaf_sharding(mesh, x.ndim)),
        cache,
    )


def cache_shardings(cache_shapes: Any, mesh: Mesh) -> Any:
    """NamedSharding tree matching an eval_shape'd cache pytree."""
    return jax.tree.map(
        lambda s: cache_leaf_sharding(mesh, len(s.shape)), cache_shapes)


def constrain_replicated(x: jax.Array, mesh: Optional[Mesh]) -> jax.Array:
    """Force a program output fully replicated — every host can then fetch
    it locally (``np.asarray`` requires ``is_fully_replicated`` once the
    mesh spans processes; the sampled-token fetch on the gang's rank 0 is
    exactly that case)."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec()))


def constrain_logits(x: jax.Array, mesh: Optional[Mesh]) -> jax.Array:
    """Vocab-sharded logits constraint ([..., vocab] rides the TP axis,
    matching the unembedding matmul's natural output layout) — no-op
    without a mesh."""
    if mesh is None:
        return x
    spec = [None] * (x.ndim - 1) + [logical_axis(mesh, "act_vocab")]
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*spec)))


def llama_param_shardings(cfg, mesh: Mesh) -> Any:
    """Param-sharding tree for a Llama config on this mesh, derived from
    the same logical-axis metadata the trainer uses (one layout table for
    train AND serve — a checkpoint's logical names mean the same thing on
    both sides)."""
    from ..models import llama as llamalib

    boxed = jax.eval_shape(
        llamalib.Llama(cfg).init,
        jax.ShapeDtypeStruct((2,), jax.numpy.uint32),
        jax.ShapeDtypeStruct((1, 8), jax.numpy.int32),
    )["params"]
    return shardlib.param_shardings(boxed, mesh)


def place_params(cfg, params: Any, mesh: Mesh) -> Any:
    """Distribute loaded weights onto the mesh (TP-sharded device_put).

    Accepts boxed (``nn.Partitioned``) or plain trees — checkpoints and
    ``model.init`` hand back boxed params; serving operates unboxed.

    When the mesh spans multiple host processes (the serving gang,
    serving/gang.py), every process calls this with the SAME host-local
    weights (each gang member loads the same snapshot) and each
    contributes its addressable shards via ``make_array_from_callback``
    — ``device_put`` cannot target non-addressable devices.
    """
    from flax import linen as nn

    params = nn.meta.unbox(params)
    shardings = llama_param_shardings(cfg, mesh)
    if jax.process_count() == 1:
        return jax.device_put(params, shardings)
    import numpy as np

    def place(leaf, s):
        arr = np.asarray(leaf)
        return jax.make_array_from_callback(arr.shape, s, lambda i: arr[i])

    return jax.tree.map(place, params, shardings)


def mesh_jit(mesh: Optional[Mesh], fn, **jit_kwargs):
    """``jax.jit`` whose calls run under the serving mesh's shard context.

    The model's ``nn.with_logical_constraint`` annotations silently no-op
    unless flax's logical-axis rules AND the abstract mesh are active at
    trace time (parallel/sharding.py shard_context docstring); every
    program call site must therefore enter the context — first call traces.
    With ``mesh=None`` this is exactly ``jax.jit``.
    """
    jitted = jax.jit(fn, **jit_kwargs)
    if mesh is None:
        return jitted

    def call(*args, **kwargs):
        with shardlib.shard_context(mesh):
            return jitted(*args, **kwargs)

    # expose AOT lowering for the serving AOT artifact path
    call.lower = lambda *a, **k: _lowered(mesh, jitted, *a, **k)
    # expose the inner jitted fn so the analysis recompile guard can
    # read its trace-cache size (analysis/runtime.py RecompileGuard)
    call._jitted = jitted
    return call


def _lowered(mesh, jitted, *args, **kwargs):
    with shardlib.shard_context(mesh):
        return jitted.lower(*args, **kwargs)
