"""Continuous batching for LLM serving: cross-request decode scheduling.

[upstream: kserve/kserve -> python/huggingfaceserver (vLLM backend)] — the
reference's LLM runtime delegates to vLLM, whose defining capability is
*continuous batching*: requests join and leave the running decode batch at
token boundaries instead of waiting for the current batch to finish
(SURVEY.md §2.2 per-framework runtimes row).  ``LlamaGenerator``
(runtimes.py) decodes each micro-batch to completion — a request arriving
one token after a 64-token batch started waits ~64 token-steps for its
first token.  This module removes that wait.

TPU-first design (vs vLLM's CUDA paged-attention kernels):

- **Block economy, gathered per dispatch (ISSUE 6).**  KV lives in a
  pool of fixed-size BLOCKS owned by a free-list allocator
  (serving/paged.py BlockAllocator); each request holds a block table
  and pays HBM for its actual length, not ``max_seq_len``.  XLA wants
  static shapes and the model's decode math wants a contiguous per-row
  cache, so every paged dispatch GATHERS its working view from the
  block pool (per-slot block tables -> the exact [slots, attend, ...]
  layout the slot-pool programs consumed), runs the byte-identical
  decode/prefill/verify math, and scatters the written blocks back.
  Views are warmed per attend rung, so ``jit_recompiles_total`` stays 0.
  Prefixes share in block quanta across live AND retired sequences
  (refcounts; the free list doubles as the prefix cache), a diverging
  request forks the boundary block with one on-device copy (COW), and a
  freed block is reused without clearing — the per-row causal mask
  makes stale KV past a row's live front invisible, exactly the
  ragged-batch argument LlamaGenerator already relies on.  The legacy
  contiguous slot pool (``block_size=0``) survives as the parity
  reference the paged programs are pinned bit-identical against.
- **Prefill rides the decode dispatch.**  Chunked (Sarathi) admission
  fuses one prefill chunk into each pool decode scan; in paged mode the
  chunk writes land in the admitting slot's blocks through the same
  gathered view (one gather, one scatter per dispatch).  The legacy
  pool keeps its batch-prefill + scatter-merge admission.
- **Decode as a chunked scan over the whole pool.**  Each dispatch runs
  ``decode_chunk`` sampling steps for ALL slots in one ``lax.scan``
  program; inactive slots ride along with their cache writes dropped
  (position pinned past the view).  Chunking amortizes the
  host round trip that dominates per-token latency on a remote-dispatch
  backend (PERF.md: 16.8 ms/token floor through the tunnel); admission
  happens between chunks, so ``decode_chunk=1`` gives strict
  token-boundary admission and larger chunks trade admission latency for
  dispatch amortization.

All buffers are donated across dispatches, so the pool cache exists in
HBM exactly once.

**Thread contract — the mailbox seam.**  Scheduler state (the slot
table, ``_waiting``, the block allocator and per-slot block tables, the
donated pool buffers, the ``_migrating`` freeze map) is owned by the
scheduler thread, full stop.  The ONE blessed path for any other
thread — HTTP handlers, migration workers, the traffic plane's
preemptor, resize orchestration — to mutate it is the migration
mailbox: post an op with ``_post_migration_op`` (or ``_queue.put`` for
plain submission) and the scheduler services it between dispatches in
``_service_migrations``, on the thread that owns the state.
Cross-thread READS are allowed GIL-copy style (``list(engine._slots)``)
but every decision made from one must be re-validated by the mailbox op
that acts on it — the snapshot is stale by construction.  The
analyzer's ``thread-affinity`` rule (analysis/rules_threads.py)
enforces the write half mechanically: an owned-state write reachable
from a non-scheduler role fails tier-1 unless pragma'd with a reason.
The seam needs no allowlist precisely because posting to the queue is
not a write — ``export_sequence`` never touches the pool, and
``_mig_export`` is only reachable from ``_loop``.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.runtime import RecompileCounter, recompile_guard
from ..models import llama as llamalib
from . import sharded as shardedlib
from .model import Model
from .paged import (
    BlockAllocator,
    HostBlockPool,
    gather_block_view,
    scatter_block_view,
    write_window_tables,
)
from . import programs as programslib
from .paged import block_keys as _block_keys
from .paged import lcp as _lcp  # noqa: F401 — the one LCP implementation
from .storage import fetch_mem
from .trace import Trace

log = logging.getLogger("kubeflow_tpu.serving")


@dataclass
class Request:
    """One generation request tracked through the engine."""

    prompt: list[int]
    max_new_tokens: int
    #: per-request sampling knobs (None = the engine's defaults) — the
    #: OpenAI fields: temperature (0 = greedy, >0 = categorical),
    #: top_p (nucleus mass), top_k (candidate cutoff; 0 = off)
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    #: QoS priority tier (serving/traffic.py: 0=high, 1=normal, 2=low):
    #: admission prefers lower tiers (stable sort — FIFO within a
    #: tier), and the traffic plane's preemptor may evict-and-requeue
    #: a live higher-tier sequence for a waiting lower-tier one
    priority: int = 1
    submitted_at: float = field(default_factory=time.perf_counter)
    #: engine step counter when the request was submitted / admitted
    submitted_step: int = 0
    admitted_step: int = -1
    slot: int = -1
    tokens: list[int] = field(default_factory=list)
    first_token_at: Optional[float] = None
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[Exception] = None

    cancelled: threading.Event = field(default_factory=threading.Event)
    #: request-lifecycle trace (serving/trace.py), None = unsampled.
    #: Every instrumentation site guards on this None, so an untraced
    #: request pays one attribute read per site and allocates nothing.
    trace: Optional[Any] = None
    #: durable-session binding (ISSUE 15): set, the idle-session reaper
    #: may hibernate this sequence under this id once it goes quiet
    session_id: Optional[str] = None
    #: idle-session accounting: stamped by the scheduler at every token
    #: delivery (and at admission/resume) — ``idle_sessions`` compares
    #: it against the reaper's idle clock.  A single float write, so
    #: the scheduler-side stamp is GIL-safe to read from any thread.
    last_token_at: float = field(default_factory=time.perf_counter)

    def cancel(self) -> None:
        """Client-side cancellation (disconnect, timeout): the request
        resolves immediately with whatever tokens it has; the engine
        frees its slot at the next chunk boundary — a cancelled request
        must stop consuming decode slots (the vLLM abort contract)."""
        self.cancelled.set()
        self.done.set()

    def wait(self, timeout: Optional[float] = None) -> list[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not complete in time")
        if self.error is not None:
            raise self.error
        return self.tokens

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


def cache_shapes(cfg: llamalib.LlamaConfig, batch: int):
    """Abstract KV-cache pytree for a ``batch``-row cache (eval_shape — no
    allocation, no dispatch)."""
    model = llamalib.Llama(cfg)
    return jax.eval_shape(
        lambda k, t, p: model.init(k, t, p, decode=True),
        jax.ShapeDtypeStruct((2,), jnp.uint32),
        jax.ShapeDtypeStruct((batch, 1), jnp.int32),
        jax.ShapeDtypeStruct((batch, 1), jnp.int32),
    )["cache"]


def make_prefill_program(cfg, attend: int, mesh=None):
    """[g, bucket] ragged prefill -> (last-token logits [g, v], row cache),
    attending only over cache slots [0, attend).

    Module-level (not an engine closure) so the AOT artifact path
    (scripts/aot_7b_serving.py) compiles the EXACT program the live engine
    dispatches — the HBM-fit evidence covers the real serving program, not
    a stand-in.
    """
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)

    def prefill(params, prompt, lengths):
        b, length = prompt.shape
        cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes(cfg, b))
        positions = jnp.broadcast_to(
            jnp.arange(length, dtype=jnp.int32)[None, :], (b, length))
        logits_all, mutated = wmodel.apply(
            {"params": params, "cache": cache}, prompt, positions,
            decode=True, mutable=["cache"])
        last = jnp.take_along_axis(
            logits_all, (lengths - 1)[:, None, None], axis=1)[:, 0]
        return (shardedlib.constrain_logits(last, mesh),
                shardedlib.constrain_cache(mutated["cache"], mesh))

    return shardedlib.mesh_jit(mesh, prefill)


def make_prefix_admit_program(cfg, attend: int, suffix_bucket: int,
                              batch_axes=None, mesh=None, seq_axes=None):
    """Admission with PREFIX REUSE, fused into one dispatch.

    A new request whose prompt shares a long prefix with what some slot's
    KV already holds (same conversation re-sent, shared system prompt,
    N-best fan-out) must not pay prefill FLOPs for the shared part —
    vLLM-class engines make this a core serving economy [upstream:
    kserve huggingfaceserver vLLM backend; SURVEY §2.2].  The slot-pool
    design supports it without paging:

      pool[dst, :lp]  <- pool[src, :lp]        (masked row copy, on-device)
      suffix forward at positions [lp, lp+sl)  (attends the copied prefix)
      pool[dst] <- updated row; logits[dst] <- last-token logits

    ``batch_axes``: per-leaf slot-axis tree (the engine's ``_batch_axes``
    probe — the slot axis sits AFTER the scanned layer axis).
    ``seq_axes``: per-leaf seq-axis tree (``_seq_axes`` probe) — the k/v
    tensors keep seq right after the slot axis, but the int8-KV scale
    buffers keep it LAST (llama._decode_attend layout note), so the
    prefix mask must target the probed dim, not a positional guess.
    Signature: (params, pool_cache, pool_logits, src, dst, lp, suffix,
    slen) -> (pool_cache, pool_logits); pool buffers donated.
    """
    from jax import lax

    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)
    if seq_axes is None:  # pre-probe callers: seq follows the slot axis
        seq_axes = jax.tree.map(
            lambda a: None if a is None else a + 1, batch_axes)

    def admit(params, pool_cache, pool_logits, src, dst, lp, suffix, slen):
        def copy_leaf(c, a, sax):
            if a is None:  # cache_index bookkeeping: untouched
                return c
            src_row = jnp.take(c, src, axis=a)   # slot axis removed
            dst_row = jnp.take(c, dst, axis=a)
            seq_len = c.shape[sax]
            s_row = sax - 1 if sax > a else sax  # row lost the slot axis
            mask = (jnp.arange(seq_len) < lp).reshape(
                [seq_len if i == s_row else 1 for i in range(c.ndim - 1)])
            merged = jnp.where(mask, src_row, dst_row)
            idx = (slice(None),) * a + (dst,)
            # mode="drop": an out-of-range dst (the warmup sentinel
            # num_slots) must discard, not clamp onto the last real slot
            return c.at[idx].set(merged, mode="drop")

        pool_cache = jax.tree.map(copy_leaf, pool_cache, batch_axes,
                                  seq_axes)
        # suffix forward against the copied prefix: slice the dst row
        # (batch 1), run a [1, bucket] decode-mode forward at positions
        # lp+arange, scatter the mutated row back
        row = jax.tree.map(
            lambda c, a: c if a is None
            else lax.dynamic_slice_in_dim(c, dst, 1, axis=a),
            pool_cache, batch_axes)
        positions = (lp + jnp.arange(suffix_bucket, dtype=jnp.int32))[None, :]
        logits_all, mutated = wmodel.apply(
            {"params": params, "cache": row}, suffix[None], positions,
            decode=True, mutable=["cache"])
        last = jnp.take_along_axis(
            logits_all, (slen - 1)[None, None, None], axis=1)[:, 0]

        def scatter_leaf(c, r, a):
            if a is None:
                return c
            idx = (slice(None),) * a + (dst,)
            return c.at[idx].set(jnp.take(r, 0, axis=a), mode="drop")

        pool_cache = shardedlib.constrain_cache(
            jax.tree.map(scatter_leaf, pool_cache, mutated["cache"],
                         batch_axes), mesh)
        pool_logits = shardedlib.constrain_logits(
            pool_logits.at[dst].set(last[0], mode="drop"), mesh)
        return pool_cache, pool_logits

    return shardedlib.mesh_jit(mesh, admit, donate_argnums=(1, 2))


def _seg_kv(seg_cache):
    """(pk, pv) leaves of a segment-pool cache tree (scan layout:
    [L, n_seg, S_seg, KV, D])."""
    attn = seg_cache["layers"]["block"]["attn"]
    return attn["cached_key"], attn["cached_value"]


def make_suffix_admit_program(cfg, attend: int, seg_att: int,
                              suffix_bucket: int, mesh=None):
    """Admission AGAINST SHARED SEGMENTS: run only the suffix forwards,
    attending the (immutable) segment KV gathered per row — the slots'
    private caches store suffixes at SLOT-LOCAL positions, so slots can
    be far shorter than prompt+response (the paged-KV capacity economy,
    SURVEY §2.2; design note in llama._decode_attend).

    BATCHED like the legacy prefill (a burst of N same-prefix requests
    costs 2 dispatches, not 2N — the admission docstring's rule holds):
    (params, seg_cache, toks [g, bucket], seg_ids [g], plens [g],
    slens [g]) -> (last_logits [g, v], row_cache) — feeds the engine's
    existing merge.  Rows with plen == 0 (group padding) attend nothing
    of the segment.
    """
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)

    def admit(params, seg_cache, toks, seg_ids, plens, slens):
        g = toks.shape[0]
        pk, pv = _seg_kv(seg_cache)
        pk = jnp.take(pk, seg_ids, axis=1)[:, :, :seg_att]  # [L,g,sa,KV,D]
        pv = jnp.take(pv, seg_ids, axis=1)[:, :, :seg_att]
        cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes(cfg, g))
        ar = jnp.arange(suffix_bucket, dtype=jnp.int32)
        gpos = plens[:, None] + ar[None, :]
        lpos = jnp.broadcast_to(ar[None, :], (g, suffix_bucket))
        logits_all, mutated = wmodel.apply(
            {"params": params, "cache": cache}, toks, gpos,
            decode=True, prefix=(pk, pv, plens.astype(jnp.int32)),
            cache_positions=lpos, mutable=["cache"])
        last = jnp.take_along_axis(
            logits_all, (slens - 1)[:, None, None], axis=1)[:, 0]
        return (shardedlib.constrain_logits(last, mesh),
                shardedlib.constrain_cache(mutated["cache"], mesh))

    return shardedlib.mesh_jit(mesh, admit)


def make_prefix_decode_program(cfg, attend: int, seg_att: int, chunk: int,
                               mesh=None):
    """``chunk`` sampling steps for the whole pool where slots may attend
    a shared segment: per-slot (seg_id, plen) gather the segment KV once
    per dispatch; private cache positions are slot-local (= global -
    plen), so the pool's rows hold only suffixes.  Rows with plen == 0
    behave exactly as the plain decode program (empty segment masked
    out)."""
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)

    def decode(params, cache, logits, seg_cache, positions, plens,
               seg_ids, active, temps, top_ps, top_ks, key):
        # positions are SLOT-LOCAL; the sentinel (max_seq_len) drops
        # writes exactly as in the plain program
        safe = jnp.where(active, positions, cfg.max_seq_len)
        pk, pv = _seg_kv(seg_cache)
        pk = jnp.take(pk, seg_ids, axis=1)[:, :, :seg_att]  # [L,b,sa,KV,D]
        pv = jnp.take(pv, seg_ids, axis=1)[:, :, :seg_att]

        def step(carry, key):
            cache, logits, lpos = carry
            tok = _sample_step(logits, temps, top_ps, top_ks, key)
            gpos = lpos + plens  # rope/causality are global
            l, mutated = wmodel.apply(
                {"params": params, "cache": cache}, tok[:, None],
                gpos[:, None], decode=True, prefix=(pk, pv, plens),
                cache_positions=lpos[:, None], mutable=["cache"])
            nxt = jnp.where(active, lpos + 1, cfg.max_seq_len)
            return (shardedlib.constrain_cache(mutated["cache"], mesh),
                    shardedlib.constrain_logits(l[:, -1, :], mesh),
                    nxt), tok

        keys = jax.random.split(key, chunk)
        (cache, logits, lpos), toks = jax.lax.scan(
            step, (cache, logits, safe), keys)
        return cache, logits, shardedlib.constrain_replicated(toks.T, mesh)

    return shardedlib.mesh_jit(mesh, decode, donate_argnums=(1, 2))


def _sample_step(logits, temps, top_ps, top_ks, key, banned=None):
    """One sampling decision for every slot — the OpenAI sampling
    family, per request, in one dispatch:

    - ``temps`` [slots] f32: 0 = greedy, >0 = categorical at T;
    - ``top_ks`` [slots] i32: 0 = off, k = keep the k most likely;
    - ``top_ps`` [slots] f32: 1 = off, p = nucleus (smallest set of
      tokens whose cumulative probability reaches p).

    HF-conventional warp order (temperature -> top-k -> top-p) on one
    descending sort of the scaled logits; filters reduce to "keep values
    >= a per-slot threshold", so the original layout never re-sorts.
    Greedy slots ignore the filtered distribution entirely.

    ``banned`` [slots] i32 (-1 = none) removes one token per slot AFTER
    the warp — the speculative residual re-draw (see _verify_math) must
    come from the residual of the WARPED distribution: masking before
    top-k/top-p would shift the kept set and admit tokens plain decode
    can never emit.  The banned token is always sampleable-complement-
    safe: it only arms when the previous draw from these same logits'
    warped set produced a DIFFERENT token, so at least one kept token
    survives the mask.  Greedy argmax ignores it (a greedy rejection
    already proved argmax != banned).
    """
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits.astype(jnp.float32) / jnp.maximum(temps, 1e-6)[:, None]

    def filtered(scaled):
        sorted_desc = -jnp.sort(-scaled, axis=-1)         # [slots, v]
        # top-k: values below the k-th largest drop (k = 0 -> keep all)
        k_eff = jnp.where(top_ks > 0, jnp.clip(top_ks, 1, v), v)
        kth = jnp.take_along_axis(
            sorted_desc, (k_eff - 1)[:, None], axis=-1)   # [slots, 1]
        ranks = jnp.arange(v, dtype=jnp.int32)[None, :]
        sorted_k = jnp.where(ranks < k_eff[:, None], sorted_desc, -jnp.inf)
        # top-p over the top-k-filtered distribution: keep tokens while
        # the cumulative probability BEFORE them is < p (always keeps
        # the top-1)
        sp = jax.nn.softmax(sorted_k, axis=-1)
        cum_before = jnp.cumsum(sp, axis=-1) - sp
        keep = jnp.logical_and(
            ranks < k_eff[:, None],
            cum_before < jnp.clip(top_ps, 1e-6, 1.0)[:, None])
        # threshold = smallest kept VALUE; original layout, no unsort
        min_keep = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1)[:, None]
        return jnp.where(
            jnp.logical_and(scaled >= min_keep, scaled >= kth),
            scaled, -jnp.inf)

    # the vocab sort costs ~9% of decode throughput (measured at 271M):
    # lax.cond executes only the taken branch, so pools with no
    # top-p/top-k request in flight pay nothing
    need = jnp.any(jnp.logical_or(top_ks > 0, top_ps < 1.0))
    final = jax.lax.cond(need, filtered, lambda s: s, scaled)
    if banned is not None:
        ids = jnp.arange(v, dtype=jnp.int32)[None, :]
        final = jnp.where(ids == banned[:, None], -jnp.inf, final)
    sampled = jax.random.categorical(key, final, axis=-1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


def _chunk_prefill_body(cfg, wmodel, budget: int, batch_axes, mesh):
    """Shared transform of the chunked-prefill programs: run ``budget``
    prompt tokens of ONE admitting slot's prefill against the pool —
    slice the slot row, forward the chunk at global positions
    [start, start+budget), scatter the mutated row back, and (on the
    final chunk only) write the last real token's logits into the pool
    logits at ``write_slot``.

    Sarathi-style chunked prefill: the prompt's KV lands in its slot
    incrementally across dispatches, each bounded by ``budget`` tokens,
    instead of one monolithic [1, prompt_bucket] program that freezes
    the decode stream for every live request (ISSUE 2).  Non-final
    chunks pass ``write_slot = num_slots`` so the logits write drops;
    the final chunk passes the real slot and ``length`` marks the last
    real token (padding beyond it writes masked garbage, the same
    stale-KV argument the slot pool already relies on).
    """
    from jax import lax

    def body(params, pool_cache, pool_logits, slot, toks, start, length,
             write_slot):
        row = jax.tree.map(
            lambda c, a: c if a is None
            else lax.dynamic_slice_in_dim(c, slot, 1, axis=a),
            pool_cache, batch_axes)
        positions = (start + jnp.arange(budget, dtype=jnp.int32))[None, :]
        logits_all, mutated = wmodel.apply(
            {"params": params, "cache": row}, toks[None], positions,
            decode=True, mutable=["cache"])
        last = jnp.take_along_axis(
            logits_all, (length - 1)[None, None, None], axis=1)[:, 0]

        def scatter_leaf(c, r, a):
            if a is None:
                return c
            idx = (slice(None),) * a + (slot,)
            # mode="drop": the warmup sentinel (slot == num_slots) must
            # discard, not clamp onto the last real slot
            return c.at[idx].set(jnp.take(r, 0, axis=a), mode="drop")

        pool_cache = shardedlib.constrain_cache(
            jax.tree.map(scatter_leaf, pool_cache, mutated["cache"],
                         batch_axes), mesh)
        pool_logits = shardedlib.constrain_logits(
            pool_logits.at[write_slot].set(last[0], mode="drop"), mesh)
        return pool_cache, pool_logits

    return body


def make_chunk_prefill_program(cfg, attend: int, budget: int, batch_axes,
                               mesh=None):
    """One ``budget``-token prefill chunk as its own dispatch — used when
    the fused program cannot ride a decode dispatch (no live decode work,
    or the live pool decodes through the segment-aware program).
    Signature: (params, pool_cache, pool_logits, slot, toks [budget],
    start, length, write_slot) -> (pool_cache, pool_logits); pool
    buffers donated."""
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)
    body = _chunk_prefill_body(cfg, wmodel, budget, batch_axes, mesh)
    return shardedlib.mesh_jit(mesh, body, donate_argnums=(1, 2))


def make_fused_step_program(cfg, attend: int, chunk: int, budget: int,
                            batch_axes, mesh=None):
    """STALL-FREE step: one dispatch = one prefill chunk of the admitting
    request + ``chunk`` decode sampling steps for the whole live pool —
    the HFTA move (PAPERS) applied to serving: heterogeneous work fused
    into one program so the decode stream never waits on a monolithic
    prefill.  The decode half is byte-identical math to
    :func:`make_decode_program` for active slots; inactive rows (the
    admitting one included) KEEP their logits through the scan, so the
    final chunk's last-token logits survive the ride-along decode and
    seed the slot's first sampled token at the next dispatch.
    """
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)
    body = _chunk_prefill_body(cfg, wmodel, budget, batch_axes, mesh)

    def fused(params, cache, logits, slot, toks, start, length, write_slot,
              positions, active, temps, top_ps, top_ks, key):
        cache, logits = body(params, cache, logits, slot, toks, start,
                             length, write_slot)
        safe = jnp.where(active, positions, cfg.max_seq_len)

        def step(carry, key):
            cache, logits, pos = carry
            tok = _sample_step(logits, temps, top_ps, top_ks, key)
            l, mutated = wmodel.apply(
                {"params": params, "cache": cache}, tok[:, None],
                pos[:, None], decode=True, mutable=["cache"])
            nxt = jnp.where(active, pos + 1, cfg.max_seq_len)
            # inactive rows hold their logits (the plain decode program
            # may clobber them — nothing reads those; here the admitting
            # row's fresh prefill logits MUST survive to the next step)
            kept = jnp.where(active[:, None], l[:, -1, :], logits)
            return (shardedlib.constrain_cache(mutated["cache"], mesh),
                    shardedlib.constrain_logits(kept, mesh),
                    nxt), tok

        keys = jax.random.split(key, chunk)
        (cache, logits, pos), out = jax.lax.scan(
            step, (cache, logits, safe), keys)
        return cache, logits, shardedlib.constrain_replicated(out.T, mesh)

    return shardedlib.mesh_jit(mesh, fused, donate_argnums=(1, 2))


def make_decode_program(cfg, attend: int, chunk: int, mesh=None):
    """``chunk`` sampling steps for the whole slot pool in one program,
    attending only over cache slots [0, attend).

    Inactive slots still compute (the price of a static pool) but their
    cache writes drop: position is pinned to max_seq_len, where the
    per-row scatter's mode="drop" discards the write and the causal mask
    hides the slot from every live row.  Pool cache + logits are donated —
    the pool exists in HBM exactly once.

    ``temps`` is a PER-SLOT f32 array (0 = greedy, >0 = categorical at
    that temperature): requests carry their own sampling temperature —
    the OpenAI per-request ``temperature`` field — without recompiling,
    and mixed greedy/sampled slots ride one dispatch.
    """
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)

    def decode(params, cache, logits, positions, active, temps,
               top_ps, top_ks, key):
        safe = jnp.where(active, positions, cfg.max_seq_len)

        def step(carry, key):
            cache, logits, pos = carry
            tok = _sample_step(logits, temps, top_ps, top_ks, key)
            l, mutated = wmodel.apply(
                {"params": params, "cache": cache}, tok[:, None],
                pos[:, None], decode=True, mutable=["cache"])
            nxt = jnp.where(active, pos + 1, cfg.max_seq_len)
            return (shardedlib.constrain_cache(mutated["cache"], mesh),
                    shardedlib.constrain_logits(l[:, -1, :], mesh),
                    nxt), tok

        keys = jax.random.split(key, chunk)
        (cache, logits, pos), toks = jax.lax.scan(
            step, (cache, logits, safe), keys)
        # tokens replicate so ANY host of a multi-process serving mesh can
        # fetch them locally (the gang's rank-0 scheduler does)
        return cache, logits, shardedlib.constrain_replicated(toks.T, mesh)

    return shardedlib.mesh_jit(mesh, decode, donate_argnums=(1, 2))


def _paged_view_len(attend: int, block_size: int) -> int:
    """Gathered-view length for an attend rung: whole blocks covering it
    (== the rung whenever block_size divides it; the model still attends
    only [0, attend), so the math stays bit-identical to the slot pool)."""
    return -(-attend // block_size) * block_size


def make_paged_decode_program(cfg, attend: int, chunk: int, block_size: int,
                              block_axes, seq_axes, mesh=None):
    """Paged twin of :func:`make_decode_program`: gather each slot's
    block table into the contiguous working view, run the identical
    ``chunk``-step sampling scan, scatter the written blocks back.
    Signature: (params, pool, logits, bt [slots, nblk], positions,
    active, temps, top_ps, top_ks, key) -> (pool, logits, toks); pool +
    logits donated.  The inactive-row sentinel pins to the VIEW length
    (>= attend), where the per-row scatter's mode="drop" discards the
    write exactly as max_seq_len does in the slot pool."""
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)
    view_len = _paged_view_len(attend, block_size)

    def decode(params, pool, logits, bt, positions, active, temps,
               top_ps, top_ks, key):
        view = shardedlib.constrain_cache(
            gather_block_view(pool, bt, block_axes, seq_axes), mesh)
        safe = jnp.where(active, positions, view_len)

        def step(carry, key):
            cache, logits, pos = carry
            tok = _sample_step(logits, temps, top_ps, top_ks, key)
            l, mutated = wmodel.apply(
                {"params": params, "cache": cache}, tok[:, None],
                pos[:, None], decode=True, mutable=["cache"])
            nxt = jnp.where(active, pos + 1, view_len)
            return (shardedlib.constrain_cache(mutated["cache"], mesh),
                    shardedlib.constrain_logits(l[:, -1, :], mesh),
                    nxt), tok

        keys = jax.random.split(key, chunk)
        (view, logits, _pos), toks = jax.lax.scan(
            step, (view, logits, safe), keys)
        # write-back narrowed to the written suffix window: this dispatch
        # wrote row r only at [safe[r], safe[r]+chunk) — shared prefix
        # blocks and idle rows (safe = view_len) scatter nothing
        bt_w = write_window_tables(bt, safe, block_size)
        pool = shardedlib.constrain_cache(
            scatter_block_view(pool, view, bt_w, block_axes, seq_axes),
            mesh)
        return pool, logits, shardedlib.constrain_replicated(toks.T, mesh)

    return shardedlib.mesh_jit(mesh, decode, donate_argnums=(1, 2))


def make_paged_chunk_prefill_program(cfg, attend: int, budget: int,
                                     block_size: int, block_axes, seq_axes,
                                     mesh=None):
    """One ``budget``-token prefill chunk against the admitting slot's
    OWN blocks: gather just that slot's table row ([1, nblk]), run the
    shared chunk body on the single-row view, scatter the blocks back.
    Signature: (params, pool, logits, bt_row [1, nblk], toks [budget],
    start, length, write_slot) -> (pool, logits); pool + logits donated.
    """
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)
    body = _chunk_prefill_body(cfg, wmodel, budget, block_axes, mesh)

    def chunk(params, pool, logits, bt_row, toks, start, length,
              write_slot):
        view = gather_block_view(pool, bt_row, block_axes, seq_axes)
        view, logits = body(params, view, logits, jnp.int32(0), toks,
                            start, length, write_slot)
        # the chunk writes only [start, start+budget): prefix blocks the
        # slot shares (full blocks below start) scatter nothing
        bt_w = write_window_tables(
            bt_row, jnp.reshape(start, (1,)), block_size)
        pool = shardedlib.constrain_cache(
            scatter_block_view(pool, view, bt_w, block_axes, seq_axes),
            mesh)
        return pool, shardedlib.constrain_logits(logits, mesh)

    return shardedlib.mesh_jit(mesh, chunk, donate_argnums=(1, 2))


def make_paged_fused_step_program(cfg, attend: int, chunk: int, budget: int,
                                  block_size: int, block_axes, seq_axes,
                                  mesh=None):
    """Paged twin of :func:`make_fused_step_program`: ONE gather serves
    both halves — the admitting slot's prefill chunk writes into its
    blocks through the same view the whole-pool decode scan runs on,
    and one scatter commits everything.  Inactive rows (the admitting
    one included) KEEP their logits through the scan, exactly the r6
    fused-step rule."""
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)
    body = _chunk_prefill_body(cfg, wmodel, budget, block_axes, mesh)
    view_len = _paged_view_len(attend, block_size)

    def fused(params, pool, logits, bt, slot, toks, start, length,
              write_slot, positions, active, temps, top_ps, top_ks, key):
        view = shardedlib.constrain_cache(
            gather_block_view(pool, bt, block_axes, seq_axes), mesh)
        view, logits = body(params, view, logits, slot, toks, start,
                            length, write_slot)
        safe = jnp.where(active, positions, view_len)

        def step(carry, key):
            cache, logits, pos = carry
            tok = _sample_step(logits, temps, top_ps, top_ks, key)
            l, mutated = wmodel.apply(
                {"params": params, "cache": cache}, tok[:, None],
                pos[:, None], decode=True, mutable=["cache"])
            nxt = jnp.where(active, pos + 1, view_len)
            kept = jnp.where(active[:, None], l[:, -1, :], logits)
            return (shardedlib.constrain_cache(mutated["cache"], mesh),
                    shardedlib.constrain_logits(kept, mesh),
                    nxt), tok

        keys = jax.random.split(key, chunk)
        (view, logits, _pos), out = jax.lax.scan(
            step, (view, logits, safe), keys)
        # per-row write fronts: decode rows write from their position,
        # the admitting slot's chunk writes from ``start``, idle rows
        # write nothing (front = view_len) — scatter only those blocks
        front = jnp.where(
            jnp.arange(bt.shape[0], dtype=jnp.int32) == slot,
            jnp.minimum(safe, start), safe)
        bt_w = write_window_tables(bt, front, block_size)
        pool = shardedlib.constrain_cache(
            scatter_block_view(pool, view, bt_w, block_axes, seq_axes),
            mesh)
        return pool, logits, shardedlib.constrain_replicated(out.T, mesh)

    return shardedlib.mesh_jit(mesh, fused, donate_argnums=(1, 2))


def make_paged_verify_program(cfg, attend: int, k: int, block_size: int,
                              block_axes, seq_axes, mesh=None):
    """Paged twin of :func:`make_verify_program`: gather, the identical
    speculative-verify math (:func:`_verify_math` — the inactive-row
    sentinel retargeted to the view length), scatter."""
    import dataclasses as _dc

    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)
    view_len = _paged_view_len(attend, block_size)
    vmath = _verify_math(
        _dc.replace(cfg, max_seq_len=view_len), wmodel, k, mesh)

    def verify(params, pool, logits, bt, drafts, banned, positions,
               active, temps, top_ps, top_ks, key):
        view = shardedlib.constrain_cache(
            gather_block_view(pool, bt, block_axes, seq_axes), mesh)
        view, logits, toks, accept = vmath(
            params, view, logits, drafts, banned, positions, active,
            temps, top_ps, top_ks, key)
        # the verify writes [pos, pos+k+1) per active row — blocks below
        # the position front (shared prefixes included) scatter nothing
        bt_w = write_window_tables(
            bt, jnp.where(active, positions, view_len), block_size)
        pool = shardedlib.constrain_cache(
            scatter_block_view(pool, view, bt_w, block_axes, seq_axes),
            mesh)
        return pool, logits, toks, accept

    return shardedlib.mesh_jit(mesh, verify, donate_argnums=(1, 2))


def make_paged_fused_verify_program(cfg, attend: int, k: int, budget: int,
                                    block_size: int, block_axes, seq_axes,
                                    mesh=None):
    """Paged twin of :func:`make_fused_verify_program`: one gather, the
    chunk body, the verify math, one scatter."""
    import dataclasses as _dc

    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)
    body = _chunk_prefill_body(cfg, wmodel, budget, block_axes, mesh)
    view_len = _paged_view_len(attend, block_size)
    vmath = _verify_math(
        _dc.replace(cfg, max_seq_len=view_len), wmodel, k, mesh)

    def fused(params, pool, logits, bt, slot, toks, start, length,
              write_slot, drafts, banned, positions, active, temps,
              top_ps, top_ks, key):
        view = shardedlib.constrain_cache(
            gather_block_view(pool, bt, block_axes, seq_axes), mesh)
        view, logits = body(params, view, logits, slot, toks, start,
                            length, write_slot)
        view, logits, vtoks, accept = vmath(
            params, view, logits, drafts, banned, positions, active,
            temps, top_ps, top_ks, key)
        base = jnp.where(active, positions, view_len)
        front = jnp.where(
            jnp.arange(bt.shape[0], dtype=jnp.int32) == slot,
            jnp.minimum(base, start), base)
        bt_w = write_window_tables(bt, front, block_size)
        pool = shardedlib.constrain_cache(
            scatter_block_view(pool, view, bt_w, block_axes, seq_axes),
            mesh)
        return pool, logits, vtoks, accept

    return shardedlib.mesh_jit(mesh, fused, donate_argnums=(1, 2))


def make_block_copy_program(block_axes, mesh=None):
    """COW fork: copy ONE block's bytes (src -> dst) across every cache
    leaf — the on-device dispatch that lets a request diverge inside a
    shared prefix block without touching the source.  dst out of range
    (the warmup sentinel) drops; src clips.  Pool donated."""

    def copy(pool, src, dst):
        def leaf(c, a):
            if a is None:
                return c
            row = jnp.take(c, src, axis=a, mode="clip")
            idx = (slice(None),) * a + (dst,)
            return c.at[idx].set(row, mode="drop")

        return shardedlib.constrain_cache(
            jax.tree.map(leaf, pool, block_axes), mesh)

    return shardedlib.mesh_jit(mesh, copy, donate_argnums=(0,))


#: blocks per migration gather/scatter dispatch: the table is a FIXED
#: [KV_MIGRATE_GROUP, 1] shape (padded with the sentinel), so ONE
#: compiled program each way serves sequences of any length while the
#: per-dispatch overhead amortizes over 8 blocks — an import between
#: two decode dispatches costs ceil(nblocks/8) scatters, not nblocks
#: (the import-stall tax the migration bench measures)
KV_MIGRATE_GROUP = 8


def make_kv_export_program(block_axes, seq_axes, mesh=None):
    """Migration gather (ISSUE 8): up to KV_MIGRATE_GROUP blocks' bytes
    out of the pool as a tuple of row-major [G, block_size, ...] leaves
    (row axis moved FIRST so the host slices per-block without knowing
    each leaf's layout; cache_index bookkeeping leaves skipped — the
    destination has its own).  Fixed [G, 1] table shape: pad rows carry
    the clip sentinel and are sliced off host-side.  The pool is NOT
    donated: export is a read (copy-then-cutover — the source keeps
    decoding until the destination acks)."""

    def export(pool, bt_rows):
        view = gather_block_view(pool, bt_rows, block_axes, seq_axes)
        out = []

        def pick(v, a):
            if a is not None:
                out.append(jnp.moveaxis(v, a, 0))
            return v

        jax.tree.map(pick, view, block_axes)
        return tuple(out)

    return shardedlib.mesh_jit(mesh, export)


def make_kv_import_program(block_axes, seq_axes, mesh=None):
    """Migration scatter (ISSUE 8): write up to KV_MIGRATE_GROUP
    received blocks' leaves into the pool at the [G, 1] table — the
    exact inverse of :func:`make_kv_export_program` (leaves arrive
    row-major, rows move back to each leaf's probed axis), same fixed
    shape, pool donated.  Pad rows carry the out-of-range sentinel and
    drop.  Leaf order matches export's (deterministic tree flatten
    order)."""

    def imp(pool, bt_rows, leaves):
        it = iter(leaves)
        # rebuild the view tree: real block leaves from the wire, the
        # axis-None bookkeeping leaves from the pool (scatter ignores
        # them — scatter_block_view returns the pool leaf unchanged)
        view = jax.tree.map(
            lambda c, a: (jnp.moveaxis(next(it), 0, a)
                          if a is not None else c),
            pool, block_axes)
        return shardedlib.constrain_cache(
            scatter_block_view(pool, view, bt_rows, block_axes,
                               seq_axes),
            mesh)

    return shardedlib.mesh_jit(mesh, imp, donate_argnums=(0,))


def make_logits_take_program(mesh=None):
    """One slot's next-token logits row (migration export; read-only,
    mode="clip" so the warmup sentinel slot reads harmlessly)."""

    def take(logits, slot):
        return shardedlib.constrain_replicated(
            jnp.take(logits, slot, axis=0, mode="clip"), mesh)

    return shardedlib.mesh_jit(mesh, take)


def make_logits_set_program(mesh=None):
    """Install an imported logits row at the destination slot (logits
    donated; mode="drop" discards the warmup sentinel write)."""

    def put(logits, row, slot):
        return shardedlib.constrain_logits(
            logits.at[slot].set(row, mode="drop"), mesh)

    return shardedlib.mesh_jit(mesh, put, donate_argnums=(0,))


class DraftProposer:
    """Draft-token source for speculative decoding (ISSUE 4).

    ``propose(history, k)`` returns up to ``k`` guessed continuation
    tokens for a request whose prompt+generated token history is
    ``history`` (host ints, the slot's KV ground truth) — or ``[]``
    when it has nothing to offer.  ALIGNMENT CONTRACT: the verify
    dispatch always emits the true next token unconditionally (t1,
    sampled on-device from the carried logits), so guessing it buys
    nothing — ``propose`` must guess the ``k`` tokens AFTER the
    immediate next one, i.e. the continuation offset by one position.
    Proposals are treated as a POINT-MASS draft distribution by the
    verifier, so any proposer is sound: a wrong guess costs only the
    verify FLOPs, never correctness.  The engine ships the
    draft-model-free :class:`NgramProposer`; a tiny-draft-model
    proposer plugs in here as a follow-up without touching the
    dispatch path.
    """

    def propose(self, history: list[int], k: int) -> list[int]:
        raise NotImplementedError


class NgramProposer(DraftProposer):
    """Prompt-lookup / n-gram drafts: match the last ``n`` tokens of the
    request's own history (prompt + generated) against that history and
    propose the tokens that followed the most recent earlier match.
    Pure host numpy — no dispatch, no model, no assets — and a huge win
    on structured/repetitive traffic (code, templated JSON, quoting the
    prompt back), where the continuation literally already exists in
    context."""

    def __init__(self, n: int = 3, window: int = 4096):
        if n < 1:
            raise ValueError("ngram length must be >= 1")
        if window < 1:
            raise ValueError("lookup window must be >= 1")
        self.n = int(n)
        #: scan at most the trailing ``window`` tokens per proposal.
        #: The lookup runs on the host BETWEEN dispatches (speculation
        #: serializes the pipeline to depth 1), so an unbounded rescan
        #: would grow linearly with context each step — O(len^2) per
        #: request, the same class PR 1's _StopScanner killed.  "Most
        #: recent earlier match" is unchanged for any match inside the
        #: window; only matches entirely older than ``window`` tokens
        #: are forgone (graceful degradation, standard prompt-lookup
        #: practice).
        self.window = int(window)

    @staticmethod
    def _lookup(arr: np.ndarray, n: int, k: int) -> list[int]:
        """Tokens that followed the most recent earlier occurrence of
        ``arr``'s last-``n`` tail (up to ``k`` of them), [] if none."""
        m = len(arr) - n  # candidate match starts: [0, m); m = tail
        if m <= 0 or k <= 0:
            return []
        tail = arr[-n:]
        windows = np.lib.stride_tricks.sliding_window_view(arr, n)[:m]
        hits = np.nonzero((windows == tail).all(axis=1))[0]
        if hits.size == 0:
            return []
        j = int(hits[-1])  # most recent earlier occurrence
        return arr[j + n: j + n + k].astype(int).tolist()

    def propose(self, history: list[int], k: int) -> list[int]:
        n = self.n
        # analysis: ok host-sync-in-dispatch — host token list, no device value
        arr = np.asarray(history[-self.window:], np.int64)
        # guess[0] predicts the position the verify's t1 already covers
        # (the DraftProposer alignment contract) — drafts are the k
        # tokens AFTER it, betting t1 repeats the match's own next
        # token.  The one-token shift is load-bearing: without it every
        # draft sits one position early and acceptance collapses to the
        # token==successor coincidence rate (a fixed-point stream hides
        # this; any proper cycle exposes it).  When the match abuts the
        # tail (short-period runs: the continuation runs off the end of
        # history), keep drafting by re-matching on history + the guess
        # so far — copy-and-continue, the prompt-lookup idiom.
        guess = self._lookup(arr, n, k + 1)
        if not guess:
            return []
        while len(guess) < k + 1:
            more = self._lookup(
                np.concatenate([arr, np.asarray(guess, np.int64)]), n,
                k + 1 - len(guess))
            if not more:
                break
            guess.extend(more)
        return guess[1: k + 1]


def _verify_math(cfg, wmodel, k: int, mesh):
    """Shared transform of the speculative-verify programs: one dispatch
    consumes ``k`` proposed tokens per slot and emits logits for all
    k+1 positions (ISSUE 4).

    Per active slot with carried logits L0 (predicting the front
    position) and drafts g_1..g_k (-1 = no proposal at that rung):

    - t1 = sample(L0) — the guaranteed-progress token, bit-identical to
      what the plain decode scan's first step would emit.  ``banned``
      masks one token out AFTER the top-k/top-p warp (inside
      _sample_step): when the PREVIOUS verify rejected draft g at this
      position, the rejected candidate was discarded, so exact
      rejection sampling requires the re-draw to come from the residual
      of the WARPED distribution (warp, then remove g, renormalize —
      masking before the warp would shift the kept set and admit tokens
      plain decode can never emit).  Greedy slots are unaffected — a
      greedy rejection already proves argmax != g.
    - ONE [slots, k+1] forward of [t1, g_1..g_k] at positions
      [front, front+k]: the decode cache path writes each token's KV at
      its own row position and the per-query causal mask makes token i
      attend exactly tokens < i — a multi-token decode forward IS the
      sequential math, batched (the same property chunked prefill
      already relies on).  This is the byte-bill amortization: ONE
      weight+KV stream serves k+1 positions.
    - candidate tokens cand_i = sample(L_i) at every draft position;
      accept the longest prefix with cand_i == g_i (a point-mass draft
      makes sample-and-match EXACTLY classic rejection sampling:
      accept g w.p. p(g), and the next dispatch's residual re-draw
      covers the reject branch).  -1 pads never match, so rungs
      without a real proposal neither accept nor arm a ban.
    - the carried logits become L_{1+a} (the row after the last emitted
      token) and the host rewinds nothing: accepted tokens' KV is
      already correct, rejected tokens' KV is stale garbage at
      positions the per-row causal mask hides until the next dispatch
      overwrites them (the slot pool's standing stale-KV argument) —
      the per-row position pointer is the only rollback.

    Returns (pool_cache, pool_logits, toks [slots, k+1], accept
    [slots]): the host emits toks[s, :1+accept[s]] and computes the
    next ban from its own draft copy at the sanctioned fetch boundary.
    """

    def verify(params, cache, logits, drafts, banned, positions, active,
               temps, top_ps, top_ks, key):
        safe = jnp.where(active, positions, cfg.max_seq_len)
        keys = jax.random.split(key, k + 1)
        t1 = _sample_step(logits, temps, top_ps, top_ks, keys[0],
                          banned=banned)
        toks = jnp.concatenate(
            [t1[:, None], drafts.astype(jnp.int32)], axis=1)
        grid = safe[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None, :]
        l, mutated = wmodel.apply(
            {"params": params, "cache": cache}, toks, grid,
            decode=True, mutable=["cache"])
        # l[:, i] = logits after toks[:, :i+1]; cand_i verifies draft i
        cand = jnp.stack(
            [_sample_step(l[:, i, :], temps, top_ps, top_ks, keys[i + 1])
             for i in range(k)], axis=1)
        match = (cand == drafts).astype(jnp.int32)
        accept = jnp.cumprod(match, axis=1).sum(axis=1)  # [slots] in [0,k]
        sel = jnp.take_along_axis(l, accept[:, None, None], axis=1)[:, 0]
        # inactive rows KEEP their logits: under fused chunked prefill
        # the admitting row's fresh prefill logits must survive (the r6
        # fused-step rule), and a just-merged row's seed logits likewise
        kept = jnp.where(active[:, None], sel.astype(logits.dtype), logits)
        return (shardedlib.constrain_cache(mutated["cache"], mesh),
                shardedlib.constrain_logits(kept, mesh),
                shardedlib.constrain_replicated(toks, mesh),
                shardedlib.constrain_replicated(accept, mesh))

    return verify


def make_verify_program(cfg, attend: int, k: int, mesh=None):
    """Speculative verify for the whole slot pool in one dispatch,
    attending only over cache slots [0, attend).  Signature: (params,
    cache, logits, drafts [slots, k], banned [slots], positions,
    active, temps, top_ps, top_ks, key) -> (cache, logits,
    toks [slots, k+1], accept [slots]); pool buffers donated.  See
    :func:`_verify_math` for the acceptance contract."""
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)
    return shardedlib.mesh_jit(
        mesh, _verify_math(cfg, wmodel, k, mesh), donate_argnums=(1, 2))


def make_fused_verify_program(cfg, attend: int, k: int, budget: int,
                              batch_axes, mesh=None):
    """STALL-FREE speculative step: one prefill chunk of the admitting
    request + one speculative verify of the whole live pool in ONE
    dispatch — chunked prefill fuses into verify dispatches exactly as
    it fuses into plain decode (make_fused_step_program), so turning
    speculation on never reopens the admission stall ISSUE 2 closed.
    The chunk body runs first; the verify keeps inactive rows' logits,
    so the final chunk's last-token logits survive to seed the slot's
    first sampled token."""
    wmodel = llamalib.Llama(cfg, decode_attend_len=attend)
    body = _chunk_prefill_body(cfg, wmodel, budget, batch_axes, mesh)
    vmath = _verify_math(cfg, wmodel, k, mesh)

    def fused(params, cache, logits, slot, toks, start, length, write_slot,
              drafts, banned, positions, active, temps, top_ps, top_ks,
              key):
        cache, logits = body(params, cache, logits, slot, toks, start,
                             length, write_slot)
        return vmath(params, cache, logits, drafts, banned, positions,
                     active, temps, top_ps, top_ks, key)

    return shardedlib.mesh_jit(mesh, fused, donate_argnums=(1, 2))


class ContinuousEngine:
    """Slot-pool continuous-batching decode engine over a Llama model.

    Parameters
    ----------
    cfg, params:    model config + weights (as in LlamaGenerator).
    num_slots:      pool width — max requests decoding concurrently.
    decode_chunk:   sampling steps per dispatch; admission happens between
                    dispatches (1 = admit at every token boundary).
    temperature:    0 = greedy; >0 = categorical sampling.
    eos_id:         optional stop token (host-checked between chunks).
    mesh_axes:      optional serving mesh, e.g. {"model": 8}: weights and
                    the slot-pool KV cache shard over the chips (TP over
                    ICI), serving models bigger than one chip's HBM —
                    the pool stays ONE jit program spanning the mesh
                    (serving/sharded.py).
    prefill_budget: 0 = legacy whole-prompt admission (one [1, bucket]
                    prefill dispatch per prompt — a long prompt freezes
                    token emission for every live request while it runs).
                    > 0 = STALL-FREE chunked admission: prompts prefill
                    ``prefill_budget`` tokens per dispatch, fused into
                    the pool decode program (make_fused_step_program),
                    so decode inter-token latency during an admission is
                    bounded by one chunk's compute instead of the whole
                    prompt's.  The first token emerges from the final
                    chunk's logits exactly as a merged prefill's would —
                    greedy tokens are bit-identical to the legacy path.
                    Tradeoff (documented, not hidden): admissions are
                    FIFO, one chunk per dispatch, so a cold BURST of g
                    prompts pays g+ dispatches where the legacy path
                    batches it as one [g, bucket] prefill + one merge —
                    the Sarathi bargain: admission throughput traded for
                    a per-dispatch prefill bound no burst can break
                    (later burst members start decoding fused with
                    earlier members' chunks, so the pool is never idle
                    while it drains).  The prefix-cache route honors the
                    bound too: it is only taken when the suffix fits one
                    budget (longer suffixes re-prefill chunked).  Known
                    carve-out: SHARED-PREFIX SEGMENTS (opt-in,
                    prefix_segments > 0) still create/admit with
                    monolithic dispatches bounded by segment_len, not
                    prefill_budget — an operator enabling both chooses
                    segment capacity economics over the strict bound.
    spec_k:         0 = off.  > 0 = SPECULATIVE DECODING (ISSUE 4):
                    every decode-carrying dispatch may verify up to
                    ``spec_k`` draft tokens per slot in ONE program
                    (make_verify_program), amortizing the weight+KV
                    HBM stream — the decode step's byte bill — over
                    every accepted run.  Drafts come from the
                    draft-model-free :class:`NgramProposer` (or an
                    injected :class:`DraftProposer`).  Greedy tokens
                    are BIT-IDENTICAL to non-speculative decode;
                    stochastic sampling is exact rejection sampling
                    against the verifier's distribution (point-mass
                    drafts make sample-and-match the textbook accept
                    rule, with the residual re-draw via the ``banned``
                    mask).  Tradeoff (documented, not hidden): the
                    accept length is VALUE-dependent, so a spec-enabled
                    pool runs its dispatch-ahead pipeline at depth 1 —
                    every verify fetch lands before the next dispatch
                    (the ``pipeline_depth`` knob is kept but inert
                    while spec_k > 0).  Iterations where no slot has a
                    draft (and no residual ban is pending) fall back to
                    the plain ``decode_chunk`` scan, so low-acceptance
                    traffic pays only the proposer's host-side lookup.
                    Segment-backed slots (prefix_segments) decode
                    through the segment program un-speculated.
    spec_ngram:     n-gram length the NgramProposer matches on
                    (default 3).
    prefix_cache:   reuse KV across requests sharing a prompt prefix
                    (min_prefix tokens or more) with any slot's current
                    content: admission becomes an on-device prefix copy +
                    suffix-only prefill (make_prefix_admit_program) —
                    repeated system prompts / conversation re-sends skip
                    their shared prefill entirely.  Under the paged pool
                    the same knob governs BLOCK-granular sharing: full
                    prefix blocks are shared by refcount (zero copy),
                    the boundary block forks with one COW dispatch, and
                    retired sequences stay matchable until their blocks
                    are actually reused.
    block_size:     0 = the legacy contiguous slot pool.  > 0 = the
                    PAGED-KV block pool (ISSUE 6): KV lives in
                    ``num_blocks`` blocks of ``block_size`` tokens
                    owned by a free-list allocator; requests hold block
                    tables and pay HBM for their actual length.  Every
                    dispatch gathers per-slot block tables into the
                    contiguous working view the slot-pool programs
                    consumed (warmed per attend rung — zero steady-state
                    recompiles), so greedy tokens are BIT-IDENTICAL to
                    the slot pool.  Admission reserves the request's
                    full worst-case span (prompt + max_new_tokens) up
                    front — insufficient free blocks queue the request
                    (backpressure), never a mid-decode eviction.
                    Supersedes ``prefix_segments`` (block-granular
                    sharing subsumes whole-segment LCP); combining them
                    is a config error.
    num_blocks:     paged pool size; 0 derives slot-pool capacity parity
                    (num_slots * ceil(max_seq_len / block_size)).
    admission_policy: optional host callable(req) -> bool consulted at
                    admission (scheduler thread); False defers the
                    request without consuming a slot.  The tier ladder
                    rides this hook (TieredEngine) instead of owning
                    per-tier KV pools.
    role:           "mixed" (default) | "prefill" | "decode" — the
                    prefill/decode disaggregation knob (ISSUE 8).  A
                    ``prefill`` engine admits and chunk-prefills only:
                    when a sequence's final chunk lands, the slot
                    FREEZES at the chunk boundary and ``on_prefilled``
                    (set by :class:`DisaggregatedPool` or the operator)
                    hands it to a decode replica via
                    ``export_sequence``/``import_sequence`` — so decode
                    ITL on the decode tier never pays prefill compute.
                    A ``decode`` engine is a migration destination; its
                    direct-submission path stays functional (drain
                    fallback), routing is the pool's job.  Roles other
                    than "mixed" require the paged pool: the migration
                    unit is the KV block.  Migration is COPY-THEN-
                    CUTOVER: export never frees the source slot; the
                    caller releases it only after the destination
                    acks, and a failed transfer resumes decoding in
                    place.
    """

    def __init__(
        self,
        cfg: llamalib.LlamaConfig,
        params: Any,
        *,
        num_slots: int = 8,
        decode_chunk: int = 1,
        prefill_budget: int = 0,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        seq_buckets: Optional[list[int]] = None,
        default_max_new_tokens: int = 16,
        pipeline_depth: int = 2,
        mesh_axes: Optional[dict[str, int]] = None,
        prefix_cache: bool = True,
        min_prefix: int = 32,
        prefix_segments: int = 0,
        segment_len: int = 0,
        spec_k: int = 0,
        spec_ngram: int = 3,
        draft_proposer: Optional[DraftProposer] = None,
        block_size: int = 0,
        num_blocks: int = 0,
        host_blocks: int = 0,
        host_watermark: float = 0.25,
        admission_policy=None,
        role: str = "mixed",
        program_cache=None,
    ):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if decode_chunk < 1:
            raise ValueError("decode_chunk must be >= 1")
        if prefill_budget < 0:
            raise ValueError("prefill_budget must be >= 0 (0 = off)")
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        if spec_k < 0:
            raise ValueError("spec_k must be >= 0 (0 = off)")
        if spec_ngram < 1:
            raise ValueError("spec_ngram must be >= 1")
        if block_size < 0:
            raise ValueError("block_size must be >= 0 (0 = slot pool)")
        if num_blocks < 0:
            raise ValueError("num_blocks must be >= 0 (0 = derived)")
        if host_blocks < 0:
            raise ValueError("host_blocks must be >= 0 (0 = no host tier)")
        if host_blocks > 0 and block_size <= 0:
            raise ValueError(
                "the host KV tier requires the paged pool "
                "(block_size > 0): the spill unit is the block")
        if not (0.0 <= float(host_watermark) <= 1.0):
            raise ValueError("host_watermark must be in [0, 1]")
        if block_size > 0 and int(prefix_segments) > 0:
            raise ValueError(
                "prefix_segments is superseded by the paged pool: "
                "block-granular sharing subsumes whole-segment LCP — "
                "drop prefix_segments or set block_size=0")
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"role {role!r}: must be mixed|prefill|decode")
        if role != "mixed" and block_size <= 0:
            raise ValueError(
                f"role={role} requires the paged pool (block_size > 0): "
                "the KV migration unit is the block")
        if 0 < cfg.max_seq_len <= block_size:
            raise ValueError(
                f"block_size {block_size} must be < max_seq_len "
                f"{cfg.max_seq_len}")
        self.cfg = cfg
        self.mesh = (
            shardedlib.build_serving_mesh(mesh_axes) if mesh_axes else None)
        if self.mesh is not None:
            params = shardedlib.place_params(cfg, params, self.mesh)
        else:
            # commit host arrays (snapshots, quantize_for_serving output)
            # to the device ONCE — leaving numpy leaves in self.params
            # would re-upload the whole model on EVERY dispatch, which a
            # remote-dispatch backend turns into seconds per token
            params = jax.device_put(params)
        self.params = params
        self.num_slots = num_slots
        self.decode_chunk = decode_chunk
        self.prefill_budget = int(prefill_budget)
        self.prefix_segments = int(prefix_segments)
        self.segment_len = int(segment_len)
        if self.prefix_segments > 0:
            if self.segment_len <= 0:
                raise ValueError("prefix_segments needs segment_len > 0")
            if self.segment_len < int(min_prefix):
                raise ValueError(
                    f"segment_len {segment_len} < min_prefix {min_prefix}:"
                    " every created segment would be unusable")
            if not cfg.scan_layers:
                raise ValueError(
                    "shared-prefix segments require scan_layers=True")
        self.spec_k = int(spec_k)
        self.spec_ngram = int(spec_ngram)
        self._proposer = draft_proposer or NgramProposer(self.spec_ngram)
        #: paged-KV block economy (ISSUE 6): block_size > 0 switches the
        #: pool storage to blocks + per-slot tables; the dispatch math is
        #: unchanged (gathered views), so the slot-pool scheduler state
        #: below stays authoritative either way
        self.block_size = int(block_size)
        self.paged = self.block_size > 0
        if self.paged and num_blocks == 0:
            # capacity parity with the slot pool it replaces: the same
            # HBM hosts the same worst case, and everything shorter
            # frees blocks for MORE concurrent conversations
            num_blocks = num_slots * (
                -(-cfg.max_seq_len // self.block_size))
        self.num_blocks = int(num_blocks)
        self._alloc = (BlockAllocator(self.num_blocks, self.block_size)
                       if self.paged else None)
        #: host-RAM KV tier (ISSUE 12): a bounded numpy mirror of
        #: retired sequences' block bytes.  The HBM free-list-as-cache
        #: only retains a prefix until its blocks are REALLOCATED; under
        #: pressure (free list below the watermark) retiring sequences
        #: spill their full blocks host-side so the hot prefix set can
        #: exceed the HBM pool.  The SCHEDULER only dispatches the
        #: gathers; a host-tier worker thread materializes them
        #: (device->host fetch must never run on the scheduler — the
        #: analyzer's *Tier/*Spill roots pin the inverse for the pool).
        self.host_blocks = int(host_blocks)
        self._host_pool = (HostBlockPool(self.host_blocks, self.block_size)
                           if self.paged and self.host_blocks > 0 else None)
        #: free-block count below which retirement spills to host RAM
        self._host_watermark_blocks = int(self.num_blocks
                                          * float(host_watermark))
        self._spill_q: "queue.Queue" = queue.Queue()
        self._spill_thread: Optional[threading.Thread] = None
        #: storage tier (KvSpillStore) for hibernate/thaw — attached by
        #: the runtime (attach_spill_store); counters surface the ISSUE
        #: 12 gauge set whether or not a store is attached
        self.spill_store = None
        #: spill/thaw counters tick from the host-tier worker, from
        #: hibernating caller threads AND from the scheduler (restore/
        #: install) — bare += across threads loses increments (the r12
        #: bench-probe lesson), so they share one small lock
        self._tier_mu = threading.Lock()
        self.kv_spills_total = 0
        self.kv_thaws_total = 0
        self.kv_thaws_degraded_total = 0
        #: optional analysis/runtime.py BlockLedger: shadow-refcount
        #: audit of the block economy + the kv_blocks_leaked_total
        #: gauge; attach via attach_block_ledger (tests, chaos, benches)
        self.block_ledger = None
        #: optional serving/trace.py Tracer shared with the runtime
        #: fronting this engine: engine-level phase durations with no
        #: request trace (a host-tier spill) observe into its sink, and
        #: a wire import with a propagated trace context continues the
        #: trace here (set by text.py / tests; never required)
        self.tracer = None
        #: per-slot block tables (host ints; the dispatch-side arrays are
        #: assembled fresh per dispatch in _block_tables)
        self._slot_blocks: list[list[int]] = [[] for _ in range(num_slots)]
        self.admission_policy = admission_policy
        self.role = role
        #: disaggregation handoff hook (scheduler thread, must not
        #: block): called with the Request when a prefill-role engine
        #: finishes a sequence's final chunk — the slot is already
        #: frozen at the boundary; the hook enqueues the migration for
        #: an off-thread worker (blocking socket sends from the
        #: scheduler are exactly what the analyzer's blocking-socket
        #: extension flags)
        self.on_prefilled = None
        #: live KV migration (ISSUE 8): slots frozen pending cutover
        #: (slot -> {"req", "entry"}) and the cross-thread mailbox the
        #: scheduler services between dispatches — export/import/resume/
        #: release all mutate pool + scheduler state, so they run ONLY
        #: on the scheduler thread
        self._migrating: dict[int, dict] = {}
        self._migrate_q: "queue.Queue[tuple]" = queue.Queue()
        self.kv_migrations_total = 0
        self.kv_migrate_failures_total = 0
        self.kv_migrate_bytes_total = 0
        #: latency histogram (ms) over completed migrations this engine
        #: initiated (export -> destination ack), fixed buckets + inf
        self._mig_buckets = (5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                             500.0, 1000.0)
        self._mig_lat_counts = [0] * (len(self._mig_buckets) + 1)
        self._mig_lat_sum = 0.0
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.default_max_new_tokens = default_max_new_tokens
        #: chunks in flight on the device before the host blocks on a
        #: fetch: depth 2 overlaps chunk k's host round trip with chunk
        #: k+1's device compute (the tunnel's ~100ms/fetch floor would
        #: otherwise serialize into the decode timeline — PERF.md).  The
        #: schedule advanced at dispatch time is value-independent, so
        #: only EOS retirement lags by up to depth-1 chunks.
        self.pipeline_depth = pipeline_depth
        self.model = llamalib.Llama(cfg)

        cap = cfg.max_seq_len - 1
        raw = seq_buckets or [
            s for s in (32, 64, 128, 256, 512, 1024, 2048, 4096) if s < cap
        ] + [cap]
        self.seq_buckets = tuple(sorted({int(b) for b in raw if 1 <= int(b) <= cap}))
        if not self.seq_buckets:
            raise ValueError(f"no usable seq bucket <= {cap}")

        #: optional serving/programs.py ProgramArtifactCache: while the
        #: engine is warming (recompile guard unarmed), unseen program
        #: signatures load serialized executables from the shared
        #: artifact root instead of paying the compile wall; once
        #: sealed the wrapper never touches disk again
        self.program_cache = program_cache
        #: warmup trace material: (family, outcome, t0, t1) per first
        #: compile / artifact load, drained by warmup() into the
        #: engine.warmup trace; the stashed trace flushes to the
        #: tracer's sink when one is attached (text.py attaches AFTER
        #: build — flush_warmup_trace() is the idempotent handoff)
        self._warm_events: list = []
        self._warmup_trace = None

        self._build_programs()
        self._init_pool()

        # host-side scheduler state
        self._queue: "queue.Queue[Request]" = queue.Queue()
        #: scheduler-owned waiting list (drained from _queue every cycle
        #: so cancelled entries are purged even while the pool is full)
        self._waiting: list[Request] = []
        self._slots: list[Optional[Request]] = [None] * num_slots
        self.prefix_cache = prefix_cache
        self.min_prefix = int(min_prefix)
        #: refcounted SHARED-PREFIX segments (the vLLM paged-KV capacity
        #: economy, r4 verdict missing #6): N concurrent requests with
        #: the same long prefix hold ONE immutable segment + N short
        #: suffix slots, instead of N full-length slots.  Configure the
        #: engine cfg's max_seq_len as the SUFFIX capacity and
        #: segment_len as the prefix capacity.
        self._seg_content: list[list[int]] = [
            [] for _ in range(prefix_segments)]
        self._seg_refs = np.zeros(max(prefix_segments, 1), np.int64)
        self._seg_used = np.zeros(max(prefix_segments, 1), np.float64)
        self._slot_plen = np.zeros(num_slots, np.int32)
        self._slot_seg = np.zeros(num_slots, np.int32)
        self.segment_hits = 0
        self.segment_tokens_shared = 0
        self.segment_evictions = 0
        #: segments planned into this admission cycle's batched suffix
        #: prefill — shielded from eviction until the dispatch lands
        self._seg_reserved: set[int] = set()
        #: tokens whose KV each physical slot currently holds at positions
        #: [0, len) — survives retirement (the KV stays in HBM) and resets
        #: on reuse; the prefix matcher's ground truth
        self._slot_content: list[list[int]] = [[] for _ in range(num_slots)]
        #: the request whose tokens may still append to a slot's content
        #: record (cleared on REUSE, not on retirement — late-arriving
        #: chunks of a retired request still wrote real KV)
        self._slot_owner: list[Optional[Request]] = [None] * num_slots
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self._active = np.zeros(num_slots, dtype=bool)
        self._positions = np.zeros(num_slots, dtype=np.int32)
        self._remaining = np.zeros(num_slots, dtype=np.int64)
        #: per-slot sampling knobs (the OpenAI per-request fields):
        #: temperature 0 = greedy; top_p 1 = off; top_k 0 = off
        self._temps = np.zeros(num_slots, dtype=np.float32)
        self._top_ps = np.ones(num_slots, dtype=np.float32)
        self._top_ks = np.zeros(num_slots, dtype=np.int32)
        #: per-slot residual ban (speculative decoding): when the last
        #: verify rejected draft g at a slot's front, the rejected
        #: candidate sample was discarded, so the next draw there must
        #: come from the residual distribution — the verify program
        #: masks this token out of the carried logits before sampling
        #: t1 (-1 = no ban; greedy slots are provably unaffected)
        self._spec_ban = np.full(num_slots, -1, dtype=np.int32)
        #: per-slot proposer backoff: a verify whose REAL drafts all
        #: rejected cost a (spec_k+1)-wide forward for one token, so the
        #: slot stops proposing for an exponentially growing cooldown
        #: (2 -> 4 -> ... -> 32 dispatches; any accept resets it).  This
        #: bounds the adversarial-traffic tax to a vanishing fraction of
        #: dispatches while leaving genuinely repetitive phases — where
        #: accepts keep the backoff at 0 — at full speculation.  Pure
        #: host heuristic over which GUESSES to offer: never affects
        #: correctness or greedy parity.
        self._spec_backoff = np.zeros(num_slots, dtype=np.int64)
        self._spec_cool = np.zeros(num_slots, dtype=np.int64)
        self.spec_tokens_proposed_total = 0
        self.spec_tokens_accepted_total = 0
        self.spec_dispatches_total = 0
        #: chunked-admission queue (prefill_budget > 0): [req, slot,
        #: prompt, next_offset] entries whose slot is RESERVED
        #: (self._slots[slot] is req) but not yet active — the head makes
        #: ``prefill_budget`` tokens of progress per dispatch, riding the
        #: fused step program whenever decode work is live
        from collections import deque

        self._prefilling: "deque[list]" = deque()
        #: (group_size, bucket) admission shapes known compiled —
        #: _pad_group pads bursts UP to one of these instead of
        #: compiling a fresh power-of-two shape mid-serving (a pool
        #: stall the jit_recompiles_total guard would count); padded
        #: rows target the dropped slot, so the waste is bounded
        #: prefill FLOPs, never correctness
        self._warm_plain: set = set()
        self._warm_seg: set = set()
        #: prompt tokens admitted-but-not-yet-prefilled, kept as a plain
        #: scheduler-maintained counter: stats() runs on HTTP threads and
        #: must not iterate a deque the scheduler mutates concurrently
        self._prefill_tokens_inflight = 0
        self.prefill_chunks_dispatched = 0
        #: host-observed ms the scheduler spent dispatching admission
        #: work while decode-able requests were live.  On async-dispatch
        #: backends this lower-bounds the true device-side stall (the
        #: monolithic prefill serializes on the device stream, not the
        #: host) — scripts/serving_bench.py's chunked-prefill row holds
        #: the measured device-level truth.
        self.decode_stall_ms_total = 0.0
        self.step_counter = 0          # decode dispatches so far
        self.tokens_emitted = 0        # useful (delivered) tokens
        #: tokens decoded for requests already EOS-retired — the price of
        #: dispatch-ahead pipelining (retirement lags ≤ pipeline_depth-1
        #: chunks); a measured cost, not a hidden one (r3 weak #4)
        self.tokens_discarded = 0
        self._error: Optional[Exception] = None
        self._stop = threading.Event()
        self._gate = threading.Lock()
        self._wake = threading.Event()
        #: per-step sampling keys are derived ON THE HOST as raw numpy
        #: uint32[2] key data ([seed, step] — distinct per step, exactly
        #: the structure PRNGKey builds).  The previous
        #: jax.random.fold_in per dispatch (a) put a device computation
        #: + implicit transfer on the hot path and (b) fed the decode
        #: programs a DEVICE key where warmup fed numpy, whose differing
        #: arg committedness re-traced decode+fused on the first live
        #: dispatch — the exact stall class the recompile guard exists
        #: to catch (it did, at 2 recompiles/engine).
        self._base_seed = int.from_bytes(os.urandom(4), "little")
        # The scheduler thread starts LAZILY on first submit(), not here:
        # warmup() mutates and donates the pool buffers, and an already-
        # running scheduler could race it over the same donated buffers
        # (two threads dispatching against one donated pool).  Deferred
        # start makes pool ownership single-threaded until real traffic.
        self._thread: Optional[threading.Thread] = None

    def _ensure_running(self) -> None:
        """Start the scheduler thread once (idempotent, called by submit
        under the gate)."""
        if self._thread is None:
            # traffic starts: from here every jit-cache growth past a
            # program's first compile is a mid-serving stall — count it
            # (warmup() also arms, for engines probed before traffic)
            self._recompiles.armed = True
            self._thread = threading.Thread(
                target=self._loop, name="continuous-engine", daemon=True)
            self._thread.start()
        if self._host_pool is not None and self._spill_thread is None:
            self._spill_thread = threading.Thread(
                target=self._host_tier_loop, name="kv-host-tier",
                daemon=True)
            self._spill_thread.start()

    # -- compiled programs -------------------------------------------------

    def _build_programs(self) -> None:
        cfg = self.cfg
        chunk = self.decode_chunk
        slots = self.num_slots
        mesh = self.mesh

        #: dispatch-hygiene auditor (analysis/runtime.py): every cached
        #: program is wrapped so jit-cache growth past its first compile
        #: counts here — a recompile in steady-state decode stalls every
        #: live request for a trace+compile, so the gauge must stay 0
        #: (tier-1 asserts it; /metrics exports jit_recompiles_total)
        self._recompiles = RecompileCounter()

        #: AOT artifact seam (serving/programs.py): every program is
        #: wrapped UNDER the guard.  With a cache, unseen signatures
        #: load/publish serialized executables while unsealed; without
        #: one, a WarmObserver just times first compiles so the
        #: engine.warmup trace gets per-family/rung spans either way.
        #: The seal predicate is the guard's armed flag, which flips
        #: before the scheduler starts — artifact I/O can never run on
        #: the dispatch thread.
        sealed = lambda: self._recompiles.armed  # noqa: E731
        if self.program_cache is not None:
            aot_base = programslib.cache_key_base(
                cfg, self.params, mesh,
                slots=slots, chunk=chunk,
                budget=self.prefill_budget, spec_k=self.spec_k,
                spec_ngram=self.spec_ngram, block=self.block_size,
                segments=self.prefix_segments, seglen=self.segment_len)

            def aot(p, family):
                return programslib.AotProgram(
                    p, cache=self.program_cache, key_base=aot_base,
                    family=family, sealed=sealed,
                    observer=self._note_warm)
        else:
            def aot(p, family):
                return programslib.WarmObserver(
                    p, family=family, sealed=sealed,
                    observer=self._note_warm)

        guard = lambda p, family: recompile_guard(aot(p, family), self._recompiles)  # noqa: E731

        #: decode-attention window buckets: each decode dispatch attends
        #: only over cache slots below the smallest bucket covering every
        #: live position (+ the chunk about to be generated) — the KV read
        #: is the decode step's HBM bill, and early conversation turns
        #: must not stream the whole max_seq_len buffer
        self.attend_buckets = tuple(
            [b for b in (128, 256, 512, 1024, 2048) if b < cfg.max_seq_len]
            + [cfg.max_seq_len])

        pool_proto = cache_shapes(cfg, slots)
        row_proto = cache_shapes(cfg, 1)
        # per-leaf batch axis, probed with batch=2 vs batch=1 so it stays
        # well-defined even when num_slots == 1 (cache_index has no batch
        # axis — it is informational and left untouched)
        probe_proto = cache_shapes(cfg, 2)

        def batch_axis(p, r):
            diff = [i for i, (a, b) in enumerate(zip(p.shape, r.shape)) if a != b]
            if not diff:
                return None
            if len(diff) != 1:
                raise RuntimeError(
                    f"ambiguous batch axis between {p.shape} and {r.shape}")
            return diff[0]

        self._pool_shapes = pool_proto
        self._batch_axes = jax.tree.map(batch_axis, probe_proto, row_proto)
        # seq-axis probe: vary max_seq_len and see which dim moves (k/v
        # keep seq after the slot axis; int8-KV scale buffers keep it
        # LAST — make_prefix_admit_program's mask needs the truth)
        import dataclasses as _dc

        seq_proto = cache_shapes(
            _dc.replace(cfg, max_seq_len=cfg.max_seq_len + 8), slots)
        self._seq_axes = jax.tree.map(batch_axis, seq_proto, pool_proto)

        self._prefill_programs: dict[int, Any] = {}

        def prefill_for(bucket: int):
            attend = next(b for b in self.attend_buckets if b >= bucket)
            if attend not in self._prefill_programs:
                self._prefill_programs[attend] = guard(make_prefill_program(
                    cfg, attend, mesh), f"prefill:{attend}")
            return self._prefill_programs[attend]

        self._prefill_for = prefill_for

        axes = self._batch_axes

        def merge(pool_cache, pool_logits, row_cache, row_logits, slots):
            """Scatter a BATCH of prefilled row caches + their next-token
            logits into the pool at ``slots`` [g].  Padded admission rows
            carry slot == num_slots, which mode="drop" discards — one
            merge dispatch admits a whole burst of requests."""
            def leaf(pool, row, axis):
                if axis is None:
                    return pool
                idx = (slice(None),) * axis + (slots,)
                return pool.at[idx].set(row, mode="drop")

            merged = jax.tree.map(leaf, pool_cache, row_cache, axes)
            return (shardedlib.constrain_cache(merged, mesh),
                    shardedlib.constrain_logits(
                        pool_logits.at[slots].set(row_logits, mode="drop"),
                        mesh))

        self._decode_programs: dict[int, Any] = {}

        def decode_for(needed: int):
            attend = next(
                (b for b in self.attend_buckets if b >= needed),
                cfg.max_seq_len)
            if attend not in self._decode_programs:
                self._decode_programs[attend] = guard(make_decode_program(
                    cfg, attend, chunk, mesh), f"decode:{attend}")
            return self._decode_programs[attend]

        self._decode_for = decode_for

        if self.prefill_budget > 0:
            budget = self.prefill_budget
            self._fused_programs: dict[int, Any] = {}
            self._chunk_programs: dict[int, Any] = {}

            def fused_for(needed: int):
                attend = next(
                    (b for b in self.attend_buckets if b >= needed),
                    cfg.max_seq_len)
                if attend not in self._fused_programs:
                    self._fused_programs[attend] = guard(make_fused_step_program(
                        cfg, attend, chunk, budget, self._batch_axes,
                        mesh), f"fused:{attend}")
                return self._fused_programs[attend]

            def chunk_prefill_for(needed: int):
                attend = next(
                    (b for b in self.attend_buckets if b >= needed),
                    cfg.max_seq_len)
                if attend not in self._chunk_programs:
                    self._chunk_programs[attend] = guard(
                        make_chunk_prefill_program(
                            cfg, attend, budget, self._batch_axes, mesh),
                        f"chunk_prefill:{attend}")
                return self._chunk_programs[attend]

            self._fused_for = fused_for
            self._chunk_prefill_for = chunk_prefill_for

        if self.spec_k > 0:
            spec_k = self.spec_k
            self._verify_programs: dict[int, Any] = {}

            def verify_for(needed: int):
                attend = next(
                    (b for b in self.attend_buckets if b >= needed),
                    cfg.max_seq_len)
                if attend not in self._verify_programs:
                    self._verify_programs[attend] = guard(
                        make_verify_program(cfg, attend, spec_k, mesh),
                        f"verify:{attend}")
                return self._verify_programs[attend]

            self._verify_for = verify_for

            if self.prefill_budget > 0:
                self._fused_verify_programs: dict[int, Any] = {}

                def fused_verify_for(needed: int):
                    attend = next(
                        (b for b in self.attend_buckets if b >= needed),
                        cfg.max_seq_len)
                    if attend not in self._fused_verify_programs:
                        self._fused_verify_programs[attend] = guard(
                            make_fused_verify_program(
                                cfg, attend, spec_k, self.prefill_budget,
                                self._batch_axes, mesh),
                            f"fused_verify:{attend}")
                    return self._fused_verify_programs[attend]

                self._fused_verify_for = fused_verify_for

        if self.prefix_segments > 0:
            import dataclasses as _dc

            # segment pool: a cache tree over its own (prefix-length)
            # config — bf16 regardless of quant_kv (the prefix arg feeds
            # the f32 attend math directly; int8 slots still compose)
            self._seg_cfg = _dc.replace(
                cfg, max_seq_len=self.segment_len, quant_kv=False)
            self._seg_shapes = cache_shapes(
                self._seg_cfg, self.prefix_segments)
            seg_row = cache_shapes(self._seg_cfg, 1)
            seg_probe = cache_shapes(self._seg_cfg, 2)
            self._seg_batch_axes = jax.tree.map(
                batch_axis, seg_probe, seg_row)
            self._seg_attends = tuple(
                [b for b in (128, 256, 512, 1024, 2048)
                 if b < self.segment_len] + [self.segment_len])

            self._seg_prefill_programs: dict[int, Any] = {}

            def seg_prefill_for(bucket: int):
                a = next(x for x in self._seg_attends if x >= bucket)
                if a not in self._seg_prefill_programs:
                    self._seg_prefill_programs[a] = guard(make_prefill_program(
                        self._seg_cfg, a, mesh), f"seg_prefill:{a}")
                return self._seg_prefill_programs[a]

            self._seg_prefill_for = seg_prefill_for

            seg_axes = self._seg_batch_axes

            def seg_merge(seg_cache, row_cache, rows):
                def leaf(pool, row, axis):
                    if axis is None:
                        return pool
                    idx = (slice(None),) * axis + (rows,)
                    return pool.at[idx].set(row, mode="drop")

                return shardedlib.constrain_cache(
                    jax.tree.map(leaf, seg_cache, row_cache, seg_axes),
                    mesh)

            self._seg_merge = guard(shardedlib.mesh_jit(
                mesh, seg_merge, donate_argnums=(0,)), "seg_merge")

            self._suffix_admit_programs: dict[tuple, Any] = {}

            def suffix_admit_for(attend: int, seg_att: int, bucket: int):
                a = next(
                    (b for b in self.attend_buckets if b >= attend),
                    cfg.max_seq_len)
                sa = next(x for x in self._seg_attends if x >= seg_att)
                k = (a, sa, bucket)
                if k not in self._suffix_admit_programs:
                    self._suffix_admit_programs[k] = guard(
                        make_suffix_admit_program(cfg, a, sa, bucket, mesh),
                        f"suffix_admit:{a}:{sa}:{bucket}")
                return self._suffix_admit_programs[k]

            self._suffix_admit_for = suffix_admit_for

            self._prefix_decode_programs: dict[tuple, Any] = {}

            def prefix_decode_for(needed: int, seg_att: int):
                a = next(
                    (b for b in self.attend_buckets if b >= needed),
                    cfg.max_seq_len)
                sa = next(x for x in self._seg_attends if x >= seg_att)
                k = (a, sa)
                if k not in self._prefix_decode_programs:
                    self._prefix_decode_programs[k] = guard(
                        make_prefix_decode_program(cfg, a, sa, chunk, mesh),
                        f"prefix_decode:{a}:{sa}")
                return self._prefix_decode_programs[k]

            self._prefix_decode_for = prefix_decode_for

        self._prefix_programs: dict[tuple[int, int], Any] = {}

        def prefix_admit_for(total_needed: int, suffix_bucket: int):
            attend = next(
                (b for b in self.attend_buckets if b >= total_needed),
                cfg.max_seq_len)
            key = (attend, suffix_bucket)
            if key not in self._prefix_programs:
                self._prefix_programs[key] = guard(make_prefix_admit_program(
                    cfg, attend, suffix_bucket, self._batch_axes, mesh,
                    seq_axes=self._seq_axes),
                    f"prefix_admit:{attend}:{suffix_bucket}")
            return self._prefix_programs[key]

        self._prefix_admit_for = prefix_admit_for

        def rung(needed: int) -> int:
            return next((b for b in self.attend_buckets if b >= needed),
                        cfg.max_seq_len)

        self._rung = rung

        if self.paged:
            # block pool: the same cache tree, rows = blocks and seq =
            # block_size; axes probed on it drive both gather and
            # scatter (k/v keep seq after the row axis, int8-KV scale
            # buffers keep it LAST — same layout truth as _seq_axes)
            bs = self.block_size
            bcfg = _dc.replace(cfg, max_seq_len=bs)
            self._block_pool_shapes = cache_shapes(bcfg, self.num_blocks)
            blk_row = cache_shapes(bcfg, 1)
            blk_probe = cache_shapes(bcfg, 2)
            self._block_axes = jax.tree.map(batch_axis, blk_probe, blk_row)
            blk_seqp = cache_shapes(
                _dc.replace(cfg, max_seq_len=bs + 8), self.num_blocks)
            self._block_seq_axes = jax.tree.map(
                batch_axis, blk_seqp, self._block_pool_shapes)
            paged_args = (bs, self._block_axes, self._block_seq_axes, mesh)

            self._paged_decode_programs: dict[int, Any] = {}
            self._paged_chunk_programs: dict[tuple, Any] = {}
            self._paged_fused_programs: dict[int, Any] = {}
            self._paged_verify_programs: dict[int, Any] = {}
            self._paged_fused_verify_programs: dict[int, Any] = {}

            def paged_decode_for(needed: int):
                a = rung(needed)
                if a not in self._paged_decode_programs:
                    self._paged_decode_programs[a] = guard(
                        make_paged_decode_program(cfg, a, chunk,
                                                  *paged_args),
                        f"paged_decode:{a}")
                return self._paged_decode_programs[a]

            def paged_chunk_for(needed: int, budget: int):
                a = rung(needed)
                k = (a, budget)
                if k not in self._paged_chunk_programs:
                    self._paged_chunk_programs[k] = guard(
                        make_paged_chunk_prefill_program(
                            cfg, a, budget, *paged_args),
                        f"paged_chunk:{a}:{budget}")
                return self._paged_chunk_programs[k]

            def paged_fused_for(needed: int):
                a = rung(needed)
                if a not in self._paged_fused_programs:
                    self._paged_fused_programs[a] = guard(
                        make_paged_fused_step_program(
                            cfg, a, chunk, self.prefill_budget,
                            *paged_args),
                        f"paged_fused:{a}")
                return self._paged_fused_programs[a]

            def paged_verify_for(needed: int):
                a = rung(needed)
                if a not in self._paged_verify_programs:
                    self._paged_verify_programs[a] = guard(
                        make_paged_verify_program(cfg, a, self.spec_k,
                                                  *paged_args),
                        f"paged_verify:{a}")
                return self._paged_verify_programs[a]

            def paged_fused_verify_for(needed: int):
                a = rung(needed)
                if a not in self._paged_fused_verify_programs:
                    self._paged_fused_verify_programs[a] = guard(
                        make_paged_fused_verify_program(
                            cfg, a, self.spec_k, self.prefill_budget,
                            *paged_args),
                        f"paged_fused_verify:{a}")
                return self._paged_fused_verify_programs[a]

            self._paged_decode_for = paged_decode_for
            self._paged_chunk_for = paged_chunk_for
            self._paged_fused_for = paged_fused_for
            self._paged_verify_for = paged_verify_for
            self._paged_fused_verify_for = paged_fused_verify_for
            self._block_copy = guard(
                make_block_copy_program(self._block_axes, mesh),
                "block_copy")
            # live KV migration (ISSUE 8): one-block gather/scatter at a
            # FIXED [1, 1] table shape — the host loops blocks, so one
            # compiled program each serves sequences of any length
            self._kv_export = guard(make_kv_export_program(
                self._block_axes, self._block_seq_axes, mesh),
                "kv_export")
            self._kv_import = guard(make_kv_import_program(
                self._block_axes, self._block_seq_axes, mesh),
                "kv_import")
            self._logits_take = guard(
                make_logits_take_program(mesh), "logits_take")
            self._logits_set = guard(
                make_logits_set_program(mesh), "logits_set")

        # logits dtype follows the model's activation dtype (bf16 on TPU;
        # the pool logits buffer must match or the decode scan carry
        # type-mismatches)
        self._logits_dtype = jax.eval_shape(
            lambda p, t: self.model.apply(
                {"params": p}, t), self.params,
            jax.ShapeDtypeStruct((1, self.seq_buckets[0]), jnp.int32),
        ).dtype

        # donate pool buffers: the pool cache must exist in HBM once, not
        # once per in-flight dispatch
        self._merge = guard(
            shardedlib.mesh_jit(mesh, merge, donate_argnums=(0, 1)),
            "merge")

    def _init_pool(self) -> None:
        mesh = self.mesh
        # paged engines allocate the BLOCK pool; the slot-shaped working
        # views are gathered per dispatch, never resident
        shapes = (self._block_pool_shapes if self.paged
                  else self._pool_shapes)
        self._pool_cache, self._pool_logits = shardedlib.mesh_jit(
            mesh,
            lambda: (
                shardedlib.constrain_cache(
                    jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                                 shapes),
                    mesh),
                shardedlib.constrain_logits(
                    jnp.zeros((self.num_slots, self.cfg.vocab_size),
                              self._logits_dtype),
                    mesh),
            ))()
        if self.prefix_segments > 0:
            self._seg_cache = shardedlib.mesh_jit(
                mesh,
                lambda: shardedlib.constrain_cache(
                    jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                                 self._seg_shapes),
                    mesh))()

    # -- public API --------------------------------------------------------

    def warmup(self, groups: Optional[list[tuple[int, int]]] = None) -> None:
        """Precompile the (admission-group, prompt-bucket) prefill/merge
        programs and the decode program so the first real burst doesn't
        pay compile time mid-request.  Warmup prefills merge into the
        out-of-range slot (dropped by the scatter) and the warmup decode
        runs with every slot inactive (cache writes dropped), so pool
        state is untouched for real traffic.

        ``groups``: list of (group_size, seq_bucket); default = group
        sizes 1 and num_slots at the smallest bucket.  Admission groups
        PAD UP to the nearest warmed group size (``_pad_group``) before
        falling back to a fresh power-of-two compile, so the default
        warm set already guarantees compile-free admission at the
        smallest bucket — warm more rungs to trade the padded rows'
        prefill FLOPs for load-time compiles.  ``attend_buckets``
        (optional): decode-window buckets to precompile; default = the
        windows the warmed prompt buckets will first decode in.

        Must run BEFORE the first submit(): the scheduler thread (started
        lazily by submit) and warmup would otherwise race over the same
        donated pool buffers.  The gate is held for the WHOLE body — a
        check-then-release would let a concurrent submit() start the
        scheduler mid-warmup and recreate the race; concurrent submitters
        instead block until warmup finishes, then proceed safely.
        """
        with self._gate:
            if self._thread is not None:
                raise RuntimeError(
                    "warmup() must run before the first submit(): the "
                    "scheduler thread owns the donated pool buffers once "
                    "traffic starts")
            self._warm_events = []
            tr = Trace(name="warmup", kind="engine")
            tr.phase("engine.warmup")
            try:
                self._warmup_locked(groups)
            finally:
                # per-family/rung compile + artifact-load spans, so the
                # compile wall shows up in /traces and
                # kft_phase_seconds like every other phase
                for family, outcome, t0, t1 in self._warm_events:
                    sp = tr.begin(f"warmup.{outcome}", family=family)
                    sp.start = t0
                    sp.done(at=t1)
                self._warm_events = []
                if self.program_cache is not None:
                    s = self.program_cache.stats()
                    tr.meta["aot_hits"] = s["aot_cache_hits_total"]
                    tr.meta["aot_misses"] = s["aot_cache_misses_total"]
                tr.finish()
                self._warmup_trace = tr
                self.flush_warmup_trace()
            # warmup's shape ladder is the paid-once warm set; growth
            # past it is a mid-serving recompile — start counting
            self._recompiles.armed = True

    def _note_warm(self, family: str, outcome: str, t0: float,
                   t1: float) -> None:
        """Observer for AotProgram/WarmObserver: one event per first
        compile or artifact load, pre-seal only (the wrappers stop
        calling once armed).  Capped below MAX_SPANS_PER_TRACE so the
        warmup trace never eats the shared dropped-span sentinel."""
        if len(self._warm_events) < 500:
            self._warm_events.append((family, outcome, t0, t1))

    def flush_warmup_trace(self) -> None:
        """Hand the stashed warmup trace to the tracer's sink.

        Idempotent, and callable at ANY point after warmup: the runtime
        attaches ``self.tracer`` only after the engine is built
        (text.py), so warmup stashes its trace and whoever attaches a
        tracer flushes it.  A flush with no tracer or no stash is a
        no-op.
        """
        tr, tracer = self._warmup_trace, self.tracer
        if tr is None or tracer is None:
            return
        self._warmup_trace = None
        tracer.sink.finish(tr)

    def _warmup_locked(self, groups) -> None:
        if groups is None:
            groups = [(1, self.seq_buckets[0]),
                      (self.num_slots, self.seq_buckets[0])]
        if self.paged:
            self._warmup_paged(groups)
            return
        # host args are NUMPY throughout: under a multi-process serving
        # mesh (the gang) a process-local device array cannot feed a
        # global-mesh jit — numpy inputs device_put as replicated on every
        # host identically (single-host: byte-identical behavior)
        warm_attends = set()
        for g, bucket in groups:
            bucket = next(b for b in self.seq_buckets if b >= bucket)
            if self.prefill_budget == 0:
                # the whole-prompt prefill + merge only serve plain
                # admission; a chunked engine never dispatches them —
                # compiling them would double warmup for dead programs
                row_logits, row_cache = self._prefill_for(bucket)(
                    self.params, np.zeros((g, bucket), np.int32),
                    np.ones(g, np.int32))
                self._pool_cache, self._pool_logits = self._merge(
                    self._pool_cache, self._pool_logits, row_cache,
                    row_logits, np.full(g, self.num_slots, np.int32))
                self._warm_plain.add((g, bucket))
            warm_attends.add(bucket + self.decode_chunk)
        for needed in sorted(warm_attends):
            self._pool_cache, self._pool_logits, toks = self._decode_for(
                needed)(
                self.params, self._pool_cache, self._pool_logits,
                np.full(self.num_slots, self.cfg.max_seq_len, np.int32),
                np.zeros(self.num_slots, bool),
                np.zeros(self.num_slots, np.float32),
                np.ones(self.num_slots, np.float32),
                np.zeros(self.num_slots, np.int32),
                np.asarray(jax.random.PRNGKey(0)))
            jax.block_until_ready(toks)
        if self.prefill_budget > 0 and warm_attends:
            # chunked admission climbs the attend ladder as the prompt
            # front advances (off + budget), so warm EVERY rung up to the
            # windows the warmed buckets imply — a mid-admission compile
            # is exactly the stall class chunked prefill exists to remove.
            # All targets are the out-of-range slot / inactive pool, so
            # every write drops and pool state is untouched.
            cover = next((a for a in self.attend_buckets
                          if a >= max(warm_attends)), self.cfg.max_seq_len)
            ptoks = np.zeros(self.prefill_budget, np.int32)
            sentinel = np.int32(self.num_slots)
            for attend in [a for a in self.attend_buckets if a <= cover]:
                self._pool_cache, self._pool_logits = (
                    self._chunk_prefill_for(attend)(
                        self.params, self._pool_cache, self._pool_logits,
                        sentinel, ptoks, np.int32(0), np.int32(1),
                        sentinel))
                self._pool_cache, self._pool_logits, toks = (
                    self._fused_for(attend)(
                        self.params, self._pool_cache, self._pool_logits,
                        sentinel, ptoks, np.int32(0), np.int32(1),
                        sentinel,
                        np.full(self.num_slots, self.cfg.max_seq_len,
                                np.int32),
                        np.zeros(self.num_slots, bool),
                        np.zeros(self.num_slots, np.float32),
                        np.ones(self.num_slots, np.float32),
                        np.zeros(self.num_slots, np.int32),
                        np.asarray(jax.random.PRNGKey(0))))
            jax.block_until_ready(toks)
        if self.spec_k > 0 and warm_attends:
            # speculation reads front + spec_k + 1 per dispatch, so it
            # climbs the attend ladder ahead of plain decode: warm EVERY
            # verify rung (and its fused-prefill sibling) up to the
            # windows the warmed buckets imply — a mid-serving verify
            # compile is exactly the stall jit_recompiles_total counts.
            # Every row is inactive (position = the max_seq_len
            # sentinel), so all writes drop and pool state is untouched.
            top = max(warm_attends) - self.decode_chunk + self.spec_k + 1
            cover = next((a for a in self.attend_buckets if a >= top),
                         self.cfg.max_seq_len)
            no_drafts = np.full((self.num_slots, self.spec_k), -1,
                                np.int32)
            no_ban = np.full(self.num_slots, -1, np.int32)
            parked = np.full(self.num_slots, self.cfg.max_seq_len,
                             np.int32)
            idle = (parked, np.zeros(self.num_slots, bool),
                    np.zeros(self.num_slots, np.float32),
                    np.ones(self.num_slots, np.float32),
                    np.zeros(self.num_slots, np.int32),
                    np.asarray(jax.random.PRNGKey(0)))
            for attend in [a for a in self.attend_buckets if a <= cover]:
                self._pool_cache, self._pool_logits, toks, _acc = (
                    self._verify_for(attend)(
                        self.params, self._pool_cache, self._pool_logits,
                        no_drafts, no_ban, *idle))
                if self.prefill_budget > 0:
                    sentinel = np.int32(self.num_slots)
                    self._pool_cache, self._pool_logits, toks, _acc = (
                        self._fused_verify_for(attend)(
                            self.params, self._pool_cache,
                            self._pool_logits, sentinel,
                            np.zeros(self.prefill_budget, np.int32),
                            np.int32(0), np.int32(1), sentinel,
                            no_drafts, no_ban, *idle))
            jax.block_until_ready(toks)
        if self.prefix_segments > 0:
            # warm the SEGMENT path (creation prefill, batched suffix
            # admit, prefix decode) — the first same-prefix burst must
            # not stall the whole pool on mid-serving compiles.  All
            # targets are out of range (row prefix_segments, slot
            # num_slots) or inactive, so every write drops.
            sb = self.seq_buckets[0]
            # warm the WHOLE segment attend ladder, not just the largest
            # bucket: a burst sharing an 896-token prefix uses the 1024
            # bucket, and a mid-serving compile there stalls the whole
            # pool — the exact class warmup exists to remove.  Operators
            # who cannot afford the load-time compiles (3 programs per
            # ladder entry) opt out with warmup_groups=[].
            for sa in self._seg_attends:
                self._seg_cache = self._seg_merge(
                    self._seg_cache,
                    self._seg_prefill_for(sa)(
                        self.params, np.zeros((1, sa), np.int32),
                        np.ones(1, np.int32))[1],
                    np.full(1, self.prefix_segments, np.int32))
                # warm the suffix admit + merge at group sizes 1 AND
                # num_slots: seg bursts pad to a warmed group shape
                # (_pad_group), so both ends of the pad ladder must be
                # compiled or a same-prefix burst freezes the pool on a
                # mid-serving [g, sb] compile (the recompile guard
                # counts exactly that)
                for g in sorted({1, self.num_slots}):
                    row_logits, row_cache = self._suffix_admit_for(
                        sb, sa, sb)(
                        self.params, self._seg_cache,
                        np.zeros((g, sb), np.int32),
                        np.zeros(g, np.int32), np.full(g, sa, np.int32),
                        np.ones(g, np.int32))
                    self._pool_cache, self._pool_logits = self._merge(
                        self._pool_cache, self._pool_logits, row_cache,
                        row_logits, np.full(g, self.num_slots, np.int32))
                    self._warm_seg.add((g, sb))
                self._pool_cache, self._pool_logits, toks = (
                    self._prefix_decode_for(sb + self.decode_chunk, sa)(
                        self.params, self._pool_cache, self._pool_logits,
                        self._seg_cache,
                        np.full(self.num_slots, self.cfg.max_seq_len,
                                np.int32),
                        np.zeros(self.num_slots, np.int32),
                        np.zeros(self.num_slots, np.int32),
                        np.zeros(self.num_slots, bool),
                        np.zeros(self.num_slots, np.float32),
                        np.ones(self.num_slots, np.float32),
                        np.zeros(self.num_slots, np.int32),
                        np.asarray(jax.random.PRNGKey(0))))
            jax.block_until_ready(toks)
        if self.prefix_cache:
            # warm the prefix-admit programs for the warmed prompt buckets
            # (a repeated prompt otherwise pays this compile mid-request —
            # exactly the latency the prefix cache exists to remove).  A
            # prompt of ANY length L <= bucket admits with total
            # (L-1) + suffix_bucket, so cover every attend bucket up to
            # the worst case, not just one key.  The warmup targets the
            # out-of-range slot; every scatter drops.
            sb = self.seq_buckets[0]
            warm_totals = set()
            for _, bucket in groups:
                b = next(x for x in self.seq_buckets if x >= bucket)
                top = b - 1 + sb  # worst-case admission total
                cover = next((a for a in self.attend_buckets if a >= top),
                             self.cfg.max_seq_len)
                warm_totals.update(
                    a for a in self.attend_buckets if a <= cover)
            for total in sorted(warm_totals):
                program = self._prefix_admit_for(total, sb)
                self._pool_cache, self._pool_logits = program(
                    self.params, self._pool_cache, self._pool_logits,
                    np.int32(self.num_slots), np.int32(self.num_slots),
                    np.int32(1), np.zeros(sb, np.int32), np.int32(1))

    def _warmup_paged(self, groups) -> None:
        """Paged warm ladder: every attend rung the warmed prompt
        buckets imply gets its decode (+ fused/chunk/verify siblings)
        compiled against an all-sentinel block table — gathers clip,
        scatters drop, every row is inactive, so pool state is
        untouched.  Prefix-hit suffix admissions at rungs above the cold
        set compile lazily on first use, which the recompile guard
        counts as that program's warm entry, not a re-trace."""
        warm_attends = set()
        for g, bucket in groups:
            bucket = next(b for b in self.seq_buckets if b >= bucket)
            warm_attends.add(bucket + self.decode_chunk)
        if not warm_attends:
            return
        top = max(warm_attends)
        if self.spec_k > 0:
            top = max(top, max(warm_attends) - self.decode_chunk
                      + self.spec_k + 1)
        cover = self._rung(top)
        pad = self._alloc.pad_block
        sent = np.int32(self.num_slots)
        # migration gather/scatter (ISSUE 8) warm FIRST: kv_import /
        # logits_set donate and REWRITE the pool buffers, and their
        # output sharding signature is what every later live dispatch
        # receives as input.  Warming them after the attend ladder left
        # the ladder's programs traced against _init_pool's signature
        # (PartitionSpec() vs the constraint's PartitionSpec(None, ...):
        # equivalent layouts, unequal cache keys), so a MESHED engine's
        # first live decode re-traced — exactly the mid-serving stall
        # class the recompile guard exists to catch (it did, ISSUE 10).
        # Live imports feed NUMPY leaves (the wire hands us host bytes),
        # so warmup must too — device-typed warmup args re-traced decode
        # programs once before (the r7 sampling-key lesson).
        grp = np.full((KV_MIGRATE_GROUP, 1), pad, np.int32)
        leaves = jax.device_get(self._kv_export(self._pool_cache, grp))
        zeros = tuple(np.zeros(np.shape(x), np.asarray(x).dtype)
                      for x in leaves)
        self._pool_cache = self._kv_import(self._pool_cache, grp, zeros)
        row = np.asarray(jax.device_get(self._logits_take(
            self._pool_logits, np.int32(self.num_slots))))
        self._pool_logits = self._logits_set(
            self._pool_logits, np.zeros_like(row), np.int32(self.num_slots))
        # logits_set is fed BOTH arg kinds in production: numpy rows on
        # the import path (wire bytes) and the device row stashed at
        # freeze time on the resume path — warm both committedness
        # combos or the first in-place resume re-traces mid-serving
        # (the r7 lesson, third sighting)
        dev_row = self._logits_take(self._pool_logits,
                                    np.int32(self.num_slots))
        self._pool_logits = self._logits_set(
            self._pool_logits, dev_row, np.int32(self.num_slots))
        idle = (np.zeros(self.num_slots, np.int32),
                np.zeros(self.num_slots, bool),
                np.zeros(self.num_slots, np.float32),
                np.ones(self.num_slots, np.float32),
                np.zeros(self.num_slots, np.int32),
                np.asarray(jax.random.PRNGKey(0)))
        no_drafts = np.full((self.num_slots, max(self.spec_k, 1)), -1,
                            np.int32)
        no_ban = np.full(self.num_slots, -1, np.int32)
        toks = None
        for a in [x for x in self.attend_buckets if x <= cover]:
            nblk = -(-a // self.block_size)
            bt = np.full((self.num_slots, nblk), pad, np.int32)
            row = np.full((1, nblk), pad, np.int32)
            self._pool_cache, self._pool_logits, toks = (
                self._paged_decode_for(a)(
                    self.params, self._pool_cache, self._pool_logits,
                    bt, *idle))
            if self.prefill_budget > 0:
                ptoks = np.zeros(self.prefill_budget, np.int32)
                self._pool_cache, self._pool_logits = (
                    self._paged_chunk_for(a, self.prefill_budget)(
                        self.params, self._pool_cache, self._pool_logits,
                        row, ptoks, np.int32(0), np.int32(1), sent))
                self._pool_cache, self._pool_logits, toks = (
                    self._paged_fused_for(a)(
                        self.params, self._pool_cache, self._pool_logits,
                        bt, sent, ptoks, np.int32(0), np.int32(1), sent,
                        *idle))
            if self.spec_k > 0:
                self._pool_cache, self._pool_logits, toks, _acc = (
                    self._paged_verify_for(a)(
                        self.params, self._pool_cache, self._pool_logits,
                        bt, no_drafts, no_ban, *idle))
                if self.prefill_budget > 0:
                    ptoks = np.zeros(self.prefill_budget, np.int32)
                    self._pool_cache, self._pool_logits, toks, _acc = (
                        self._paged_fused_verify_for(a)(
                            self.params, self._pool_cache,
                            self._pool_logits, bt, sent, ptoks,
                            np.int32(0), np.int32(1), sent, no_drafts,
                            no_ban, *idle))
        if self.prefill_budget == 0:
            # monolithic paged admission: one chunk covers the whole
            # prompt/suffix, programs keyed (rung, bucket) — warm the
            # cold-admission pair per bucket
            for bucket in [b for b in self.seq_buckets if b <= cover]:
                a = self._rung(bucket)
                row = np.full((1, -(-a // self.block_size)), pad,
                              np.int32)
                self._pool_cache, self._pool_logits = (
                    self._paged_chunk_for(a, bucket)(
                        self.params, self._pool_cache, self._pool_logits,
                        row, np.zeros(bucket, np.int32), np.int32(0),
                        np.int32(1), sent))
        if self.prefix_cache:
            # the COW fork dispatch (dst out of range: dropped)
            self._pool_cache = self._block_copy(
                self._pool_cache, np.int32(0), np.int32(pad))
        if toks is not None:
            jax.block_until_ready(toks)

    def submit(
        self, prompt: list[int], max_new_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        top_p: Optional[float] = None, top_k: Optional[int] = None,
        priority: Optional[int] = None, trace=None,
        session_id: Optional[str] = None,
    ) -> Request:
        req = Request(
            prompt=list(map(int, prompt)),
            # explicit None check: 0 is a real request ("no completion",
            # OpenAI max_tokens=0) and must not fall through to the default
            max_new_tokens=int(
                self.default_max_new_tokens
                if max_new_tokens is None else max_new_tokens),
            temperature=(None if temperature is None else float(temperature)),
            top_p=(None if top_p is None else float(top_p)),
            top_k=(None if top_k is None else int(top_k)),
            priority=(1 if priority is None else int(priority)),
            trace=trace,
            session_id=(None if session_id is None else str(session_id)),
        )
        if trace is not None:
            # the queue-wait phase opens HERE and closes when the
            # scheduler reserves a slot (_admit) — the admission queue
            # is the first engine-side stall cause the trace attributes
            trace.phase("engine.queue", prompt_tokens=len(req.prompt))
        req.submitted_step = self.step_counter
        with self._gate:
            if self._error is not None:
                raise RuntimeError(
                    f"engine failed: {self._error!r}") from self._error
            if self._stop.is_set():
                raise RuntimeError("engine is shutting down")
            self._queue.put(req)
            self._ensure_running()
        self._wake.set()
        return req

    def generate(self, prompt: list[int], max_new_tokens: Optional[int] = None,
                 timeout: float = 120.0,
                 temperature: Optional[float] = None,
                 top_p: Optional[float] = None,
                 top_k: Optional[int] = None) -> list[int]:
        return self.submit(prompt, max_new_tokens, temperature,
                           top_p=top_p, top_k=top_k).wait(timeout)

    def stats(self) -> dict:
        """Engine observability snapshot (exported as Prometheus gauges
        by the model server's /metrics)."""
        if self.paged:
            a = self._alloc
            allocated = a.num_blocks - a.free_blocks
            # analysis: ok host-sync-in-dispatch — host token lists
            live_tokens = sum(
                len(self._slot_content[s]) for s in range(self.num_slots)
                if self._slot_blocks[s])
            host = (self._host_pool.stats() if self._host_pool is not None
                    else {"kv_blocks_host_tier": 0, "kv_host_bytes": 0,
                          "kv_host_capacity_blocks": 0,
                          "kv_host_spills_total": 0,
                          "kv_host_restores_total": 0,
                          "kv_host_evictions_total": 0})
            paged = {
                **a.stats(),
                # hierarchical KV tiers (ISSUE 12): host-RAM mirror
                # occupancy + spill/thaw traffic across ALL downward/
                # upward tier transitions (host AND storage), the
                # storage tier's verify failures (a torn spill detected
                # at thaw — re-prefilled, never served), and the
                # cluster-visible hibernated-session census
                **host,
                "kv_spills_total": self.kv_spills_total,
                "kv_thaws_total": self.kv_thaws_total,
                "kv_thaws_degraded_total": self.kv_thaws_degraded_total,
                "kv_spill_verify_failures_total": (
                    self.spill_store.verify_failures_total
                    if self.spill_store is not None else 0),
                "kv_sessions_hibernated": (
                    self.spill_store.session_count()
                    if self.spill_store is not None else 0),
                # reserved-but-unwritten span across live tables: the
                # block economy's internal fragmentation + upfront
                # worst-case commitment, as a ratio of allocated bytes
                "kv_fragmentation_ratio": (
                    0.0 if allocated == 0 else round(max(
                        0.0, 1.0 - live_tokens
                        / (allocated * self.block_size)), 4)),
                # zero-leaked-blocks invariant (analysis/runtime.py
                # BlockLedger): blocks still referenced at a quiesce
                # boundary that no live slot holds; 0 without a ledger
                # attached (nothing audited = nothing claimed)
                "kv_blocks_leaked_total": (
                    self.block_ledger.leaked_total
                    if self.block_ledger is not None else 0),
            }
        else:
            paged = {
                "kv_block_size": 0, "kv_blocks_total": 0,
                "kv_blocks_free": 0, "kv_blocks_cow_copies_total": 0,
                "prefix_block_hits_total": 0,
                "kv_fragmentation_ratio": 0.0,
                "kv_blocks_leaked_total": 0,
                "kv_blocks_host_tier": 0, "kv_host_bytes": 0,
                "kv_host_capacity_blocks": 0, "kv_host_spills_total": 0,
                "kv_host_restores_total": 0,
                "kv_host_evictions_total": 0,
                "kv_spills_total": 0, "kv_thaws_total": 0,
                "kv_thaws_degraded_total": 0,
                "kv_spill_verify_failures_total": 0,
                "kv_sessions_hibernated": 0,
            }
        return {
            **paged,
            "slots_capacity": self.num_slots,
            # analysis: ok host-sync-in-dispatch — _active is the HOST numpy slot table, not a device value
            "slots_live": int(self._active.sum()),
            "queue_depth": len(self._waiting) + self._queue.qsize(),
            "decode_steps": self.step_counter,
            "tokens_emitted": self.tokens_emitted,
            "tokens_discarded": self.tokens_discarded,
            "prefill_budget": self.prefill_budget,
            "prefill_chunks_dispatched": self.prefill_chunks_dispatched,
            "prefill_tokens_inflight": self._prefill_tokens_inflight,
            "decode_stall_ms_total": round(self.decode_stall_ms_total, 3),
            # speculative decoding (ISSUE 4): drafts offered vs accepted
            # by the verifier, and how many pool dispatches speculated
            "spec_tokens_proposed_total": self.spec_tokens_proposed_total,
            "spec_tokens_accepted_total": self.spec_tokens_accepted_total,
            "spec_dispatches_total": self.spec_dispatches_total,
            "spec_acceptance_rate": round(
                self.spec_tokens_accepted_total
                / max(self.spec_tokens_proposed_total, 1), 4),
            # live KV migration (ISSUE 8): sequences IMPORTED by this
            # engine (one count per migration — the exporting side's
            # outbound view is the latency histogram count), payload
            # bytes both directions, failures counted by the
            # orchestrating layer, and the export->ack latency
            # histogram (cumulative buckets, Prometheus-style)
            "kv_migrations_total": self.kv_migrations_total,
            "kv_migrate_bytes_total": self.kv_migrate_bytes_total,
            "kv_migrate_failures_total": self.kv_migrate_failures_total,
            **self._migration_histogram(),
            # dispatch hygiene (analysis/runtime.py recompile_guard):
            # jit-cache growth past each program's first compile; MUST
            # stay 0 in steady state — a recompile stalls the whole pool
            "jit_recompiles_total": int(self._recompiles.count),
            # AOT program-artifact cache (serving/programs.py): warmup
            # hit/miss economics + store size; zeros when no cache is
            # configured so dashboards keep one shape either way
            **(self.program_cache.stats() if self.program_cache
               is not None else {
                   "aot_cache_hits_total": 0,
                   "aot_cache_misses_total": 0,
                   "aot_cache_load_failures_total": 0,
                   "aot_cache_published_total": 0,
                   "aot_cache_bytes_read_total": 0,
                   "aot_cache_bytes_written_total": 0,
                   "aot_cache_entries": 0,
                   "aot_cache_bytes": 0,
               }),
            "prefix_hits": self.prefix_hits,
            "prefix_tokens_saved": self.prefix_tokens_saved,
            "segments_capacity": self.prefix_segments,
            "segments_live": int(sum(
                1 for c in self._seg_content if c)),
            "segment_hits": self.segment_hits,
            "segment_tokens_shared": self.segment_tokens_shared,
            "segment_evictions": self.segment_evictions,
        }

    def _migration_histogram(self) -> dict:
        out = {}
        cum = 0
        for b, c in zip(self._mig_buckets, self._mig_lat_counts):
            cum += c
            out[f"kv_migrate_latency_ms_bucket_le_{b:g}"] = cum
        cum += self._mig_lat_counts[-1]
        out["kv_migrate_latency_ms_bucket_le_inf"] = cum
        out["kv_migrate_latency_ms_count"] = cum
        out["kv_migrate_latency_ms_sum"] = round(self._mig_lat_sum, 3)
        return out

    def stop(self) -> None:
        with self._gate:
            self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._spill_thread is not None:
            # the host-tier worker drains its queue then exits (a spill
            # dispatched before stop still lands in the pool — tests
            # audit the tier at this boundary)
            self._spill_thread.join(timeout=10)
            self._spill_thread = None
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.error = RuntimeError("engine shut down")
            req.done.set()
        for req in self._waiting:
            if not req.done.is_set():
                req.error = RuntimeError("engine shut down")
                req.done.set()
        self._waiting.clear()
        for req in self._slots:
            if req is not None and not req.done.is_set():
                req.error = RuntimeError("engine shut down")
                req.done.set()
        self._fail_migration_waiters(RuntimeError("engine shut down"))
        if self.block_ledger is not None and self._alloc is not None:
            # terminal boundary audit: blocks still referenced that no
            # slot owns are leaks even when the engine dies — the gauge
            # must say so before the allocator is garbage
            self._audit_blocks_now()

    # -- scheduler loop ----------------------------------------------------

    def _admit(self) -> None:
        """Prefill queued requests into free slots (between decode chunks).

        Admissions are BATCHED: waiting requests group by prompt bucket and
        each group runs as one multi-row prefill + one multi-slot merge —
        a burst of 8 requests costs 2 dispatches, not 16 (each dispatch
        pays the remote-dispatch latency floor, PERF.md)."""
        # drain the cross-thread queue into the scheduler-owned waiting
        # list and purge cancellations NOW — a cancelled entry must not
        # linger (inflating queue_depth) just because the pool is full
        while True:
            try:
                self._waiting.append(self._queue.get_nowait())
            except queue.Empty:
                break
        self._waiting = [r for r in self._waiting
                         if not r.cancelled.is_set()]
        # QoS priority admission (serving/traffic.py): better tiers
        # admit first; the sort is STABLE, so the default all-tier-1
        # traffic keeps exact FIFO order (a no-op for every deployment
        # without QoS), and FIFO holds within each tier
        self._waiting.sort(key=lambda r: r.priority)
        free = [i for i, r in enumerate(self._slots) if r is None]
        taken: list[tuple[Request, int]] = []  # (req, slot)
        plans: list[tuple] = []                # paged: parallel to taken
        deferred: list[Request] = []
        while free and self._waiting:
            req = self._waiting.pop(0)
            # budget the KV cache: prompt + generated tokens must fit
            # max_seq_len — writes past it are silently dropped by the
            # per-row scatter and decode would return garbage from a
            # frozen cache (the same guard LlamaGenerator applies at load)
            if req.max_new_tokens >= self.cfg.max_seq_len:
                req.max_new_tokens = self.cfg.max_seq_len - 1
            if not req.prompt:
                # empty prompt -> empty continuation (runtimes.py rule)
                req.done.set()
                continue
            if (self.admission_policy is not None
                    and not self.admission_policy(req)):
                # policy says not now (e.g. the tier ladder's class
                # quota is full): defer without consuming a slot —
                # later waiters of other classes may still admit
                deferred.append(req)
                continue
            if self.paged:
                plan = self._plan_paged(req)
                if plan is None:
                    # pool-exhaustion backpressure: the request WAITS
                    # for blocks instead of evicting someone mid-decode
                    # (unless _plan_paged FAILED it outright — a span no
                    # empty pool could ever host must not re-queue)
                    if not req.done.is_set():
                        deferred.append(req)
                    continue
                plans.append(plan)
            slot = free.pop(0)
            # reserve immediately so admission_policy / later planning
            # in this same cycle sees the occupancy
            self._slots[slot] = req
            if req.trace is not None:
                # queue wait ends at slot reservation; prefill begins
                req.trace.phase("engine.prefill", slot=slot,
                                queue_depth=len(self._waiting))
            taken.append((req, slot))
        if deferred:
            self._waiting = deferred + self._waiting
        if not taken:
            return
        if self.paged:
            self._admit_paged(taken, plans)
            return
        # SHARED-SEGMENT routing sees the FULL prompt (legacy truncation
        # below caps it to the slot length — which for a suffix-slot pool
        # is exactly what segments exist to avoid); then legacy
        # prefix-cache routing: a prompt sharing >= min_prefix tokens
        # with some slot's live KV admits via on-device copy + suffix
        # prefill (src == dst is the conversation-continues case)
        grouped: list[tuple[Request, list[int], int]] = []
        seg_groups: dict[int, list] = {}  # bucket -> [(req, slot, seg, blen, suffix)]
        # host-observed admission-dispatch time while decode work is live
        # (the decode_stall_ms_total gauge — see its __init__ note)
        stall_t0 = time.perf_counter()
        # analysis: ok host-sync-in-dispatch — host numpy scheduler state
        had_live = bool(self._active.any())
        dispatched = False
        for req, slot in taken:
            if self.prefix_segments > 0:
                try:
                    plan = self._plan_segment(req)
                except Exception as e:  # noqa: BLE001 — fail this request
                    req.error = e
                    req.done.set()
                    continue
                if plan is not None:
                    seg, blen, suffix, _created = plan
                    bucket = next(
                        b for b in self.seq_buckets if b >= len(suffix))
                    seg_groups.setdefault(bucket, []).append(
                        (req, slot, seg, blen, suffix))
                    continue
            cap = min(self.seq_buckets[-1],
                      self.cfg.max_seq_len - req.max_new_tokens)
            prompt = req.prompt[-cap:]  # left-truncate, keep the tail
            src, lp = (self._best_prefix(prompt)
                       if self.prefix_cache else (-1, 0))
            # with chunked admission on, the prefix route is only taken
            # when its monolithic suffix prefill fits the per-dispatch
            # budget — a barely-matching long prompt must not sneak an
            # unbounded prefill past the stall bound (the common chat
            # continuation resends the whole conversation plus one short
            # turn, so the reuse that matters survives this guard)
            if (src < 0 or lp < self.min_prefix
                    or (self.prefill_budget > 0
                        and len(prompt) - lp > self.prefill_budget)):
                grouped.append((req, prompt, slot))
                continue
            try:
                self._admit_with_prefix(req, prompt, slot, src, lp)
                dispatched = True
            except Exception as e:  # noqa: BLE001 — fail this request only
                req.error = e
                req.done.set()
        # batched segment admissions: one multi-row suffix prefill + one
        # merge per bucket group (the 2-dispatches-per-burst rule holds
        # for the segment path too); pad rows carry plen 0 / slot
        # num_slots, which the masks and the merge scatter drop
        for bucket, members in seg_groups.items():
            g = self._pad_group(len(members), bucket, self._warm_seg)
            try:
                toks = np.zeros((g, bucket), np.int32)
                seg_ids = np.zeros(g, np.int32)
                plens = np.zeros(g, np.int32)
                slens = np.ones(g, np.int32)
                slots = np.full(g, self.num_slots, np.int32)
                max_blen = 1
                for j, (req, slot, seg, blen, suffix) in enumerate(members):
                    toks[j, : len(suffix)] = suffix
                    seg_ids[j] = seg
                    plens[j] = blen
                    slens[j] = len(suffix)
                    slots[j] = slot
                    max_blen = max(max_blen, blen)
                program = self._suffix_admit_for(bucket, max_blen, bucket)
                row_logits, row_cache = program(
                    self.params, self._seg_cache, toks, seg_ids, plens,
                    slens)
                self._pool_cache, self._pool_logits = self._merge(
                    self._pool_cache, self._pool_logits, row_cache,
                    row_logits, slots)
                for req, slot, seg, blen, suffix in members:
                    self._occupy(req, req.prompt, slot, plen=blen, seg=seg,
                                 local_len=len(suffix))
                dispatched = True
            except Exception as e:  # noqa: BLE001 — fail this group only
                for req, *_ in members:
                    req.error = e
                    req.done.set()
        self._seg_reserved.clear()
        if self.prefill_budget > 0:
            # CHUNKED admission (the stall-free path): reserve the slot
            # now, prefill ``prefill_budget`` tokens per dispatch from the
            # scheduler loop — fused into the decode dispatch whenever
            # decode work is live — and activate on the final chunk.  The
            # prefix-cache and segment routes above still run first: a
            # matching prefix admits in one cheap suffix dispatch either
            # way.
            for req, prompt, slot in grouped:
                self._slots[slot] = req
                self._slot_content[slot] = []  # grows as chunks land
                self._slot_owner[slot] = None  # set by _occupy when live
                self._prefilling.append([req, slot, list(prompt), 0])
                self._prefill_tokens_inflight += len(prompt)
            if had_live and dispatched:
                self.decode_stall_ms_total += (
                    time.perf_counter() - stall_t0) * 1e3
            return
        groups: dict[int, list[tuple[Request, list[int], int]]] = {}
        for req, prompt, slot in grouped:
            bucket = next(b for b in self.seq_buckets if b >= len(prompt))
            groups.setdefault(bucket, []).append((req, prompt, slot))
        for bucket, members in groups.items():
            g = self._pad_group(len(members), bucket, self._warm_plain)
            try:
                toks = np.zeros((g, bucket), np.int32)
                lengths = np.ones(g, np.int32)
                slots = np.full(g, self.num_slots, np.int32)
                for j, (req, prompt, slot) in enumerate(members):
                    toks[j, : len(prompt)] = prompt
                    lengths[j] = len(prompt)
                    slots[j] = slot
                row_logits, row_cache = self._prefill_for(bucket)(
                    self.params, toks, lengths)
                self._pool_cache, self._pool_logits = self._merge(
                    self._pool_cache, self._pool_logits,
                    row_cache, row_logits, slots)
                for req, prompt, slot in members:
                    self._occupy(req, prompt, slot)
                dispatched = True
            except Exception as e:  # noqa: BLE001 — fail this group only
                for req, _, _ in members:
                    req.error = e
                    req.done.set()
        if had_live and dispatched:
            self.decode_stall_ms_total += (
                time.perf_counter() - stall_t0) * 1e3

    def _pad_group(self, need: int, bucket: int, warmed: set) -> int:
        """Admission group size for ``need`` members at ``bucket``.

        Prefer padding UP to a group shape already compiled (warmup's
        defaults, or any shape a previous burst compiled): the padded
        rows' prefill runs against the dropped slot, costing bounded
        FLOPs, whereas a fresh power-of-two compile freezes the whole
        pool for the trace+compile — the stall class the recompile
        guard (jit_recompiles_total) exists to surface.  With nothing
        warm at this bucket, fall back to the classic power-of-two pad
        and record it (compiled once = warm from now on)."""
        cands = [g for (g, b) in warmed if b == bucket and g >= need]
        if cands:
            return min(cands)
        g = 1
        while g < need:
            g *= 2
        g = min(g, self.num_slots)
        warmed.add((g, bucket))
        return g

    def _occupy(self, req: Request, prompt: list[int], slot: int, *,
                plen: int = 0, seg: int = 0,
                local_len: Optional[int] = None) -> None:
        self._slots[slot] = req
        self._active[slot] = True
        if req.trace is not None:
            # prefill (or import) ends at activation; decode begins
            req.trace.phase("engine.decode", slot=slot)
        # positions are SLOT-LOCAL: = global for plain slots, suffix
        # length for segment-backed ones
        self._positions[slot] = (
            local_len if local_len is not None else len(prompt))
        self._remaining[slot] = req.max_new_tokens
        self._temps[slot] = (self.temperature if req.temperature is None
                             else req.temperature)
        self._top_ps[slot] = 1.0 if req.top_p is None else req.top_p
        self._top_ks[slot] = 0 if req.top_k is None else req.top_k
        self._spec_ban[slot] = -1  # residual bans do not cross occupants
        self._spec_backoff[slot] = 0
        self._spec_cool[slot] = 0
        if plen > 0:
            self._slot_plen[slot] = plen
            self._slot_seg[slot] = seg
            self._seg_refs[seg] += 1
            self._seg_used[seg] = time.monotonic()
            # a segment-backed slot's KV sits at OFFSET positions — the
            # legacy slot-copy prefix matcher must never match it
            self._slot_content[slot] = []
            self._slot_owner[slot] = None
        else:
            self._slot_content[slot] = list(prompt)
            self._slot_owner[slot] = req
        req.slot = slot
        req.admitted_step = self.step_counter

    def _release_seg(self, slot: int) -> None:
        """Drop a freed slot's segment reference (refcounted sharing)."""
        if self.prefix_segments > 0 and self._slot_plen[slot] > 0:
            self._seg_refs[self._slot_seg[slot]] -= 1
            self._slot_plen[slot] = 0
            self._slot_seg[slot] = 0

    def _create_segment(self, tokens: list[int]) -> int:
        """Prefill ``tokens`` into a free (or evictable refcount-0 LRU)
        segment row; returns the row index or -1 when the pool is full of
        referenced segments (caller falls back to legacy admission)."""
        free = [i for i, c in enumerate(self._seg_content) if not c]
        if not free:
            evictable = [
                i for i in range(self.prefix_segments)
                if self._seg_refs[i] == 0 and self._seg_content[i]
                and i not in self._seg_reserved]
            if not evictable:
                return -1
            victim = min(evictable, key=lambda i: self._seg_used[i])
            self._seg_content[victim] = []
            self.segment_evictions += 1
            free = [victim]
        seg = free[0]
        bucket = next(b for b in self._seg_attends if b >= len(tokens))
        toks = np.zeros((1, bucket), np.int32)
        toks[0, : len(tokens)] = tokens
        _, row_cache = self._seg_prefill_for(bucket)(
            self.params, toks, np.asarray([len(tokens)], np.int32))
        self._seg_cache = self._seg_merge(
            self._seg_cache, row_cache, np.asarray([seg], np.int32))
        self._seg_content[seg] = list(tokens)
        self._seg_used[seg] = time.monotonic()
        return seg

    def _plan_segment(self, req: Request) -> Optional[tuple]:
        """Segment routing decision for one request: (seg, blen, suffix,
        hit) or None (caller falls through to the legacy paths).  May
        CREATE a segment (one prefill dispatch); the reservation set
        keeps segments planned this admission cycle from being evicted
        before their batched suffix prefill lands."""
        prompt = req.prompt
        cap = len(prompt) - 1  # >= 1 suffix token must run for logits
        # longest-common-prefix match: segment KV at positions < lcp
        # depends only on tokens < lcp (causal), so ANY prompt sharing
        # those tokens may attend that much of the segment — one segment
        # serves every variation on a system prompt
        best, blen = -1, 0
        # analysis: ok host-sync-in-dispatch — host token list, no device value
        p_arr = np.asarray(prompt, np.int64)
        for i, content in enumerate(self._seg_content):
            if min(len(content), cap) <= blen:
                continue
            lcp = _lcp(content, p_arr, cap)
            if lcp > blen:
                best, blen = i, lcp
        def feasible(bl: int) -> bool:
            # the FULL requested generation must fit the suffix slot —
            # shrinking max_new here would make token counts depend on
            # cache state (segment hit vs miss); infeasible plans fall
            # back to the legacy path, which truncates the PROMPT and
            # preserves max_new like every non-segment engine
            sfx = len(prompt) - bl
            return (0 < sfx <= self.seq_buckets[-1]
                    and sfx + req.max_new_tokens <= self.cfg.max_seq_len - 1)

        created = False
        if blen < self.min_prefix and cap >= self.min_prefix:
            # too little shared with ANY segment (a 1-token BOS overlap
            # must not block a new prompt from getting its own segment).
            # Feasibility is checked BEFORE the creation prefill: an
            # abandoned plan must not burn a dispatch + a segment row.
            want = min(self.segment_len, cap)
            if want >= self.min_prefix and feasible(want):
                made = self._create_segment(prompt[:want])
                if made >= 0:
                    best, blen, created = made, want, True
        if best < 0 or blen < self.min_prefix or not feasible(blen):
            return None
        suffix = prompt[blen:]
        self._seg_reserved.add(best)
        if not created:
            self.segment_hits += 1
            self.segment_tokens_shared += blen
        return best, blen, suffix, created

    def _plan_paged(self, req: Request) -> Optional[tuple]:
        """Paged admission plan: (prompt, start, table, cow_src,
        shared_n) with the request's FULL worst-case block span
        (prompt + max_new_tokens) reserved up front, or None when the
        free list cannot host it (backpressure — nothing is held).

        Prefix reuse at BLOCK granularity: full blocks of the best
        matching live/retired sequence are shared by refcount (zero
        copy, zero prefill); a match ending mid-block forks the
        boundary block with one COW dispatch so the suffix prefill
        starts at the exact divergence point."""
        bs = self.block_size
        cap = min(self.seq_buckets[-1],
                  self.cfg.max_seq_len - req.max_new_tokens)
        prompt = req.prompt[-cap:]  # left-truncate, keep the tail
        total = len(prompt) + req.max_new_tokens
        nb_total = -(-total // bs)
        if nb_total > self._alloc.num_blocks:
            # structurally impossible: even an EMPTY pool cannot host
            # this request's worst-case span — fail it now (deferring
            # would park it forever and busy-spin an idle scheduler)
            req.error = RuntimeError(
                f"request needs {nb_total} KV blocks but the pool has "
                f"{self._alloc.num_blocks} (num_blocks too small for "
                f"prompt + max_new_tokens = {total} at block_size {bs})")
            req.done.set()
            return None
        start, shared, cow_src, restore = 0, [], None, None
        if self.prefix_cache:
            blocks, lcp = self._paged_match(prompt)
            lcp = min(lcp, len(prompt) - 1)
            if lcp >= self.min_prefix:
                nfull = lcp // bs
                shared = [int(b) for b in blocks[:nfull]]
                start = nfull * bs
                if lcp > start and nfull < len(blocks):
                    # COW fork: copy the partially-matching boundary
                    # block into the first fresh block, then prefill
                    # only from the true divergence point
                    cow_src = int(blocks[nfull])
                    start = lcp
            if self._host_pool is not None:
                # host-tier restore (ISSUE 12): a DEEPER prefix than
                # any HBM-resident match may survive in host RAM —
                # scattering it back (~memcpy) beats re-prefilling the
                # same tokens.  Full blocks only; the restored blocks
                # are fresh allocations the admission scatter fills.
                hid, hlcp = self._host_pool.match(
                    # analysis: ok host-sync-in-dispatch — host token list, no device value
                    np.asarray(prompt, np.int64), len(prompt) - 1)
                hstart = (hlcp // bs) * bs
                if hstart > start and hstart >= self.min_prefix:
                    shared, cow_src = [], None
                    start = hstart
                    restore = (hid, hstart // bs)
        # pin shared blocks OUT of the free list before allocating —
        # alloc must never hand a block we are about to share
        self._alloc.ref(shared)
        fresh = self._alloc.alloc(nb_total - len(shared))
        if fresh is None:
            self._alloc.release(shared)
            return None
        if shared:
            self._alloc.prefix_block_hits_total += len(shared)
        return prompt, start, shared + fresh, cow_src, len(shared), restore

    def _paged_match(self, prompt: list[int]) -> tuple[tuple, int]:
        """(blocks, lcp): the best block-backed prefix source for this
        prompt — live slots' content records first, then the
        allocator's retired-sequence registry (freed-but-unreused
        blocks: the free list doubling as the prefix cache)."""
        cap = len(prompt) - 1
        if cap <= 0:
            return (), 0
        # analysis: ok host-sync-in-dispatch — host token list, no device value
        p = np.asarray(prompt, np.int64)
        best_blocks: tuple = ()
        best = 0
        for s in range(self.num_slots):
            content, blocks = self._slot_content[s], self._slot_blocks[s]
            if not blocks or min(len(content), cap) <= best:
                continue
            lcp = _lcp(content, p, cap)
            if lcp > best:
                best_blocks, best = tuple(blocks), lcp
        reg_blocks, reg_lcp = self._alloc.match(p, cap)
        if reg_lcp > best:
            best_blocks, best = reg_blocks, reg_lcp
        return best_blocks, best

    def _admit_paged(self, taken, plans) -> None:
        """Install the planned admissions: blocks are reserved; fork COW
        boundaries on-device; enqueue the chunked prefill.  Paged
        admission is ALWAYS chunk-driven — with ``prefill_budget == 0``
        a single chunk covers the whole remainder (the monolithic
        admission bound, unchanged from the legacy path)."""
        stall_t0 = time.perf_counter()
        # analysis: ok host-sync-in-dispatch — host numpy scheduler state
        had_live = bool(self._active.any())
        dispatched = False
        for (req, slot), plan in zip(taken, plans):
            prompt, start, table, cow_src, shared_n, restore = plan
            if restore is not None:
                hid, nfull = restore
                host_blk = self._host_pool.take(hid, nfull)
                if host_blk is None or len(host_blk) < nfull:
                    # evicted between match and take: prefill everything
                    start = 0
                else:
                    self._scatter_host_blocks(table[:nfull], host_blk)
                    with self._tier_mu:
                        self.kv_thaws_total += 1
                    dispatched = True
            if cow_src is not None:
                try:
                    self._pool_cache = self._block_copy(
                        self._pool_cache, np.int32(cow_src),
                        np.int32(table[shared_n]))
                    self._alloc.cow_copies_total += 1
                    if req.trace is not None:
                        # the COW fork is a named cost on the trace
                        req.trace.begin(
                            "kv.cow", src=int(cow_src),
                            dst=int(table[shared_n])).done()
                    dispatched = True
                except Exception as e:  # noqa: BLE001 — fail THIS
                    # request only (the legacy fail-this-group contract);
                    # a GangEngine publish failure set _error: re-raise
                    # so the gang goes fatal instead of diverging
                    req.error = e
                    req.done.set()
                    self._slots[slot] = None
                    self._alloc.release(table)
                    if self._error is not None:
                        raise
                    continue
            self._slot_blocks[slot] = table
            if self.block_ledger is not None:
                # per-sequence ledger attribution: a leak report names
                # the owning slot + admission path, not just a block id
                self.block_ledger.annotate(self._alloc, table,
                                           f"slot{slot}:admit")
            # the shared prefix IS real KV content at [0, start) — the
            # prefix matcher's ground truth from the first chunk on
            self._slot_content[slot] = list(prompt[:start])
            self._slot_owner[slot] = None
            self._prefilling.append([req, slot, list(prompt), start])
            self._prefill_tokens_inflight += len(prompt) - start
            if start > 0:
                self.prefix_hits += 1
                self.prefix_tokens_saved += start
        if had_live and dispatched:
            self.decode_stall_ms_total += (
                time.perf_counter() - stall_t0) * 1e3

    def _block_tables(self, attend: int) -> np.ndarray:
        """[num_slots, nblk] dispatch block tables for an attend rung —
        host numpy assembled fresh per dispatch (never mutated after),
        padded with the allocator's out-of-range sentinel (gathers
        clip, scatters drop)."""
        nblk = -(-attend // self.block_size)
        bt = np.full((self.num_slots, nblk), self._alloc.pad_block,
                     np.int32)
        for s, blocks in enumerate(self._slot_blocks):
            if blocks:
                m = min(len(blocks), nblk)
                bt[s, :m] = blocks[:m]
        return bt

    def _retire_slot(self, slot: int) -> None:
        """Free a slot for reuse: scheduler state, the segment ref and —
        paged — the block table.  Refcount-zero blocks join the free
        list UNCLEARED with the sequence registered, so a future prompt
        sharing this conversation's prefix resurrects them instead of
        re-prefilling (reuse costs a dict pop, never a clearing
        dispatch)."""
        self._slots[slot] = None
        self._active[slot] = False
        self._remaining[slot] = 0
        self._migrating.pop(slot, None)
        self._release_seg(slot)
        if self.paged and self._slot_blocks[slot]:
            blocks = self._slot_blocks[slot]
            if self.prefix_cache:
                self._alloc.register(self._slot_content[slot], blocks)
                # host-tier spill (ISSUE 12): under free-list pressure
                # this retirement's registration is about to be
                # cannibalized — DISPATCH the gathers now (device
                # ordering guarantees they read today's bytes even if
                # the blocks are reallocated before the fetch lands);
                # the host-tier worker materializes off-thread
                self._maybe_spill_host(slot, blocks)
            self._alloc.release(blocks)
            self._slot_blocks[slot] = []

    # -- hierarchical KV tiers (ISSUE 12) ----------------------------------
    #
    # HBM -> host RAM -> manifest-verified storage.  The spill unit is
    # the PR 6 block; the spill wire format is the PR 7 export_sequence
    # snapshot.  Thread contract (the mailbox seam, mechanically pinned
    # by the analyzer's *Tier/*Spill/*Hibernate roots): the SCHEDULER
    # only dispatches gathers/scatters and walks host dicts; every
    # device->host fetch and every byte of file/socket I/O runs on a
    # host-tier worker or the hibernating caller's thread.

    def _maybe_spill_host(self, slot: int, blocks: list) -> None:
        """Scheduler-side spill decision + gather DISPATCH for a
        retiring sequence's full blocks (host-tier admission runs on
        the worker thread)."""
        hp = self._host_pool
        if hp is None:
            return
        if self._alloc.free_blocks >= self._host_watermark_blocks:
            return  # no pressure: the HBM free-list cache retains it
        content = self._slot_content[slot]
        nfull = min(len(content) // self.block_size, len(blocks))
        if nfull == 0:
            return
        toks = list(content[: nfull * self.block_size])
        if hp.contains_prefix(toks, min_tokens=len(toks)):
            return  # already held: re-spilling would churn the LRU
        ids = [int(b) for b in blocks[:nfull]]
        groups = []
        for i in range(0, len(ids), KV_MIGRATE_GROUP):
            grp = ids[i:i + KV_MIGRATE_GROUP]
            bt = np.full((KV_MIGRATE_GROUP, 1), self._alloc.pad_block,
                         np.int32)
            bt[:len(grp), 0] = grp
            groups.append((self._kv_export(self._pool_cache, bt),
                           len(grp)))
        self._spill_q.put((toks, groups))

    def _host_tier_loop(self) -> None:
        """Host-tier worker: materialize dispatched spill gathers
        (device->host fetch OFF the scheduler thread) and admit them to
        the HostBlockPool."""
        while not (self._stop.is_set() and self._spill_q.empty()):
            try:
                toks, groups = self._spill_q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                t0 = time.perf_counter()
                host_blocks = []
                for leaves, valid in groups:
                    host = [np.asarray(x) for x in jax.device_get(leaves)]
                    for j in range(valid):
                        host_blocks.append([x[j:j + 1] for x in host])
                if self._host_pool.put(toks, host_blocks) >= 0:
                    with self._tier_mu:
                        self.kv_spills_total += 1
                    if self.tracer is not None:
                        # engine-level phase with no request trace: the
                        # spill happens after retirement, but its cost
                        # lands in the same phase histograms a scrape
                        # reads (worker thread — never the scheduler)
                        self.tracer.sink.observe_phase(
                            "kv.host_spill", time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001 — a failed spill only
                # costs the cache entry (the HBM registry still holds
                # the prefix until reallocation); the tier must never
                # take the engine down
                log.debug("host-tier spill failed: %s", e)

    def attach_spill_store(self, store) -> None:
        """Attach the storage tier (:class:`~.storage.KvSpillStore`) —
        hibernate/thaw default to it and ``stats()`` surfaces its
        verify-failure and hibernated-session gauges."""
        self.spill_store = store

    def idle_sessions(self, idle_s: float,
                      now: Optional[float] = None) -> list:
        """Live session-bound sequences whose token stream has been
        quiet for ``idle_s`` — the idle-session reaper's probe
        (ISSUE 15).  GIL ``list()``-copy read of the slot table (the
        EnginePreemptor pattern); the decision is double-checked by
        ``hibernate_sequence``'s own mailbox export, so a sequence that
        wakes between probe and export just exports at its current
        (fresh) position or reports nothing-to-do.  Only sequences with
        a ``session_id`` qualify: an anonymous request has no durable
        identity to thaw under."""
        now = time.perf_counter() if now is None else now
        out = []
        for req in list(self._slots):
            if req is None or req.done.is_set():
                continue
            if not getattr(req, "session_id", None):
                continue
            if now - req.last_token_at >= float(idle_s):
                out.append(req)
        return out

    def hibernate_sequence(self, req: Request, session_id: str,
                           store=None, timeout: float = 60.0) -> bool:
        """Spill a live sequence to the storage tier and retire it
        (ISSUE 12): the PR 7 export snapshot is written through the
        manifest-verified :class:`~.storage.KvSpillStore` (atomic
        tmp+fsync+rename, per-file hashes), then the slot is released —
        its blocks return to the free list still prefix-registered.
        The request HANDLE stays unresolved (the session is parked
        durable); ``thaw_sequence`` — on THIS engine, or on any replica
        sharing the store — resumes it bit-identically.

        Crash discipline is copy-then-cutover lifted to the storage
        tier: a spill that dies mid-write publishes nothing and the
        sequence resumes decoding in place.  Runs on the CALLER's
        thread (device fetch + file I/O) — never call from scheduler
        context.  Returns False when the request already finished."""
        store = store or self.spill_store
        if store is None:
            raise RuntimeError("no spill store attached "
                               "(attach_spill_store)")
        t0 = time.perf_counter()
        if req.trace is not None:
            req.trace.phase("kv.hibernate", session=session_id)
        snap = self.export_sequence(req, timeout)
        if snap is None:
            return False
        toks = [int(t) for t in snap["prompt"]] + \
            [int(t) for t in snap.get("generated", ())]
        wsp = (req.trace.begin("kv.spill_write")
               if req.trace is not None else None)
        try:
            store.write(session_id, snap,
                        block_keys=_block_keys(toks, self.block_size))
        except Exception:
            # nothing published (atomic rename never ran): the source
            # still owns the sequence — resume in place, exactly-once
            if wsp is not None:
                wsp.set(error=True).done()
            try:
                self.resume_sequence(req, timeout)
            except (RuntimeError, TimeoutError):
                pass
            raise
        if wsp is not None:
            wsp.done()
        self.release_sequence(req, timeout)
        with self._tier_mu:
            self.kv_spills_total += 1
        if self.tracer is not None:
            self.tracer.sink.observe_phase(
                "kv.hibernate", time.perf_counter() - t0,
                req.trace.trace_id if req.trace is not None else "")
        return True

    def thaw_sequence(self, session_id: str, store=None,
                      req: Optional[Request] = None,
                      timeout: float = 60.0) -> tuple[Request, dict]:
        """Resume a hibernated session from the storage tier (any
        replica sharing the store).  When the controller attached a
        ``thaw_gate`` (``autoscale.thaw_concurrency``, ISSUE 16) the
        thaw waits its turn there first — a domain outage thaws its
        dead half's sessions in a herd, and an uncapped herd of
        import_sequence scatters would starve live decode.  Returns
        ``(req, info)``:

        - verified payload -> ``import_sequence`` scatters the spilled
          blocks and decoding resumes at the exact position,
          bit-identical greedy to the uninterrupted run;
        - torn/corrupt payload (manifest hash mismatch) -> NEVER
          scattered: the session re-prefills from the manifest's token
          record (``info["degraded"] = True``, same greedy tokens, KV
          recomputed; a pending stochastic spec-ban is dropped);
        - unreadable manifest -> :class:`~.storage.SpillCorrupt`.

        ``info["tokens"]`` carries the tokens generated BEFORE
        hibernation (the session transcript the API handle already
        delivered).  The spill entry is consumed on success."""
        gate = getattr(self, "thaw_gate", None)
        if gate is not None:
            with gate:
                return self._thaw_sequence_gated(
                    session_id, store, req, timeout)
        return self._thaw_sequence_gated(session_id, store, req, timeout)

    def _thaw_sequence_gated(self, session_id: str, store=None,
                             req: Optional[Request] = None,
                             timeout: float = 60.0
                             ) -> tuple[Request, dict]:
        store = store or self.spill_store
        if store is None:
            raise RuntimeError("no spill store attached "
                               "(attach_spill_store)")
        t0 = time.perf_counter()
        if req is not None and req.trace is not None:
            req.trace.phase("kv.thaw", session=session_id)
        snap, ok = store.read(session_id)
        prior = [int(t) for t in snap.get("generated", ())]
        if ok:
            new_req = self.import_sequence(snap, req=req, timeout=timeout)
        else:
            prompt = [int(t) for t in snap["prompt"]]
            remaining = int(snap["remaining"]) \
                if snap.get("phase") == "decode" \
                else int(snap["max_new_tokens"])
            # the handle's budget counts DELIVERED tokens (delivery
            # retires at len(req.tokens) >= max_new_tokens), and the
            # prior transcript rides the handle — so the budget is
            # prior + remainder, while the SNAPSHOT's max_new_tokens
            # below stays the remainder (it sizes the block span on
            # top of the re-prefilled prompt)
            if req is None:
                req = Request(
                    prompt=prompt,
                    max_new_tokens=len(prior) + remaining,
                    temperature=snap.get("temperature"),
                    top_p=snap.get("top_p"), top_k=snap.get("top_k"),
                    priority=int(snap.get("priority", 1)))
                req.tokens = list(prior)
            else:
                # nothing else owns the parked handle while hibernated:
                # _occupy reads req.max_new_tokens at activation
                req.max_new_tokens = len(prior) + remaining
            re_snap = {
                "v": 1, "phase": "prefill",
                "block_size": self.block_size,
                # prompt + prior generation re-prefill as one prompt:
                # recomputing their KV from tokens is the same math the
                # chunked-prefill parity bar pins, so the continuation
                # stays greedy bit-identical
                "prompt": prompt + prior, "generated": [],
                "position": 0, "remaining": remaining,
                "max_new_tokens": remaining,
                "temperature": snap.get("temperature"),
                "top_p": snap.get("top_p"), "top_k": snap.get("top_k"),
                "priority": int(snap.get("priority", 1)),
                "spec_ban": -1, "blocks": [],
            }
            new_req = self.import_sequence(re_snap, req=req,
                                           timeout=timeout)
            with self._tier_mu:
                self.kv_thaws_degraded_total += 1
        store.delete(session_id)
        with self._tier_mu:
            self.kv_thaws_total += 1
        if self.tracer is not None:
            self.tracer.sink.observe_phase(
                "kv.thaw", time.perf_counter() - t0,
                new_req.trace.trace_id
                if new_req.trace is not None else "")
        return new_req, {"degraded": not ok, "tokens": prior,
                         "session": session_id}

    def export_prefix_blocks(self, tokens: list[int],
                             timeout: float = 60.0
                             ) -> tuple[list[int], list]:
        """(covered_tokens, host block leaf-lists) for the longest
        full-block prefix of ``tokens`` this engine's pool holds (live
        slots or the free-list-as-cache registry) — the serving side of
        the cluster block registry's peer fetch (a cold replica imports
        this instead of recomputing a hot prefix).  Gathers are
        dispatched on the scheduler; the fetch happens HERE on the
        caller's thread."""
        if not self.paged:
            raise RuntimeError("prefix export requires the paged pool")
        out = self._post_migration_op("export_prefix",
                                      [int(t) for t in tokens], None,
                                      timeout)
        blocks = []
        for leaves, valid in out.get("blocks_dev", ()):
            host = [np.asarray(x) for x in jax.device_get(leaves)]
            for j in range(valid):
                blocks.append([x[j:j + 1] for x in host])
        return out.get("covered", []), blocks

    def install_prefix(self, tokens: list[int], blocks: list,
                       timeout: float = 60.0) -> bool:
        """Install a fetched prefix (host block leaf-lists, one per
        FULL block of ``tokens``) into this pool's registry: alloc,
        scatter, register, release — the blocks land on the free list
        content-registered, so the next same-prefix admission shares
        them instead of prefilling (prefill-once-per-cluster).  False
        when the pool has no room (never evicts live sequences)."""
        if not self.paged:
            raise RuntimeError("prefix install requires the paged pool")
        out = self._post_migration_op(
            "install_prefix", [int(t) for t in tokens], blocks, timeout)
        return bool(out.get("ok"))

    def _mig_export_prefix(self, tokens: list[int], out: dict) -> None:
        """Scheduler body: match + dispatch grouped gathers (no fetch)."""
        blocks, lcp_n = self._paged_match_full(tokens)
        nfull = lcp_n // self.block_size
        ids = [int(b) for b in blocks[:nfull]]
        groups = []
        for i in range(0, len(ids), KV_MIGRATE_GROUP):
            grp = ids[i:i + KV_MIGRATE_GROUP]
            bt = np.full((KV_MIGRATE_GROUP, 1), self._alloc.pad_block,
                         np.int32)
            bt[:len(grp), 0] = grp
            groups.append((self._kv_export(self._pool_cache, bt),
                           len(grp)))
        out["covered"] = tokens[: nfull * self.block_size]
        out["blocks_dev"] = groups

    def _paged_match_full(self, tokens: list[int]) -> tuple[tuple, int]:
        """Like _paged_match but UNCAPPED (a prefix export may cover
        the whole token record — there is no suffix to prefill here)."""
        cap = len(tokens)
        if cap == 0:
            return (), 0
        # analysis: ok host-sync-in-dispatch — host token list, no device value
        p = np.asarray(tokens, np.int64)
        best_blocks: tuple = ()
        best = 0
        for s in range(self.num_slots):
            content, blocks = self._slot_content[s], self._slot_blocks[s]
            if not blocks or min(len(content), cap) <= best:
                continue
            n = _lcp(content, p, cap)
            if n > best:
                best_blocks, best = tuple(blocks), n
        reg_blocks, reg_n = self._alloc.match(p, cap)
        if reg_n > best:
            best_blocks, best = reg_blocks, reg_n
        return best_blocks, best

    def _scatter_host_blocks(self, ids: list, blocks: list) -> None:
        """Grouped scatter of host block leaf-lists into pool blocks
        ``ids`` (scheduler thread; pure dispatch — the leaves are
        already host numpy).  Shared by the host-tier restore, the
        registry prefix install, and nothing else: one write path, one
        warmed program (``_kv_import``)."""
        G = KV_MIGRATE_GROUP
        for i in range(0, len(blocks), G):
            grp = blocks[i:i + G]
            bt = np.full((G, 1), self._alloc.num_blocks, np.int32)
            bt[:len(grp), 0] = [int(ids[i + j])
                                for j in range(len(grp))]
            leaves = []
            for li in range(len(grp[0])):
                # analysis: ok host-sync-in-dispatch — host numpy leaves
                parts = [np.asarray(b[li]) for b in grp]
                stack = np.concatenate(parts, axis=0)
                if len(grp) < G:
                    stack = np.concatenate(
                        [stack, np.zeros(
                            (G - len(grp),) + stack.shape[1:],
                            stack.dtype)], axis=0)
                leaves.append(stack)
            self._pool_cache = self._kv_import(
                self._pool_cache, bt, tuple(leaves))

    def prefix_census(self, timeout: float = 30.0) -> list:
        """Copies of every block-registered token record (live slots +
        the free-list registry), taken at a scheduler boundary — the
        /metrics block-registry probe hashes these OFF-thread into
        ``kft_kv_prefix_key`` rows (paged.prefix_digest).  Empty when
        the scheduler has not started (no traffic = no content; a
        metrics scrape must not start the pool)."""
        if not self.paged or self._thread is None:
            return []
        try:
            out = self._post_migration_op("prefix_census", None, None,
                                          timeout)
        except (RuntimeError, TimeoutError):
            return []
        return out.get("tokens", [])

    def _mig_prefix_census(self, out: dict) -> None:
        records = []
        for s in range(self.num_slots):
            content = self._slot_content[s]
            if self._slot_blocks[s] and len(content) >= self.block_size:
                # analysis: ok host-sync-in-dispatch — host token list copy
                records.append(np.asarray(content, np.int64))
        for toks, blocks in self._alloc._seqs.values():
            # analysis: ok host-sync-in-dispatch — registry token copy, host numpy
            records.append(np.asarray(
                toks[: len(blocks) * self.block_size], np.int64))
        out["tokens"] = records

    def _mig_install_prefix(self, tokens: list[int], blocks: list,
                            out: dict) -> None:
        """Scheduler body: alloc + grouped scatter + register/release."""
        n = min(len(blocks), len(tokens) // self.block_size)
        if n == 0:
            out["ok"] = False
            return
        table = self._alloc.alloc(n)
        if table is None:
            out["ok"] = False  # no room: never evict live sequences
            return
        self._scatter_host_blocks(table, blocks[:n])
        if self.block_ledger is not None:
            self.block_ledger.annotate(self._alloc, table,
                                       "registry:install_prefix")
        self._alloc.register(tokens[: n * self.block_size], table)
        self._alloc.release(table)
        with self._tier_mu:
            self.kv_thaws_total += 1
        out["ok"] = True

    # -- live KV migration (ISSUE 8) ---------------------------------------
    #
    # The transferable unit is PR 6's paged block: export gathers a
    # sequence's written blocks device->host, import allocs + scatters
    # them on the destination, and the scheduler state (position,
    # remaining budget, sampling knobs, next-token logits row) rides
    # along — the destination resumes at the exact position with
    # bit-identical greedy tokens.  Discipline is COPY-THEN-CUTOVER:
    # export freezes the slot but frees NOTHING; only release (after
    # the destination acks) retires it, and resume un-freezes after a
    # failed transfer.  All pool/scheduler mutation runs on the
    # scheduler thread via the mailbox; the device->host fetch and any
    # socket streaming run on the CALLER's thread (the analyzer's
    # blocking-socket rule pins that split).

    def export_sequence(self, req: Request,
                        timeout: float = 60.0) -> Optional[dict]:
        """Copy step: snapshot ``req``'s live KV + scheduler state.

        Freezes the slot at a chunk boundary (in-flight dispatches are
        drained first) and returns a host snapshot dict — block bytes
        as numpy leaves, ready for :meth:`import_sequence` or the gang
        channel's ``kv_migrate`` framing.  Returns None when the
        request already finished (nothing to migrate).  The source
        sequence stays intact and decodable until
        :meth:`release_sequence`."""
        if not self.paged:
            raise RuntimeError(
                "KV migration requires the paged pool (block_size > 0)")
        xsp = (req.trace.begin("kv.export")
               if req.trace is not None else None)
        out = self._post_migration_op("export", req, None, timeout)
        snap = out.get("snap")
        if snap is None:
            if xsp is not None:
                xsp.set(empty=True).done()
            return None
        # device->host materialization on the CALLER's thread: the
        # scheduler only dispatched the (grouped) gathers.  Each group
        # leaf is row-major [G, ...]; slice the valid rows back into
        # per-block leaf lists (the wire frames stay per-block)
        nbytes = 0
        blocks = []
        for leaves, valid in snap.pop("blocks_dev"):
            host = [np.asarray(x) for x in jax.device_get(leaves)]
            for j in range(valid):
                blk = [x[j:j + 1] for x in host]
                nbytes += sum(x.nbytes for x in blk)
                blocks.append(blk)
        snap["blocks"] = blocks
        ld = snap.pop("logits_dev", None)
        if ld is not None:
            row = np.asarray(jax.device_get(ld))
            nbytes += row.nbytes
            snap["logits"] = row
        self.kv_migrate_bytes_total += nbytes
        if xsp is not None:
            xsp.done(blocks=len(blocks), bytes=nbytes)
            # the context rides the snapshot so a WIRE destination (a
            # fresh-handle import on another process) can continue the
            # same trace — in-process imports share the handle and need
            # nothing
            snap["trace"] = req.trace.wire_context()
        return snap

    def import_sequence(self, snapshot: dict, req: Optional[Request] = None,
                        timeout: float = 60.0, hold: bool = False) -> Request:
        """Cutover step: install an exported sequence into this pool.

        Allocates the sequence's full remaining worst-case block span
        (admission semantics: exhaustion is a raised rejection, never a
        partial hold — the source then resumes in place), scatters the
        received blocks, installs the logits row and scheduler state,
        and resumes decoding at the exact position.  ``req`` re-targets
        an existing Request (in-process handoff: the front server's
        handle keeps streaming, no client reconnect); None builds a
        fresh one from the snapshot (cross-process import).

        ``hold=True`` installs the sequence FROZEN (blocks scattered,
        state recorded, but the slot stays inactive until
        :meth:`resume_sequence`): the elastic gang resize (ISSUE 10)
        imports every live conversation into the new-degree pool while
        the old-degree pool still owns them — only the atomic cutover
        flips which side decodes, so a resize that dies mid-commit can
        discard the held copies with zero duplicated tokens."""
        if not self.paged:
            raise RuntimeError(
                "KV migration requires the paged pool (block_size > 0)")
        if snapshot is None:
            raise ValueError(
                "snapshot is None — the sequence had already finished "
                "on the source (export_sequence returned None)")
        isp = (req.trace.begin("kv.import",
                               blocks=len(snapshot.get("blocks", ())),
                               hold=hold)
               if req is not None and req.trace is not None else None)
        try:
            out = self._post_migration_op("import", snapshot, (req, hold),
                                          timeout)
        except Exception as e:
            if isp is not None:
                isp.set(error=str(e)).done()
            raise
        if isp is not None:
            isp.done()
        return out["req"]

    def take_waiting(self, timeout: float = 60.0) -> list:
        """Atomically withdraw every queued-but-unadmitted request (the
        resize cutover hands them to the new-degree engine, ISSUE 10).
        Runs on the scheduler thread like every state-mutating
        migration op — the waiting list is scheduler-owned."""
        return self._post_migration_op("take_waiting", None, None,
                                       timeout)["reqs"]

    def quiesced_live_requests(self, timeout: float = 60.0) -> list:
        """Scheduler-thread snapshot of every admitted, unfinished
        request (the resize export set).  Taken through the migration
        mailbox so it lands AFTER any in-flight admission cycle: a
        request racing the quiesce policy swap must end up in the
        export set, not stranded in a slot the cutover's stop() then
        fails (the mailbox services at the loop top, after the racing
        cycle's slot assignments are visible and before any cycle that
        already observes the deferred policy admits)."""
        return self._post_migration_op("live_slots", None, None,
                                       timeout)["reqs"]

    def adopt_request(self, req: Request) -> None:
        """Enqueue an EXISTING Request handle (resize cutover: waiting
        requests follow the pool to the new-degree engine with their
        handles — and any tokens already streamed — intact)."""
        if req.trace is not None:
            req.trace.phase("engine.queue", adopted=True)
        with self._gate:
            if self._error is not None:
                raise RuntimeError(
                    f"engine failed: {self._error!r}") from self._error
            if self._stop.is_set():
                raise RuntimeError("engine is shutting down")
            self._queue.put(req)
            self._ensure_running()
        self._wake.set()

    def resume_sequence(self, req: Request, timeout: float = 60.0) -> None:
        """Abort a migration: un-freeze the exported slot so the source
        keeps decoding as if the transfer never happened (the failed-
        mid-stream contract; counts into kv_migrate_failures_total at
        the orchestrating layer)."""
        self._post_migration_op("resume", req, None, timeout)

    def release_sequence(self, req: Request, timeout: float = 60.0) -> None:
        """Commit the cutover after the destination acked: retire the
        source slot.  Blocks join the free list UNCLEARED with the
        sequence registered, so the migrated-away conversation stays
        prefix-matchable here until its blocks are actually reused."""
        self._post_migration_op("release", req, None, timeout)

    # -- block-ledger audit (analysis/runtime.py BlockLedger) --------------

    def attach_block_ledger(self, ledger) -> None:
        """Wrap this engine's BlockAllocator with an analysis
        :class:`~kubeflow_tpu.analysis.runtime.BlockLedger`.

        From then on every alloc/ref/release is conservation-checked as
        it happens, the scheduler audits the zero-leaked-blocks
        invariant whenever the pool goes fully idle, and ``stats()``
        exports the shared ``kv_blocks_leaked_total`` tally (surfaced
        as a /metrics gauge by the model server).  One ledger may span
        several engines (migration source+destination, resize
        old+new) — the tally is the union.  Attach at a QUIESCENT
        boundary: before traffic for a complete ledger, or while the
        scheduler is idle (the books open at the current refcounts; an
        economy op racing the attach itself would slip past the shadow
        snapshot and later read as spurious drift)."""
        if not self.paged:
            raise RuntimeError(
                "block ledger requires the paged pool (block_size > 0)")
        ledger.attach(self._alloc)
        if self._host_pool is not None:
            # the host tier joins the audit: spill/evict gauge drift is
            # conservation-checked like the HBM refcounts (ISSUE 12)
            ledger.attach_host_pool(self._host_pool)
        self.block_ledger = ledger

    def audit_blocks(self, timeout: float = 60.0) -> list:
        """On-demand zero-leak audit at a consistent boundary: runs on
        the scheduler thread via the migration mailbox (between
        dispatches, after any in-flight admission/retirement), so the
        held-block set it audits against cannot be mid-mutation.
        Returns the leak records (empty = invariant holds).  The tests'
        per-scenario ad-hoc ``kv_blocks_free == num_blocks`` asserts
        collapse onto this one call."""
        if self.block_ledger is None:
            return []
        if self._stop.is_set() and (
                self._thread is None or not self._thread.is_alive()):
            # post-shutdown boundary (resize retired this engine, a test
            # audits after stop): no scheduler to race — audit directly
            return self._audit_blocks_now()
        return self._post_migration_op("audit", None, None,
                                       timeout)["leaks"]

    def _held_blocks(self) -> list[int]:
        """Blocks legitimately referenced right now: live/frozen slot
        tables.  A frozen migrating slot keeps its blocks by design
        (copy-then-cutover) and chunked-prefill reservations set
        ``_slots[slot]`` up front, so the slot table is the complete
        ownership record."""
        held: list[int] = []
        for slot, blocks in enumerate(self._slot_blocks):
            if blocks and (self._slots[slot] is not None
                           or slot in self._migrating):
                held.extend(blocks)
        return held

    def _audit_blocks_now(self) -> list:
        """Scheduler-thread audit body (mailbox op + idle hook)."""
        if self.block_ledger is None or self._alloc is None:
            return []
        if self._host_pool is not None:
            self.block_ledger.audit_host(self._host_pool)
        return self.block_ledger.audit_quiesced(
            self._alloc, held=self._held_blocks())

    def observe_migration_ms(self, ms: float) -> None:
        """Record one completed migration's export->ack latency into
        the kv_migrate_latency_ms histogram."""
        for i, b in enumerate(self._mig_buckets):
            if ms <= b:
                break
        else:
            i = len(self._mig_buckets)
        self._mig_lat_counts[i] += 1
        self._mig_lat_sum += float(ms)

    def _post_migration_op(self, kind: str, a, b, timeout: float) -> dict:
        ev = threading.Event()
        out: dict = {}
        with self._gate:
            if self._error is not None:
                raise RuntimeError(
                    f"engine failed: {self._error!r}") from self._error
            if self._stop.is_set():
                raise RuntimeError("engine is shutting down")
            self._migrate_q.put((kind, a, b, ev, out))
            self._ensure_running()
        self._wake.set()
        if not ev.wait(timeout):
            # ABANDON the op so it can never execute later: a stale
            # import landing after the caller resumed the source would
            # double-decode one request (both flags are set-then-check
            # under the GIL, so exactly one side wins — either the
            # scheduler already took the op, and we wait out its
            # bounded execution, or it will skip it)
            out["abandoned"] = True
            if not (out.get("taken") and ev.wait(60)):
                raise TimeoutError(
                    f"migration {kind} not serviced within {timeout}s")
        err = out.get("error")
        if err is not None:
            raise err if isinstance(err, Exception) \
                else RuntimeError(str(err))
        return out

    def _service_migrations(self, pending) -> None:
        """Scheduler-side mailbox pump (between dispatches): every
        migration op mutates pool buffers and scheduler state, so they
        all run here — the one thread that owns both."""
        if self._migrate_q.empty():
            return
        while True:
            try:
                kind, a, b, ev, out = self._migrate_q.get_nowait()
            except queue.Empty:
                return
            out["taken"] = True
            if out.get("abandoned"):
                # the caller timed out and already acted on failure
                # (resumed the source): executing now would double-own
                # the sequence — drop the op instead
                out["error"] = RuntimeError("migration op abandoned")
                ev.set()
                continue
            try:
                if kind == "export":
                    self._mig_export(a, out, pending)
                elif kind == "import":
                    self._mig_import(a, b[0], out, hold=b[1])
                elif kind == "resume":
                    self._mig_resume(a)
                elif kind == "take_waiting":
                    self._mig_take_waiting(out)
                elif kind == "audit":
                    out["leaks"] = self._audit_blocks_now()
                elif kind == "export_prefix":
                    self._mig_export_prefix(a, out)
                elif kind == "prefix_census":
                    self._mig_prefix_census(out)
                elif kind == "install_prefix":
                    self._mig_install_prefix(a, b, out)
                elif kind == "live_slots":
                    out["reqs"] = [r for r in self._slots
                                   if r is not None
                                   and not r.done.is_set()]
                else:
                    self._mig_release(a)
            except Exception as e:  # noqa: BLE001 — resolve THIS waiter;
                # a GangEngine publish failure set _error: re-raise so
                # the gang goes fatal instead of diverging
                out["error"] = e
                ev.set()
                if self._error is not None:
                    raise
                continue
            ev.set()

    def _fail_migration_waiters(self, e: Exception) -> None:
        """Resolve every queued migration op with ``e`` (engine death /
        shutdown) so cross-thread callers never hang on the mailbox."""
        while True:
            try:
                *_a, ev, out = self._migrate_q.get_nowait()
            except queue.Empty:
                return
            out["error"] = e
            ev.set()

    def _find_req_slot(self, req: Request) -> Optional[int]:
        for i, r in enumerate(self._slots):
            if r is req:
                return i
        return None

    def _mig_export(self, req: Request, out: dict, pending) -> None:
        # land every in-flight dispatch first: the slot's position,
        # delivered tokens and content record must agree before the
        # snapshot freezes it
        while pending:
            self._process(*pending.pop(0))
        slot = self._find_req_slot(req)
        if slot is None or req.done.is_set():
            out["snap"] = None  # finished/cancelled: nothing to migrate
            return
        if slot in self._migrating:
            rec = self._migrating[slot]
            entry = rec.get("entry")
        else:
            # a partially-prefilled sequence exports at its chunk
            # boundary: pull its admission entry so no further chunk
            # dispatches advance it while the transfer runs
            entry = None
            for e in self._prefilling:
                if e[0] is req:
                    entry = e
                    break
            rec = {"req": req, "entry": entry}
            if entry is not None:
                self._prefilling.remove(entry)
                self._prefill_tokens_inflight -= len(entry[2]) - entry[3]
            else:
                self._active[slot] = False
                # freeze-time logits stash: the pool decode/verify scans
                # recompute EVERY row's logits — active or not — so a
                # frozen slot's live row DRIFTS while other slots keep
                # decoding.  The snapshot and any later resume must read
                # this frozen copy, never the clobbered live row (found
                # by the ISSUE 10 resize parity suite: a resumed
                # sequence's next token sampled from another dispatch's
                # garbage).
                rec["logits"] = self._logits_take(self._pool_logits,
                                                  np.int32(slot))
            self._migrating[slot] = rec
        out["snap"] = self._snapshot_slot(slot, req, entry, rec)

    def _snapshot_slot(self, slot: int, req: Request, entry,
                       rec=None) -> dict:
        """Device-side snapshot (scheduler thread): block gathers are
        DISPATCHED here, fetched by the caller off-thread.  ``rec`` is
        the slot's freeze record — a decode-phase snapshot reads its
        stashed logits row (taken at freeze time), because the live row
        is rewritten by every later pool dispatch."""
        bs = self.block_size
        if entry is not None:
            phase = "prefill"
            prompt, off = list(entry[2]), int(entry[3])
            position = off
            generated: list[int] = []
            remaining = int(req.max_new_tokens)
            logits_dev = None
            temp = (self.temperature if req.temperature is None
                    else req.temperature)
            top_p = 1.0 if req.top_p is None else req.top_p
            top_k = 0 if req.top_k is None else req.top_k
        else:
            phase = "decode"
            position = int(self._positions[slot])
            generated = list(req.tokens)
            content = list(self._slot_content[slot])
            prompt = content[: max(position - len(generated), 0)]
            remaining = int(self._remaining[slot])
            logits_dev = (rec or {}).get("logits")
            if logits_dev is None:
                logits_dev = self._logits_take(self._pool_logits,
                                               np.int32(slot))
            temp = float(self._temps[slot])
            top_p = float(self._top_ps[slot])
            top_k = int(self._top_ks[slot])
        nwritten = min(-(-position // bs), len(self._slot_blocks[slot])) \
            if position > 0 else 0
        ids = [int(b) for b in self._slot_blocks[slot][:nwritten]]
        blocks_dev = []  # [(group leaves, valid rows)]
        for i in range(0, len(ids), KV_MIGRATE_GROUP):
            grp = ids[i:i + KV_MIGRATE_GROUP]
            bt = np.full((KV_MIGRATE_GROUP, 1), self._alloc.pad_block,
                         np.int32)
            bt[:len(grp), 0] = grp
            blocks_dev.append(
                (self._kv_export(self._pool_cache, bt), len(grp)))
        return {
            "v": 1, "phase": phase, "block_size": bs,
            "prompt": [int(t) for t in prompt],
            "generated": [int(t) for t in generated],
            "position": position, "remaining": remaining,
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(temp), "top_p": float(top_p),
            "top_k": int(top_k), "priority": int(req.priority),
            "spec_ban": int(self._spec_ban[slot]),
            "blocks_dev": blocks_dev, "logits_dev": logits_dev,
        }

    def _mig_take_waiting(self, out: dict) -> None:
        """Withdraw the waiting list + intake queue (resize cutover)."""
        reqs = [r for r in self._waiting if not r.done.is_set()]
        self._waiting.clear()
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                break
            if not r.done.is_set():
                reqs.append(r)
        out["reqs"] = reqs

    def _mig_import(self, snap: dict, req: Optional[Request],
                    out: dict, hold: bool = False) -> None:
        bs = int(snap["block_size"])
        if bs != self.block_size:
            raise ValueError(
                f"block_size mismatch: snapshot {bs} vs pool "
                f"{self.block_size}")
        phase = snap.get("phase", "decode")
        position = int(snap["position"])
        remaining = int(snap["remaining"])
        prompt = [int(t) for t in snap["prompt"]]
        generated = [int(t) for t in snap.get("generated", ())]
        blocks = snap.get("blocks", [])
        if phase == "prefill":
            total = len(prompt) + int(snap["max_new_tokens"])
        else:
            total = position + remaining
        nb_total = max(-(-total // bs), len(blocks), 1)
        if nb_total > self._alloc.num_blocks:
            raise RuntimeError(
                f"sequence needs {nb_total} KV blocks but the pool has "
                f"{self._alloc.num_blocks}")
        free = [i for i, r in enumerate(self._slots) if r is None]
        if not free:
            raise RuntimeError("no free slot on the destination pool")
        table = self._alloc.alloc(nb_total)
        if table is None:
            raise RuntimeError(
                f"destination pool exhausted: {self._alloc.free_blocks} "
                f"free blocks < {nb_total} needed")
        slot = free[0]
        try:
            nbytes = 0
            G = KV_MIGRATE_GROUP
            for i in range(0, len(blocks), G):
                grp = blocks[i:i + G]
                bt = np.full((G, 1), self._alloc.num_blocks, np.int32)
                bt[:len(grp), 0] = [int(table[i + j])
                                    for j in range(len(grp))]
                leaves = []
                for li in range(len(grp[0])):
                    # analysis: ok host-sync-in-dispatch — wire bytes are host numpy
                    parts = [np.asarray(b[li]) for b in grp]
                    nbytes += sum(x.nbytes for x in parts)
                    stack = np.concatenate(parts, axis=0)
                    if len(grp) < G:
                        stack = np.concatenate(
                            [stack, np.zeros(
                                (G - len(grp),) + stack.shape[1:],
                                stack.dtype)], axis=0)
                    leaves.append(stack)
                self._pool_cache = self._kv_import(
                    self._pool_cache, bt, tuple(leaves))
            if req is None:
                req = Request(
                    prompt=prompt,
                    max_new_tokens=int(snap["max_new_tokens"]),
                    temperature=snap.get("temperature"),
                    top_p=snap.get("top_p"), top_k=snap.get("top_k"),
                    priority=int(snap.get("priority", 1)))
                req.tokens = list(generated)
                if self.tracer is not None and snap.get("trace"):
                    # cross-process import: continue the propagated
                    # trace on a fresh handle (the wire `trace` field);
                    # in-process handoffs share the handle and with it
                    # the live Trace object.  No door owns this
                    # trace's finalization — register it for the
                    # tracer's lazy reap (finish-on-done runs on a
                    # read surface's thread, never here)
                    req.trace = self.tracer.adopt(snap["trace"])
                    self.tracer.watch(req.done, req.trace)
            self._slots[slot] = req
            self._slot_blocks[slot] = [int(b) for b in table]
            if self.block_ledger is not None:
                self.block_ledger.annotate(self._alloc, table,
                                           f"slot{slot}:import")
            req.slot = slot
            req.admitted_step = self.step_counter
            if phase == "prefill":
                self._slot_content[slot] = prompt[:position]
                self._slot_owner[slot] = None
                self._active[slot] = False
                entry = [req, slot, prompt, position]
                if hold:
                    # installed FROZEN (resize commit): the admission
                    # entry waits in the freeze record exactly as a
                    # mid-prefill export's does — resume_sequence
                    # re-queues it at the head
                    self._migrating[slot] = {"req": req, "entry": entry}
                else:
                    self._prefilling.append(entry)
                    self._prefill_tokens_inflight += len(prompt) - position
                    if req.trace is not None:
                        req.trace.phase("engine.prefill", slot=slot,
                                        imported=True)
            else:
                # analysis: ok host-sync-in-dispatch — wire bytes are host numpy
                row = np.asarray(snap["logits"])
                nbytes += row.nbytes
                self._pool_logits = self._logits_set(
                    self._pool_logits, row, np.int32(slot))
                self._slot_content[slot] = prompt + generated
                self._slot_owner[slot] = req
                self._positions[slot] = position
                self._remaining[slot] = remaining
                self._temps[slot] = float(snap.get("temperature") or 0.0)
                self._top_ps[slot] = float(snap.get("top_p") or 1.0)
                self._top_ks[slot] = int(snap.get("top_k") or 0)
                self._spec_ban[slot] = int(snap.get("spec_ban", -1))
                self._spec_backoff[slot] = 0
                self._spec_cool[slot] = 0
                if hold:
                    self._active[slot] = False
                    # stash the imported row for the resume reinstall:
                    # earlier-resumed slots' dispatches rewrite every
                    # live logits row, held ones included
                    self._migrating[slot] = {"req": req, "entry": None,
                                             "logits": row}
                else:
                    self._active[slot] = not req.done.is_set()
                    if req.trace is not None:
                        req.trace.phase("engine.decode", slot=slot,
                                        imported=True)
            self.kv_migrations_total += 1
            self.kv_migrate_bytes_total += nbytes
            out["req"] = req
        except Exception:
            # failed mid-install: unwind fully — no leaked blocks, no
            # half-occupied slot (the source still owns the sequence)
            self._slots[slot] = None
            self._slot_blocks[slot] = []
            self._slot_content[slot] = []
            self._active[slot] = False
            self._alloc.release(table)
            raise

    def _mig_resume(self, req: Request) -> None:
        slot = self._find_req_slot(req)
        if slot is None:
            return  # finished and swept while the transfer ran
        rec = self._migrating.pop(slot, None)
        if rec is None:
            # never frozen (e.g. the export op was ABANDONED on
            # timeout, or resume raced a completed cutover): there is
            # nothing to undo.  Activating blind here would corrupt a
            # mid-prefill slot — remaining is 0 until _occupy runs, so
            # the next schedule advance would retire it and release
            # blocks its _prefilling entry still references.
            return
        if req.done.is_set():
            return  # the sweep retires it next iteration
        if rec.get("entry") is not None:
            e = rec["entry"]
            # resume at the HEAD: this sequence was mid-admission
            self._prefilling.appendleft(e)
            self._prefill_tokens_inflight += len(e[2]) - e[3]
            if req.trace is not None:
                req.trace.phase("engine.prefill", resumed=True)
        else:
            if rec.get("logits") is not None:
                # reinstall the freeze-time logits row: the live row was
                # rewritten by every pool dispatch that ran while this
                # slot was frozen — sampling from it would emit garbage
                self._pool_logits = self._logits_set(
                    self._pool_logits, rec["logits"], np.int32(slot))
            self._active[slot] = True
            if req.trace is not None:
                req.trace.phase("engine.decode", resumed=True)
        # idle-session accounting: a freeze window (migration, resize,
        # a held import waiting between turns) is not IDLENESS — the
        # resume restarts the reaper's clock so a just-thawed or
        # just-cutover sequence cannot be reaped for time it spent
        # frozen by an actuator
        req.last_token_at = time.perf_counter()

    def _mig_release(self, req: Request) -> None:
        slot = self._find_req_slot(req)
        if slot is None:
            return
        # cutover commit: the destination owns the sequence now.  The
        # request object itself is NOT resolved — it keeps accruing
        # tokens from the destination engine (the re-targeted handle).
        # kv_migrations_total counts on the IMPORTING side only (one
        # increment per migration — a pool summing both tiers must not
        # double-count); the source's outbound view is the latency
        # histogram count.
        self._retire_slot(slot)

    def _best_prefix(self, prompt: list[int]) -> tuple[int, int]:
        """(src_slot, lp): the longest usable prefix of ``prompt`` already
        present in some slot's KV.  Caps at len(prompt)-1 — at least one
        suffix token must run to produce the next-token logits.

        Vectorized: this runs on the scheduler thread for EVERY
        admission; a token-by-token Python loop at 64 slots x 4k tokens
        would cost the same order as the admission saving itself."""
        best_slot, best_lp = -1, 0
        cap = len(prompt) - 1
        # analysis: ok host-sync-in-dispatch — host token list, no device value
        p = np.asarray(prompt, np.int64)
        for s, content in enumerate(self._slot_content):
            if min(len(content), cap) <= best_lp:
                continue  # cannot beat the incumbent
            lcp = _lcp(content, p, cap)
            if lcp > best_lp:
                best_slot, best_lp = s, lcp
        return best_slot, best_lp

    def _admit_with_prefix(self, req: Request, prompt: list[int],
                           slot: int, src: int, lp: int) -> None:
        suffix = prompt[lp:]
        bucket = next(b for b in self.seq_buckets if b >= len(suffix))
        toks = np.zeros(bucket, np.int32)
        toks[: len(suffix)] = suffix
        program = self._prefix_admit_for(lp + bucket, bucket)
        self._pool_cache, self._pool_logits = program(
            self.params, self._pool_cache, self._pool_logits,
            np.int32(src), np.int32(slot), np.int32(lp),
            toks, np.int32(len(suffix)))
        self._occupy(req, prompt, slot)
        self.prefix_hits += 1
        self.prefix_tokens_saved += lp

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except Exception as e:  # noqa: BLE001 — a dead engine thread must
            # not strand waiters: fail everything in flight and refuse new
            # submissions (submit() re-raises self._error)
            with self._gate:
                self._error = e
            for req in self._slots:
                if req is not None and not req.done.is_set():
                    req.error = e
                    req.done.set()
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                req.error = e
                req.done.set()
            for req in self._waiting:
                if not req.done.is_set():
                    req.error = e
                    req.done.set()
            self._waiting.clear()
            self._fail_migration_waiters(e)

    def _purge_prefilling(self) -> None:
        """Drop chunked-admission entries whose request resolved out of
        band (cancel mid-prefill): the out-of-band sweep already freed
        the slot; the KV written so far stays recorded in
        ``_slot_content`` so the prefix matcher can reuse the partial
        prefill (the same retirement-keeps-content rule live slots
        follow)."""
        if not self._prefilling:
            return
        kept = type(self._prefilling)()
        for e in self._prefilling:
            if e[0].done.is_set():
                self._prefill_tokens_inflight -= len(e[2]) - e[3]
            else:
                kept.append(e)
        self._prefilling = kept

    def _prefill_chunk_args(self):
        """Host decision for the head of the chunked-admission queue:
        (entry, toks [budget], take, final, write_slot, attend_needed).
        With ``prefill_budget == 0`` (paged monolithic admission) the
        one chunk covers the whole remainder, bucketed like a legacy
        prefill."""
        entry = self._prefilling[0]
        req, slot, prompt, off = entry
        rem = len(prompt) - off
        budget = self.prefill_budget or next(
            b for b in self.seq_buckets if b >= rem)
        take = min(budget, rem)
        final = (off + take) == len(prompt)
        toks = np.zeros(budget, np.int32)
        toks[:take] = prompt[off:off + take]
        write_slot = slot if final else self.num_slots
        return entry, toks, take, final, write_slot, off + budget

    def _fail_prefill_head(self, entry, e: Exception) -> None:
        """Resolve the head admission's request with the dispatch error —
        and ONLY that request (the legacy path's fail-this-group-only
        contract).  The slot/entry/token counter are reclaimed by the
        sweep and purge at the next loop top.  A GangEngine dispatch
        failure additionally set self._error (the published op may have
        reached followers); re-raise so the gang goes fatal instead of
        limping with divergent pools."""
        entry[0].error = e
        entry[0].done.set()
        if self._error is not None:
            raise e

    def _advance_prefill(self, entry, take: int, final: bool) -> None:
        """Book one dispatched chunk: the slot's KV now holds
        prompt[:off+take] (device dispatch order guarantees any later
        program reads it written), and the final chunk activates the
        slot — its first token samples from the freshly written logits
        at the NEXT dispatch, exactly as a merged whole-prompt prefill's
        would."""
        req, slot, prompt, off = entry
        entry[3] = off + take
        self._slot_content[slot] = prompt[: off + take]
        self._prefill_tokens_inflight -= take
        self.prefill_chunks_dispatched += 1
        if final:
            self._prefilling.popleft()
            self._occupy(req, prompt, slot)
            if self.role == "prefill" and self.on_prefilled is not None:
                # disaggregation handoff (ISSUE 8): freeze at the chunk
                # boundary — the final chunk's logits are in the pool
                # row, so the DESTINATION samples the first token
                # exactly as this engine would have.  The hook only
                # enqueues; a raising hook fails open into local decode
                # (correctness first, disaggregation second).
                self._active[slot] = False
                self._migrating[slot] = {"req": req, "entry": None}
                if req.trace is not None:
                    # disaggregation: prefill ends frozen at the chunk
                    # boundary; the handoff phase runs until the decode
                    # tier's import activates the sequence there
                    req.trace.phase("engine.handoff", slot=slot)
                try:
                    self.on_prefilled(req)
                except Exception as e:  # noqa: BLE001 — degrade to mixed
                    log.debug("on_prefilled hook failed: %s", e)
                    self._migrating.pop(slot, None)
                    self._active[slot] = True

    def _loop_inner(self) -> None:
        # in-flight chunk dispatches: (device tokens, [(slot, req, take)])
        pending: list[tuple[Any, list[tuple[int, Request, int]]]] = []
        while not self._stop.is_set():
            self._service_migrations(pending)
            self._admit()
            # free slots whose request resolved OUT of band (cancel()):
            # the normal retirements already cleared theirs, so a done-
            # but-still-active slot can only be a cancellation (or a
            # cancel mid-chunked-prefill — reserved but never activated)
            for slot in range(self.num_slots):
                req = self._slots[slot]
                if req is not None and req.done.is_set():
                    # cancel-mid-prefill included: blocks return to the
                    # free list while the partial KV stays matchable
                    self._retire_slot(slot)
            self._purge_prefilling()
            has_prefill = bool(self._prefilling)
            #: chunked admission can ride a decode dispatch only when a
            #: fused program exists (prefill_budget > 0); the paged
            #: monolithic path (budget 0) dispatches its single
            #: whole-remainder chunk standalone AFTER the decode —
            #: exactly the legacy admission bound, block-table backed
            can_fuse = has_prefill and self.prefill_budget > 0
            if not self._active.any() and not has_prefill:
                # drain the tail, then wait for work without spinning
                while pending:
                    self._process(*pending.pop(0))
                if (self._active.any() or self._waiting or self._prefilling
                        or not self._queue.empty()
                        or not self._migrate_q.empty()):
                    continue  # _process freed slots or work arrived
                if self.block_ledger is not None and not self._migrating:
                    # fully idle, nothing frozen: every block still
                    # referenced outside a slot table is a leak — the
                    # ledger counts each once, so idle re-audits are
                    # free
                    self._audit_blocks_now()
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            # analysis: ok host-sync-in-dispatch — host numpy scheduler state
            live = bool(self._active.any())
            if live:
                # step_counter counts DECODE dispatches (the decode_steps
                # gauge, admitted_step ages): prefill-only iterations
                # must not inflate it — and only decode-carrying
                # dispatches consume a sampling key
                self.step_counter += 1
                key = np.array(
                    [self._base_seed, self.step_counter & 0xFFFFFFFF],
                    np.uint32)
            snapshot = [
                (slot, self._slots[slot],
                 int(min(self.decode_chunk, self._remaining[slot])))
                for slot in range(self.num_slots)
                if self._active[slot] and self._slots[slot] is not None
            ]
            # any sampled request in this dispatch?  One attribute read
            # per live slot; stays False (and allocates NOTHING below)
            # at sample=0 — the zero-overhead contract the trace layer
            # pins (tests/test_observability.py)
            traced = False
            for _s, _r, _t in snapshot:
                if _r.trace is not None:
                    traced = True
                    break
            family = "decode"  # program family attr for dispatch spans
            rung = 0
            # pass NUMPY COPIES that are never mutated again: the CPU
            # backend zero-copies numpy buffers across the jit boundary,
            # and the schedule advance below mutates self._positions /
            # self._active while the async-dispatched decode may not have
            # executed yet — an aliased input then reads ADVANCED
            # positions (writes land one slot off, intermittently, under
            # dispatch-ahead pipelining; reproduced 3/10 before this fix)
            live_seg = (live and self.prefix_segments > 0
                        # analysis: ok host-sync-in-dispatch — host numpy
                        and bool((self._slot_plen[self._active] > 0).any()))
            use_spec, drafts, proposed = (
                self._plan_spec()
                if live and self.spec_k > 0 and not live_seg
                else (False, None, 0))
            # window = smallest attend bucket covering every live position
            # plus this dispatch's write span (chunk steps, or the
            # speculative t1 + spec_k drafts) — early turns read KV
            # proportional to the conversation front, not max_seq_len
            span = (self.spec_k + 1) if use_spec else self.decode_chunk
            # analysis: ok host-sync-in-dispatch — host numpy scheduler state
            needed = ((int(self._positions[self._active].max()) + span)
                      if live else self.decode_chunk)
            spec_out = None  # (toks, accept) device results of a verify
            if live_seg:
                # the segment decode program advances EVERY active slot
                # without the verify's residual mask, so any pending ban
                # would go stale (wrong position) and later mask a VALID
                # token — drop them.  Bit-identical for greedy (the
                # rejection already proved argmax != ban at the banned
                # position); for stochastic slots this one draw comes
                # from the full distribution instead of the residual —
                # the documented carve-out of speculating pools that
                # also serve shared-prefix segments.
                if self.spec_k > 0:
                    self._spec_ban[:] = -1
                # analysis: ok host-sync-in-dispatch — host numpy scheduler state
                seg_att = int(self._slot_plen[self._active].max())
                family, rung = "seg_decode", needed
                plens = np.where(
                    self._active, self._slot_plen, 0).astype(np.int32)
                self._pool_cache, self._pool_logits, toks = (
                    self._prefix_decode_for(needed, seg_att)(
                        self.params, self._pool_cache, self._pool_logits,
                        self._seg_cache, self._positions.copy(), plens,
                        self._slot_seg.astype(np.int32).copy(),
                        self._active.copy(), self._temps.copy(),
                        self._top_ps.copy(), self._top_ks.copy(), key))
            elif live and has_prefill and can_fuse:
                # the stall-free hot path: one dispatch = one prefill
                # chunk + the whole pool's decode scan
                entry, ptoks, take, final, write_slot, p_needed = (
                    self._prefill_chunk_args())
                psp = (entry[0].trace.begin(
                    "prefill.chunk", take=take, offset=int(entry[3]),
                    final=final, fused=True)
                    if entry[0].trace is not None else None)
                try:
                    if use_spec:
                        # chunked prefill fuses into the VERIFY dispatch
                        # exactly as it fuses into plain decode — turning
                        # speculation on never reopens the ISSUE 2 stall
                        a = max(needed, p_needed)
                        if self.paged:
                            a = self._rung(a)
                            family, rung = "paged_fused_verify", a
                            (self._pool_cache, self._pool_logits, vtoks,
                             vacc) = self._paged_fused_verify_for(a)(
                                self.params, self._pool_cache,
                                self._pool_logits, self._block_tables(a),
                                np.int32(entry[1]), ptoks,
                                np.int32(entry[3]), np.int32(take),
                                np.int32(write_slot),
                                drafts, self._spec_ban.copy(),
                                self._positions.copy(),
                                self._active.copy(), self._temps.copy(),
                                self._top_ps.copy(),
                                self._top_ks.copy(), key)
                        else:
                            family, rung = "fused_verify", a
                            (self._pool_cache, self._pool_logits, vtoks,
                             vacc) = self._fused_verify_for(a)(
                                self.params, self._pool_cache,
                                self._pool_logits,
                                np.int32(entry[1]), ptoks,
                                np.int32(entry[3]),
                                np.int32(take), np.int32(write_slot),
                                drafts, self._spec_ban.copy(),
                                self._positions.copy(),
                                self._active.copy(),
                                self._temps.copy(), self._top_ps.copy(),
                                self._top_ks.copy(), key)
                        spec_out = (vtoks, vacc)
                    elif self.paged:
                        a = self._rung(max(needed, p_needed))
                        family, rung = "paged_fused", a
                        self._pool_cache, self._pool_logits, toks = (
                            self._paged_fused_for(a)(
                                self.params, self._pool_cache,
                                self._pool_logits, self._block_tables(a),
                                np.int32(entry[1]), ptoks,
                                np.int32(entry[3]),
                                np.int32(take), np.int32(write_slot),
                                self._positions.copy(),
                                self._active.copy(),
                                self._temps.copy(), self._top_ps.copy(),
                                self._top_ks.copy(), key))
                    else:
                        family, rung = "fused", max(needed, p_needed)
                        self._pool_cache, self._pool_logits, toks = (
                            self._fused_for(max(needed, p_needed))(
                                self.params, self._pool_cache,
                                self._pool_logits,
                                np.int32(entry[1]), ptoks,
                                np.int32(entry[3]),
                                np.int32(take), np.int32(write_slot),
                                self._positions.copy(),
                                self._active.copy(),
                                self._temps.copy(), self._top_ps.copy(),
                                self._top_ks.copy(), key))
                except Exception as e:  # noqa: BLE001 — fail THIS request
                    # (the legacy path's per-group isolation): a
                    # compile/trace failure raises before execution, so
                    # the donated pool buffers are intact; sweep + purge
                    # reclaim the slot and entry next iteration.  A gang
                    # engine's _fatal already recorded the error — there
                    # the published op may have reached followers and the
                    # whole gang must restart, not paper over it.
                    if psp is not None:
                        psp.set(error=str(e)).done()
                    self._fail_prefill_head(entry, e)
                    continue  # no decode chunk landed this iteration
                if psp is not None:
                    psp.done()
                self._advance_prefill(entry, take, final)
            elif use_spec:
                if self.paged:
                    a = self._rung(needed)
                    family, rung = "paged_verify", a
                    self._pool_cache, self._pool_logits, vtoks, vacc = (
                        self._paged_verify_for(a)(
                            self.params, self._pool_cache,
                            self._pool_logits, self._block_tables(a),
                            drafts, self._spec_ban.copy(),
                            self._positions.copy(), self._active.copy(),
                            self._temps.copy(), self._top_ps.copy(),
                            self._top_ks.copy(), key))
                else:
                    family, rung = "verify", needed
                    self._pool_cache, self._pool_logits, vtoks, vacc = (
                        self._verify_for(needed)(
                            self.params, self._pool_cache,
                            self._pool_logits,
                            drafts, self._spec_ban.copy(),
                            self._positions.copy(), self._active.copy(),
                            self._temps.copy(), self._top_ps.copy(),
                            self._top_ks.copy(), key))
                spec_out = (vtoks, vacc)
            elif live:
                if self.paged:
                    a = self._rung(needed)
                    family, rung = "paged_decode", a
                    self._pool_cache, self._pool_logits, toks = (
                        self._paged_decode_for(a)(
                            self.params, self._pool_cache,
                            self._pool_logits, self._block_tables(a),
                            self._positions.copy(), self._active.copy(),
                            self._temps.copy(), self._top_ps.copy(),
                            self._top_ks.copy(), key))
                else:
                    family, rung = "decode", needed
                    self._pool_cache, self._pool_logits, toks = (
                        self._decode_for(needed)(
                            self.params, self._pool_cache,
                            self._pool_logits,
                            self._positions.copy(), self._active.copy(),
                            self._temps.copy(), self._top_ps.copy(),
                            self._top_ks.copy(), key))
            if has_prefill and (not live or live_seg or not can_fuse):
                # no decode dispatch to ride (idle pool), or the pool
                # decodes through the segment-aware program: run the
                # chunk standalone, AFTER the decode dispatch — the
                # decode scan rewrites every slot's logits, so the final
                # chunk's last-token logits must land after it on the
                # device stream, and the slot activates only once both
                # are in flight (the next dispatch samples its first
                # token from the prefill logits, never a clobbered row)
                # paged monolithic admission (budget 0) DRAINS the whole
                # queue here — each entry is exactly one whole-remainder
                # chunk, so serializing them across loop iterations
                # would only interleave admission stalls into the decode
                # stream; one drain per iteration matches the legacy
                # batched-prefill admission bound
                while self._prefilling:
                    entry, ptoks, take, final, write_slot, p_needed = (
                        self._prefill_chunk_args())
                    psp = (entry[0].trace.begin(
                        "prefill.chunk", take=take, offset=int(entry[3]),
                        final=final, fused=False)
                        if entry[0].trace is not None else None)
                    try:
                        if self.paged:
                            a = self._rung(p_needed)
                            nblk = -(-a // self.block_size)
                            row = np.full((1, nblk),
                                          self._alloc.pad_block,
                                          np.int32)
                            blocks = self._slot_blocks[entry[1]]
                            row[0, :min(len(blocks), nblk)] = \
                                blocks[:nblk]
                            self._pool_cache, self._pool_logits = (
                                self._paged_chunk_for(a, len(ptoks))(
                                    self.params, self._pool_cache,
                                    self._pool_logits, row, ptoks,
                                    np.int32(entry[3]), np.int32(take),
                                    np.int32(write_slot)))
                        else:
                            self._pool_cache, self._pool_logits = (
                                self._chunk_prefill_for(p_needed)(
                                    self.params, self._pool_cache,
                                    self._pool_logits,
                                    np.int32(entry[1]), ptoks,
                                    np.int32(entry[3]),
                                    np.int32(take), np.int32(write_slot)))
                    except Exception as e:  # noqa: BLE001 — fail THIS
                        # request (purge reclaims the head entry next
                        # loop top)
                        if psp is not None:
                            psp.set(error=str(e)).done()
                        self._fail_prefill_head(entry, e)
                        break
                    if psp is not None:
                        psp.done()
                    self._advance_prefill(entry, take, final)
                    if not (self.paged and self.prefill_budget == 0):
                        break  # budgeted chunks: one per dispatch cycle
            if not live:
                # prefill-only iteration: no decode chunk landed, but
                # earlier dispatches' tokens may be waiting — deliver
                # them NOW, or a request whose final chunk is already in
                # flight would not resolve until the whole admission
                # finishes (its pending entry is only drained by the
                # depth check below or the idle branch, neither of which
                # runs while only prefill work exists)
                while pending:
                    self._process(*pending.pop(0))
                continue
            dspans = None
            if traced:
                # per-request dispatch spans: enqueue -> fetch-landed,
                # carrying the program family + warmed rung actually
                # dispatched (closed by _process after the fetch)
                dspans = []
                for _slot, _req, _take in snapshot:
                    if _req.trace is not None:
                        dspans.append(_req.trace.begin(
                            "dispatch", family=family, rung=int(rung),
                            step=self.step_counter))
            if spec_out is not None:
                self.spec_dispatches_total += 1
                # counted HERE, not at plan time: a fused-verify dispatch
                # that fails (_fail_prefill_head + continue) never ran a
                # verify, and counting its proposals would permanently
                # deflate the exported spec_acceptance_rate
                self.spec_tokens_proposed_total += proposed
                # the verify's advance is VALUE-dependent (accept
                # lengths decide it): no schedule advance here — the
                # depth-1 drain below lands the fetch before the next
                # dispatch and _process applies it
                pending.append((spec_out, snapshot, "verify", drafts,
                                dspans))
            else:
                # advance the value-independent schedule NOW so the next
                # chunk can dispatch before this one's tokens are fetched
                for slot, req, take in snapshot:
                    self._positions[slot] += self.decode_chunk
                    self._remaining[slot] -= take
                    if self._remaining[slot] <= 0:
                        # slot is schedulable for a new occupant
                        # immediately; the request itself resolves when
                        # its tokens arrive (blocks freed here are safe
                        # to reuse mid-flight: device dispatch order
                        # writes the new occupant's prefill after this
                        # chunk — the slot pool's standing stale-KV
                        # argument, now at block granularity)
                        self._retire_slot(slot)
                pending.append((toks, snapshot, "chunk", None, dspans))
            if self.spec_k > 0:
                # speculation makes the dispatch schedule value-
                # dependent: the next iteration's positions, proposals
                # (matched against the freshest emitted tokens) and
                # residual bans all need this dispatch's accept lengths
                # on the host first, so a spec-enabled pool runs the
                # dispatch-ahead pipeline at depth 1.  The
                # pipeline_depth knob is kept but inert while spec is on
                # (class docstring documents the trade).
                while pending:
                    self._process(*pending.pop(0))
            elif len(pending) >= self.pipeline_depth:
                self._process(*pending.pop(0))
        while pending:
            self._process(*pending.pop(0))

    def _plan_spec(self):
        """Host draft planning for one dispatch:
        (use_verify, drafts, proposed).

        ``drafts`` is [slots, spec_k] int32, -1-padded: -1 never equals
        a sampled token, so rungs without a real proposal can neither
        accept nor arm a residual ban.  A verify dispatch is worth its
        (spec_k+1)-wide forward when any slot has real drafts OR a
        residual ban is pending — the ban must be consumed by a
        verify's masked first sample (the plain decode program has no
        residual mask; skipping would bias stochastic slots against
        their rejected draft's alternatives).  Otherwise the pool falls
        back to the plain ``decode_chunk`` scan, so draft-free traffic
        pays only this host-side lookup."""
        k = self.spec_k
        drafts = np.full((self.num_slots, k), -1, np.int64)
        proposed = 0
        for slot in range(self.num_slots):
            if not self._active[slot] or self._slots[slot] is None:
                continue
            if self._spec_cool[slot] > 0:
                # zero-accept backoff (see __init__): this slot's recent
                # guesses were all wrong — sit out a few dispatches
                self._spec_cool[slot] -= 1
                continue
            # only draft what the request can still emit beyond t1: a
            # slot at its last token would burn a (spec_k+1)-wide
            # forward on tokens _deliver_verify must discard, and the
            # undeliverable tail would skew the acceptance counters
            lim = min(k, int(self._remaining[slot]) - 1)
            if lim <= 0:
                continue
            try:
                p = self._proposer.propose(self._slot_content[slot], lim)
            except Exception:  # noqa: BLE001 — drafts are pure guesses:
                # an injected DraftProposer that raises must degrade to
                # "no draft for this slot", never kill the scheduler
                # thread (which would fail every in-flight request)
                log.debug("draft proposer failed for slot %d", slot,
                          exc_info=True)
                continue
            if p:
                # clamp to the planned budget: the protocol says "up to
                # k" but an overlong list from a custom proposer must
                # not blow up the broadcast below
                p = list(p)[:lim]
                drafts[slot, : len(p)] = p
                proposed += len(p)
        # analysis: ok host-sync-in-dispatch — host numpy scheduler state
        use = proposed > 0 or bool((self._spec_ban[self._active] >= 0).any())
        return use, drafts.astype(np.int32), proposed

    def _process(self, toks_dev, snapshot, kind: str = "chunk",
                 drafts=None, spans=None) -> None:
        """Fetch one dispatch's device results (blocks) and deliver."""
        # THE declared fetch boundary: sampled tokens (plus, for verify
        # dispatches, per-slot accept lengths) leave the device here,
        # depth-gated by the dispatch-ahead pipeline
        # analysis: ok host-sync-in-dispatch — the one intended fetch
        fetched = jax.device_get(toks_dev)
        now = time.perf_counter()
        if spans:
            # close the dispatch spans at the fetch: enqueue -> landed
            # is the interval a stalled device queue shows up in.  A
            # timestamp write, never finalization (the sink runs on the
            # finishing caller's thread).
            for sp in spans:
                sp.done(now)
        if kind == "verify":
            self._deliver_verify(fetched, snapshot, drafts, now)
            return
        # analysis: ok host-sync-in-dispatch — numpy view after the fetch
        toks = np.asarray(fetched)  # [slots, chunk]
        for slot, req, take in snapshot:
            if req.done.is_set():
                # EOS-retired (or cancelled) by an earlier chunk: these
                # tokens were decoded for nobody — count the waste
                self.tokens_discarded += take
                continue
            # analysis: ok host-sync-in-dispatch — numpy after the fetch
            emitted = toks[slot, :take].tolist()
            if self._slot_owner[slot] is req:
                # extend the slot's KV-content record (prefix matcher
                # ground truth) — the sampled tokens' KV was written by
                # the decode dispatch that produced them
                self._slot_content[slot].extend(emitted)
            done = False
            if self.eos_id is not None and self.eos_id in emitted:
                emitted = emitted[: emitted.index(self.eos_id) + 1]
                self.tokens_discarded += take - len(emitted)
                done = True
                # free the slot unless a new occupant already claimed it
                # (max_new-tokens freeing happens at dispatch time)
                if self._slots[slot] is req:
                    self._retire_slot(slot)
            if emitted and req.first_token_at is None:
                req.first_token_at = now
            req.tokens.extend(emitted)
            if emitted:
                req.last_token_at = now
            self.tokens_emitted += len(emitted)
            if done or len(req.tokens) >= req.max_new_tokens:
                if req.trace is not None:
                    # decode phase ends at delivery of the last token;
                    # the root stays open until the serving surface
                    # finishes the trace (response written)
                    req.trace.end_phase(tokens=len(req.tokens))
                req.done.set()

    def _deliver_verify(self, fetched, snapshot, drafts, now) -> None:
        """Value-dependent delivery for one speculative dispatch
        (called from the sanctioned fetch in :meth:`_process`): the
        accept lengths decide how many tokens each slot emitted and how
        far its position front advanced — rejected drafts' KV is
        "rolled back" purely by the pointer not advancing over it (the
        stale rows stay masked until the next dispatch's writes cover
        them; no cache-rewrite dispatch, ISSUE 4) — and whether a
        residual ban arms for the slot's next verify."""
        toks, acc = fetched  # [slots, spec_k+1], [slots]
        k = self.spec_k
        for slot, req, _take in snapshot:
            # analysis: ok host-sync-in-dispatch — numpy after the fetch
            a = int(acc[slot])
            self.spec_tokens_accepted_total += a
            if int(drafts[slot, 0]) >= 0:  # this slot offered real drafts
                if a == 0:
                    self._spec_backoff[slot] = min(
                        max(2 * self._spec_backoff[slot], 2), 32)
                    self._spec_cool[slot] = self._spec_backoff[slot]
                else:
                    self._spec_backoff[slot] = 0
            # residual ban: the first rejected rung's candidate sample
            # was discarded conditioned on differing from the draft, so
            # the next draw must exclude the draft (-1 pads arm nothing
            # — their candidates were never conditioned on)
            ban = int(drafts[slot, a]) if a < k else -1
            if req.done.is_set():
                # cancelled out of band: these tokens went to nobody
                self.tokens_discarded += 1 + a
                self._spec_ban[slot] = -1
                continue
            take = min(1 + a, int(self._remaining[slot]))
            self.tokens_discarded += (1 + a) - take
            # analysis: ok host-sync-in-dispatch — numpy after the fetch
            emitted = toks[slot, :take].tolist()
            self._positions[slot] += take
            self._remaining[slot] -= take
            if self._slot_owner[slot] is req:
                self._slot_content[slot].extend(emitted)
            done = False
            if self.eos_id is not None and self.eos_id in emitted:
                # EOS may land mid-burst: truncate at the exact token
                cut = emitted.index(self.eos_id) + 1
                self.tokens_discarded += take - cut
                emitted = emitted[:cut]
                done = True
            if emitted and req.first_token_at is None:
                req.first_token_at = now
            req.tokens.extend(emitted)
            if emitted:
                req.last_token_at = now
            self.tokens_emitted += len(emitted)
            if done or len(req.tokens) >= req.max_new_tokens \
                    or self._remaining[slot] <= 0:
                if req.trace is not None:
                    req.trace.end_phase(tokens=len(req.tokens))
                req.done.set()
                done = True
            if done and self._slots[slot] is req:
                self._retire_slot(slot)
                ban = -1
            self._spec_ban[slot] = ban


class TieredEngine:
    """The tier ladder as an ADMISSION POLICY over ONE paged pool.

    History: r6/r7 tiers were N separate ContinuousEngine pools, each
    with its own capped KV buffer — the only way a slot-sized contiguous
    pool could stop one long conversation from billing every short
    request max_seq_len of reserved HBM.  The paged block economy
    (ISSUE 6) deletes that reason: a request's KV bill is its actual
    length in blocks, whatever its neighbors do, so the per-tier pools
    (and their split prefix caches, duplicated programs, and
    cross-tier re-prefill tax) are gone — not wrapped, deleted.

    What survives is the SCHEDULING intent as policy: ``tier_lens``
    still classifies requests by known total length (prompt +
    max_new_tokens) and ``tier_slots`` still guarantees each class its
    share of concurrency — enforced through the engine's
    ``admission_policy`` hook, so a burst of long conversations can
    never starve short-request admission (they queue while the short
    classes' reserved slots stay available).  One pool means one prefix
    cache spanning every length class: the conversation that outgrows
    its class now KEEPS its cached blocks.

    Tradeoff (documented, not hidden): the old per-tier pools ALSO
    capped the decode window structurally — a short request co-resident
    with a 2048-token conversation now attends (and gathers) at the
    pool-wide rung, the r3 window tax the capped short pool used to
    prevent.  The ladder trades that per-token read tax for the block
    economy's capacity + one shared prefix cache; operators whose
    traffic is dominated by short requests next to very long
    conversations should route them to separate ISvc replicas (the
    router splits by model, and per-replica pools are cheap once KV is
    block-billed).

    ``tier_lens`` is the ascending ladder of class boundaries (e.g.
    [128, 512, 2048]); the classic two-tier API (``short_len`` /
    ``short_slots``) is the one-entry case.  ``tier_slots`` reserves
    slots per bounded class (the remainder is the unbounded class).
    """

    def __init__(self, cfg, params, *, short_len: int = 512,
                 short_slots: Optional[int] = None, num_slots: int = 8,
                 tier_lens: Optional[list[int]] = None,
                 tier_slots: Optional[list[int]] = None,
                 **kw):
        if tier_lens is None:
            tier_lens = [int(short_len)]
            tier_slots = [num_slots // 2 if short_slots is None
                          else int(short_slots)]
        tier_lens = [int(t) for t in tier_lens]
        if sorted(set(tier_lens)) != tier_lens:
            raise ValueError(f"tier_lens {tier_lens} must be strictly "
                             "ascending")
        for t in tier_lens:
            if not (1 < t < cfg.max_seq_len):
                raise ValueError(
                    f"tier cap {t} must be in (1, {cfg.max_seq_len})")
        if tier_slots is None:
            per = max(1, num_slots // (len(tier_lens) + 1))
            tier_slots = [per] * len(tier_lens)
        tier_slots = [int(n) for n in tier_slots]
        if len(tier_slots) != len(tier_lens) or any(
                n < 1 for n in tier_slots):
            raise ValueError("tier_slots must give every tier >= 1 slot")
        if sum(tier_slots) >= num_slots:
            raise ValueError("tier_slots must leave the uncapped pool "
                             ">= 1 slot")
        self.caps = list(tier_lens)
        self.short_len = tier_lens[0]
        self.quotas = tier_slots + [num_slots - sum(tier_slots)]
        # the ladder REQUIRES the paged pool (one block economy is what
        # makes per-tier KV pools deletable); operators may tune the
        # block size, not opt back into contiguous slots
        if kw.get("block_size", None) in (None, 0):
            kw["block_size"] = max(
                1, min(16, self.short_len // 2))
        self.engine = ContinuousEngine(
            cfg, params, num_slots=num_slots,
            admission_policy=self._admit_quota, **kw)
        #: compatibility surface: ONE pool — `.pools` iterates it,
        #: `.short`/`.long` alias it (both classes live there now)
        self.pools = [self.engine]
        self.short = self.engine
        self.long = self.engine

    # -- admission policy (scheduler thread) ------------------------------

    def _classify(self, req: Request) -> int:
        total = len(req.prompt) + req.max_new_tokens
        for i, cap in enumerate(self.caps):
            if total < cap:
                return i
        return len(self.caps)

    def _admit_quota(self, req: Request) -> bool:
        """Reserve each class its concurrency share: admit only while
        the request's class holds fewer slots than its quota (counted
        over the live+reserved slot table, scheduler-thread-only)."""
        cls = self._classify(req)
        live = sum(
            1 for r in self.engine._slots
            if r is not None and self._classify(r) == cls)
        return live < self.quotas[cls]

    def submit(self, prompt, max_new_tokens=None,
               temperature=None, top_p=None, top_k=None,
               priority=None, trace=None, session_id=None) -> Request:
        return self.engine.submit(
            prompt, max_new_tokens, temperature, top_p=top_p, top_k=top_k,
            priority=priority, trace=trace, session_id=session_id)

    def generate(self, prompt, max_new_tokens=None,
                 timeout: float = 120.0, temperature=None,
                 top_p=None, top_k=None) -> list[int]:
        return self.submit(prompt, max_new_tokens, temperature,
                           top_p=top_p, top_k=top_k).wait(timeout)

    def warmup(self, groups=None) -> None:
        self.engine.warmup(groups)

    def flush_warmup_trace(self) -> None:
        self.engine.flush_warmup_trace()

    def stop(self) -> None:
        self.engine.stop()

    # drop-in interface parity with ContinuousEngine: runtimes that front
    # the engine (serving/text.py) read these
    @property
    def eos_id(self):
        return self.engine.eos_id

    @property
    def default_max_new_tokens(self) -> int:
        return self.engine.default_max_new_tokens

    @property
    def cfg(self):
        return self.engine.cfg

    @property
    def tokens_emitted(self) -> int:
        return self.engine.tokens_emitted

    @property
    def prefix_hits(self) -> int:
        return self.engine.prefix_hits

    @property
    def prefix_tokens_saved(self) -> int:
        return self.engine.prefix_tokens_saved

    def stats(self) -> dict:
        merged = dict(self.engine.stats())
        # analysis: ok host-sync-in-dispatch — host scheduler state
        live = [0] * len(self.quotas)
        for r in self.engine._slots:
            if r is not None:
                live[self._classify(r)] += 1
        merged["classes"] = [
            {"cap": (self.caps[i] if i < len(self.caps) else 0),
             "quota": q, "live": live[i]}
            for i, q in enumerate(self.quotas)]
        # ONE snapshot serves the compatibility keys too — re-invoking
        # engine.stats() per key would pay the slot walk again and
        # could report two inconsistent snapshots in one payload
        snap = dict(merged)
        merged["pools"] = [snap]
        merged["short_pool"] = snap
        merged["long_pool"] = snap
        return merged


def migrate_live_sequences(src: "ContinuousEngine", dst=None, *,
                           send=None, on_latency=None) -> tuple[int, int]:
    """Drain/rebalance: migrate every live conversation off ``src``.

    The drain primitive behind replica retirement (the ISvc controller's
    scale-down), defrag (moving the last sequences off a fragmented pool
    IS compaction — the destination packs them into fresh contiguous
    blocks), and chaos node-drain recovery.  ``dst`` imports in-process;
    ``send`` (callable(host_snapshot, req) -> bool) streams over the
    gang channel's kv_migrate framing instead — a wire ``send`` MUST
    resolve indeterminate outcomes itself before returning (the
    ``DisaggregatedPool._send_wire`` pattern: a commit-delivered /
    ack-lost transfer needs the handle registry + destination-ownership
    check, or resuming here double-decodes the request).
    Copy-then-cutover per sequence: a failed transfer resumes decoding
    on ``src`` — a drain can fall short, never lose a conversation.
    Returns (moved, failed).
    """
    if send is None and dst is None:
        raise ValueError("migrate_live_sequences needs dst or send")
    moved = failed = 0
    for req in [r for r in list(src._slots)
                if r is not None and not r.done.is_set()]:
        if send is not None:
            def transfer(snap, _r=req):
                return send(snap, _r)
        else:
            def transfer(snap, _r=req):
                return dst.import_sequence(snap, req=_r) is not None
        outcome = _migrate_one(src, req, transfer, on_latency)
        if outcome is True:
            moved += 1
        elif outcome is False:
            failed += 1
    return moved, failed


def _migrate_one(src: "ContinuousEngine", req: Request, transfer,
                 on_latency=None) -> Optional[bool]:
    """ONE copy-then-cutover attempt — the shared per-sequence
    orchestration under both migrate_live_sequences and the
    DisaggregatedPool handoff worker (export -> transfer -> release on
    success / resume on failure, with the failure bookkeeping in
    exactly one place).  ``transfer(host_snapshot)`` returns True
    (installed), False (definitively not installed) or None
    (indeterminate — a tri-state wire send that did NOT resolve the
    two-generals tail itself, contract violation): None is treated as
    failed-and-resume with a loud warning, which is only safe because
    an unresolved transfer can at worst orphan a FRESH destination
    request (no shared handle -> no double-decode); handle-sharing
    senders must resolve before returning (_send_wire does).
    Returns True = moved, False = failed, None = nothing to do."""
    t0 = time.perf_counter()
    if req.trace is not None:
        # idempotent when the prefill-role freeze already opened it —
        # the phase spans freeze -> destination activation either way
        req.trace.phase("engine.handoff")
    try:
        snap = src.export_sequence(req)
    except (RuntimeError, TimeoutError) as e:
        log.debug("migration export failed: %s", e)
        src.kv_migrate_failures_total += 1
        # a timed-out export was ABANDONED (never freezes), but a
        # failed one may have frozen the slot first: unfreezing a
        # never-frozen sequence is a no-op, so always try
        try:
            src.resume_sequence(req)
        except (RuntimeError, TimeoutError):
            pass
        return False
    if snap is None:
        return None  # finished before the transfer could start
    tsp = (req.trace.begin("kv.transfer")
           if req.trace is not None else None)
    try:
        ok = transfer(snap)
    except Exception as e:  # noqa: BLE001 — rejection/socket death is
        # a per-sequence failure, not a drain abort: resume in place
        log.debug("migration transfer failed: %s", e)
        ok = False
    if tsp is not None:
        tsp.done(ok=bool(ok))
    if ok is None:
        log.warning(
            "kv_migrate transfer returned indeterminate (commit sent, "
            "ack lost) without resolving it; treating as failed — the "
            "destination may hold an orphaned copy")
        ok = False
    try:
        if ok:
            src.release_sequence(req)
            ms = (time.perf_counter() - t0) * 1e3
            src.observe_migration_ms(ms)
            if on_latency is not None:
                on_latency(ms)
            return True
        src.kv_migrate_failures_total += 1
        src.resume_sequence(req)
    except (RuntimeError, TimeoutError) as e:
        log.debug("migration cutover failed: %s", e)
    return False


class DisaggregatedPool:
    """Prefill/decode disaggregation over live paged-KV migration.

    Chunked prefill (PR 2) bounds the admission stall but prefill still
    competes with decode for the same chips; this pool splits them: N
    ``role="prefill"`` engines admit and chunk-prefill only, and every
    finished sequence is handed — KV blocks, logits row, scheduler
    state — to the ``role="decode"`` engine with the most free blocks
    (the load signal the block economy gives for free).  Decode ITL on
    the decode tier never pays prefill compute again; the handoff is a
    copy-then-cutover migration, so a failed transfer just decodes on
    the prefill engine (degraded, never wrong), and the REQUEST HANDLE
    is re-targeted in place — SSE streams survive the hop without a
    client reconnect.

    ``wire=True`` routes every handoff through the authenticated,
    length-framed ``kv_migrate`` stream (serving/gang.py) over
    loopback TCP — the same bytes a cross-host deployment ships — with
    the destination resolving the request handle from the migration-id
    registry; ``wire=False`` imports in-process.  Engine-shaped:
    runtimes (text.py), the model server's /metrics export and the
    benches front it exactly like ContinuousEngine.
    """

    def __init__(self, cfg, params, *, prefill_replicas: int = 1,
                 decode_replicas: int = 1, wire: bool = False,
                 migrate_token: str = "", sock_wrap=None,
                 seq_buckets=None, **kw):
        if int(kw.get("block_size", 0)) <= 0:
            raise ValueError(
                "disaggregation requires the paged pool (block_size > 0)")
        if prefill_replicas < 1 or decode_replicas < 1:
            raise ValueError("disaggregation needs >= 1 replica per role")
        kw.pop("role", None)
        self.prefill = [
            ContinuousEngine(cfg, params, role="prefill",
                             seq_buckets=seq_buckets, **kw)
            for _ in range(prefill_replicas)]
        self.decode = [
            ContinuousEngine(cfg, params, role="decode",
                             seq_buckets=seq_buckets, **kw)
            for _ in range(decode_replicas)]
        self.pools = self.prefill + self.decode
        #: guards the TIER LISTS (prefill/decode membership) against the
        #: rebalance actuator (ISSUE 15) racing the handoff worker's and
        #: submit's tier picks.  Engine internals stay mailbox-guarded
        #: as ever — this lock only covers which list an engine is on.
        self._tier_lock = threading.Lock()
        self.tier_rebalances_total = 0
        self._handoff_q: "queue.Queue" = queue.Queue()
        self._stopping = threading.Event()
        from collections import deque

        #: recent handoff latencies for the bench/debugging; the
        #: unbounded record is the engine-side histogram
        self.migration_latencies_ms: "deque[float]" = deque(maxlen=4096)
        self._servers = []
        if wire and not migrate_token:
            # the pool's tiers share a process: mint a per-pool secret
            # instead of running the loopback listener open (the
            # gang-token rule — an empty token silently opens the
            # channel); cross-process deployments pass their own
            import secrets

            migrate_token = secrets.token_hex(16)
        self._wire_token = migrate_token
        self._sock_wrap = sock_wrap
        if wire:
            # lazy import: gang.py imports this module
            from .gang import KvMigrationServer

            for eng in self.decode:
                self._servers.append(KvMigrationServer(
                    eng, token=migrate_token, sock_wrap=sock_wrap))
        for eng in self.prefill:
            eng.on_prefilled = (
                lambda req, _e=eng: self._handoff_q.put((_e, req)))
        self._worker = threading.Thread(
            target=self._pump, name="kv-migrate", daemon=True)
        self._worker.start()

    def _pump(self) -> None:
        """Handoff worker: the blocking half of every migration (device
        fetch, socket streaming, cutover waits) lives HERE, never on an
        engine scheduler thread (the analyzer's blocking-socket rule
        pins exactly that)."""
        while not self._stopping.is_set():
            try:
                src, req = self._handoff_q.get(timeout=0.1)
            except queue.Empty:
                continue
            # destination = most free blocks (rebalancing for free).
            # The engine OBJECT is captured (not its index): a tier
            # rebalance may rewrite the decode list between pick and
            # transfer, and the object stays a valid import target
            # either way.
            with self._tier_lock:
                di = max(range(len(self.decode)),
                         key=lambda i: self.decode[i]._alloc.free_blocks)
                deng = self.decode[di]
            if self._servers:
                def transfer(snap, _r=req, _d=di):
                    return self._send_wire(snap, _r, _d)
            else:
                def transfer(snap, _r=req, _e=deng):
                    return _e.import_sequence(snap, req=_r) is not None
            # any transfer failure degrades to local decode on the
            # prefill engine (_migrate_one resumes it there)
            _migrate_one(src, req, transfer,
                         self.migration_latencies_ms.append)

    def _send_wire(self, snap: dict, req: Request, di: int) -> bool:
        """One wire handoff with the commit-ack two-generals tail
        handled: a DEFINITIVE outcome (ack, explicit rejection, or a
        death before kv_commit went out — confirmed by the handle
        still being registered) resolves immediately; an INDETERMINATE
        one (commit delivered, ack lost) must NOT resume blind — the
        destination's server thread is installing the same request
        handle, and double-decoding it would duplicate client tokens.
        There we poll destination ownership for the import's bounded
        service time: installed -> late cutover (success), rejected ->
        ownership never appears -> resume after the grace."""
        from .gang import (
            migrate_sequence,
            register_migration_handle,
            unregister_migration_handle,
        )

        srv = self._servers[di]
        mid = register_migration_handle(req)
        st = migrate_sequence(snap, "127.0.0.1", srv.port,
                              token=self._wire_token, mid=mid,
                              sock_wrap=self._sock_wrap)
        if st is True:
            return True
        if st is False:
            # definitive: withdraw the handle if the server never took
            # it (pre-commit death); an explicit rejection consumed it
            unregister_migration_handle(mid)
            return False
        if unregister_migration_handle(mid):
            return False  # commit never arrived: source may resume
        # commit consumed, ack lost: the import is in flight on the
        # destination — wait out its bounded service time (mailbox +
        # grouped scatters; 60s mirrors import_sequence's own timeout)
        deadline = time.perf_counter() + 60.0
        while time.perf_counter() < deadline:
            if (self.decode[di]._find_req_slot(req) is not None
                    or req.done.is_set()):
                return True
            time.sleep(0.01)
        log.warning(
            "kv_migrate cutover unresolved after 60s (commit delivered, "
            "no ack, destination never installed): resuming the source")
        return False

    # -- engine-shaped surface --------------------------------------------

    def submit(self, prompt, max_new_tokens=None,
               temperature=None, top_p=None, top_k=None,
               priority=None, trace=None, session_id=None) -> Request:
        # admissions are role-gated: ONLY prefill engines take traffic
        # (least-loaded by queued + live), decode engines only import
        with self._tier_lock:
            eng = min(self.prefill,
                      key=lambda e: e._queue.qsize() + len(e._prefilling)
                      + int(e._active.sum()))
        return eng.submit(prompt, max_new_tokens, temperature,
                          top_p=top_p, top_k=top_k, priority=priority,
                          trace=trace, session_id=session_id)

    def generate(self, prompt, max_new_tokens=None, timeout: float = 120.0,
                 temperature=None, top_p=None, top_k=None) -> list[int]:
        return self.submit(prompt, max_new_tokens, temperature,
                           top_p=top_p, top_k=top_k).wait(timeout)

    def warmup(self, groups=None) -> None:
        for eng in self.pools:
            eng.warmup(groups)

    def flush_warmup_trace(self) -> None:
        for eng in self.pools:
            eng.flush_warmup_trace()

    def stop(self) -> None:
        self._stopping.set()
        self._worker.join(timeout=10)
        for srv in self._servers:
            srv.close()
        for eng in self.pools:
            eng.stop()

    @property
    def eos_id(self):
        return self.prefill[0].eos_id

    @eos_id.setter
    def eos_id(self, value) -> None:
        for eng in self.pools:
            eng.eos_id = value

    @property
    def default_max_new_tokens(self) -> int:
        return self.prefill[0].default_max_new_tokens

    @property
    def cfg(self):
        return self.prefill[0].cfg

    @property
    def tokens_emitted(self) -> int:
        return sum(e.tokens_emitted for e in self.pools)

    @property
    def prefix_hits(self) -> int:
        return sum(e.prefix_hits for e in self.pools)

    @property
    def prefix_tokens_saved(self) -> int:
        return sum(e.prefix_tokens_saved for e in self.pools)

    def tier_pressure(self) -> dict:
        """Per-tier load signal for the autoscaler's tier-rebalance
        decision (ISSUE 15): backlog per prefill replica (queued +
        mid-prefill sequences — the work the prefill tier has not
        finished) vs live decode sequences per decode replica.  GIL
        list/queue-size reads only."""
        with self._tier_lock:
            prefill, decode = list(self.prefill), list(self.decode)
        pb = sum(e._queue.qsize() + len(e._prefilling) for e in prefill)
        dl = sum(int(e._active.sum()) for e in decode)
        return {
            "prefill_pressure": pb / max(len(prefill), 1),
            "decode_pressure": dl / max(len(decode), 1),
            "prefill_replicas": len(prefill),
            "decode_replicas": len(decode),
        }

    def rebalance(self, prefill_replicas: int) -> bool:
        """Tier-ratio actuator (ISSUE 15): move engines between the
        prefill and decode tiers until the prefill tier holds
        ``prefill_replicas`` — chips are fungible across roles as the
        admission/decode mix shifts (Podracer).  Both tiers keep >= 1
        engine.  Runs on the CALLER's thread (the autoscaler loop):

        - prefill -> decode: the least-loaded prefill engine stops
          taking admissions (list membership gates ``submit``), its
          handoff hook drops, and its role flips — in-flight prefills
          finish and decode LOCALLY (degraded, never wrong: the same
          fallback a failed handoff takes).
        - decode -> prefill: the emptiest decode engine first drains
          its live sequences onto the surviving decode engines through
          ``migrate_live_sequences`` (copy-then-cutover — a failed
          move decodes in place and the flip is aborted), then flips.

        Wire-mode pools refuse: the per-decode-engine migration
        servers are placement state this actuator does not manage.
        Returns True when the tier split changed."""
        target = int(prefill_replicas)
        if self._servers:
            raise RuntimeError(
                "tier rebalance unsupported on wire=True pools")
        if not 1 <= target <= len(self.pools) - 1:
            raise ValueError(
                f"prefill_replicas {target} out of range "
                f"[1, {len(self.pools) - 1}]")
        changed = False
        while True:
            with self._tier_lock:
                delta = target - len(self.prefill)
                if delta == 0:
                    break
                if delta < 0:
                    # prefill -> decode: membership flip is enough; the
                    # role read happens at prefill completion, so a
                    # sequence mid-chunk just decodes where it is
                    eng = min(self.prefill,
                              key=lambda e: e._queue.qsize()
                              + len(e._prefilling))
                    self.prefill.remove(eng)
                    eng.on_prefilled = None
                    eng.role = "decode"
                    self.decode.append(eng)
                    self.tier_rebalances_total += 1
                    changed = True
                    continue
                # decode -> prefill: pick the emptiest donor, but drain
                # OUTSIDE the lock (migration ops carry 60s timeouts)
                eng = max(self.decode,
                          key=lambda e: e._alloc.free_blocks)
                rest = [d for d in self.decode if d is not eng]
            dst = max(rest, key=lambda e: e._alloc.free_blocks)
            moved, failed = migrate_live_sequences(eng, dst)
            if failed:
                # the donor still owns sequences: flipping it to
                # prefill would strand them behind admission-only
                # scheduling — abort, the next tick retries
                raise RuntimeError(
                    f"tier rebalance aborted: {failed} sequences "
                    "failed to drain off the donor decode engine")
            with self._tier_lock:
                if eng in self.decode and len(self.decode) > 1:
                    self.decode.remove(eng)
                    eng.role = "prefill"
                    eng.on_prefilled = (
                        lambda req, _e=eng:
                        self._handoff_q.put((_e, req)))
                    self.prefill.append(eng)
                    self.tier_rebalances_total += 1
                    changed = True
        return changed

    def stats(self) -> dict:
        """Numeric stats summed across the tiers (counters add; the
        capacity-style gauges add too — the pool's capacity IS the sum
        of its tiers'), plus the tier split.  RATIO gauges must not
        add: they are recomputed from the summed counters (acceptance)
        or allocation-weighted (fragmentation)."""
        merged: dict = {}
        per: list[dict] = []
        config_keys = {"kv_block_size", "prefill_budget"}
        for eng in self.pools:
            st = eng.stats()
            per.append(st)
            for k, v in st.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                if k in config_keys or k.startswith("aot_cache_"):
                    # the pool's engines share ONE artifact cache —
                    # summing its counters would multiply them by the
                    # replica count
                    merged.setdefault(k, v)
                else:
                    merged[k] = merged.get(k, 0) + v
        merged["spec_acceptance_rate"] = round(
            merged.get("spec_tokens_accepted_total", 0)
            / max(merged.get("spec_tokens_proposed_total", 0), 1), 4)
        allocated = (merged.get("kv_blocks_total", 0)
                     - merged.get("kv_blocks_free", 0))
        merged["kv_fragmentation_ratio"] = round(
            sum((st["kv_blocks_total"] - st["kv_blocks_free"])
                * st["kv_fragmentation_ratio"] for st in per)
            / allocated, 4) if allocated > 0 else 0.0
        merged["disagg_prefill_replicas"] = len(self.prefill)
        merged["disagg_decode_replicas"] = len(self.decode)
        return merged


def engine_kwargs(config: dict, *, default_eos=None,
                  default_max_new_tokens: int = 16) -> dict:
    """ContinuousEngine kwargs from a serving-config dict — shared by
    build_engine AND the serving gang (serving/gang.py), whose follower
    hosts must construct byte-identical programs from the same config."""
    return dict(
        num_slots=int(config.get("num_slots", 8)),
        decode_chunk=int(config.get("decode_chunk", 4)),
        prefill_budget=int(config.get("prefill_budget", 0)),
        temperature=float(config.get("temperature", 0.0)),
        eos_id=config.get("eos_id", default_eos),
        pipeline_depth=int(config.get("pipeline_depth", 2)),
        mesh_axes=config.get("mesh_axes"),
        prefix_cache=bool(config.get("prefix_cache", True)),
        min_prefix=int(config.get("min_prefix", 32)),
        prefix_segments=int(config.get("prefix_segments", 0)),
        segment_len=int(config.get("segment_len", 0)),
        spec_k=int(config.get("spec_k", 0)),
        spec_ngram=int(config.get("spec_ngram", 3)),
        block_size=int(config.get("block_size", 0)),
        num_blocks=int(config.get("num_blocks", 0)),
        host_blocks=int(config.get("host_blocks", 0)),
        host_watermark=float(config.get("host_watermark", 0.25)),
        role=str(config.get("role", "mixed")),
        default_max_new_tokens=int(
            config.get("max_new_tokens", default_max_new_tokens)),
    )


def resolve_model_source(config: dict, *, name: str = "model"):
    """(cfg, params) from a serving config's model source — the ONE
    resolution site shared by the in-process generator and every gang
    member (serving/gang.py), so ``params_ref``/``storage_path``
    semantics cannot drift between placements.

    ``adapter_path``: a ``save_adapter`` snapshot to merge into the base
    at load (kernel += A@B * scale) — after the merge the model is plain
    Llama, so TP sharding and int8 quantization compose unchanged."""
    ref = config.get("params_ref")
    if ref:
        cfg, params = fetch_mem(ref[len("mem://"):])
    elif config.get("storage_path"):
        cfg, params = llamalib.load_pretrained(config["storage_path"])
    else:
        raise RuntimeError(
            f"model {name}: need params_ref or storage_path (set "
            "storage_uri on the component spec — the storage initializer "
            "resolves it to storage_path)")
    adapter = config.get("adapter_path")
    if adapter:
        acfg, adapters = llamalib.load_adapter(adapter)
        cfg, params = llamalib.merge_adapter(acfg, params, adapters)
    if config.get("max_seq_len"):
        # serve-time override: with shared-prefix segments the SLOT pool
        # is sized for suffixes (cfg.max_seq_len), far below the
        # snapshot's trained context — the capacity knob deployments turn
        import dataclasses as _dc

        cfg = _dc.replace(cfg, max_seq_len=int(config["max_seq_len"]))
    return cfg, params


def apply_serving_quant(cfg, params, config: dict):
    """Honor the serving config's int8 knobs (``quant_weights`` /
    ``quant_kv``) — shared by build_engine and every gang member
    (serving/gang.py), so a quantized deployment quantizes identically on
    all hosts."""
    w = bool(config.get("quant_weights"))
    k = bool(config.get("quant_kv"))
    if not (w or k):
        return cfg, params
    return llamalib.quantize_for_serving(cfg, params, weights=w, kv=k)


def build_engine(cfg, params, config: dict, *, default_eos=None,
                 default_max_new_tokens: int = 16) -> "ContinuousEngine":
    """Engine from a serving-config dict — the ONE construction site shared
    by every runtime that fronts the engine (token-level and text), so
    knobs stay in sync.  Honors "warmup_groups": [] to skip warmup.
    ``short_pool_len`` (tokens) turns on the two-tier pool (TieredEngine):
    short conversations decode with windows bounded by it regardless of
    what the long pool is doing."""
    kw = engine_kwargs(
        config, default_eos=default_eos,
        default_max_new_tokens=default_max_new_tokens)
    # AOT program-artifact cache (serving/programs.py): constructed
    # HERE, not in engine_kwargs — engine_kwargs is also the
    # controller's validation probe and must stay side-effect-free
    kw["program_cache"] = programslib.build_program_cache(config)
    cfg, params = apply_serving_quant(cfg, params, config)
    short_len = config.get("short_pool_len")
    tier_lens = config.get("tier_lens")
    disagg = config.get("disaggregation")
    if disagg:
        # prefill/decode disaggregation (ISSUE 8): {"prefill": n,
        # "decode": m, "wire": bool} — n prefill-role engines hand
        # finished sequences to m decode-role engines by live paged-KV
        # migration, picked by free-block count
        if tier_lens or short_len:
            raise ValueError(
                "disaggregation does not compose with the tier ladder: "
                "route tiers to separate ISvcs instead")
        # token side channel first (the gang_token_file rule: configs
        # are cluster-readable); inline token for hand-rolled/test
        # configs; empty + wire => the pool mints a per-pool secret
        tok = str(disagg.get("token", ""))
        if disagg.get("token_file"):
            with open(disagg["token_file"]) as f:
                tok = f.read().strip()
        engine = DisaggregatedPool(
            cfg, params,
            prefill_replicas=int(disagg.get("prefill", 1)),
            decode_replicas=int(disagg.get("decode", 1)),
            wire=bool(disagg.get("wire", False)),
            migrate_token=tok,
            seq_buckets=config.get("seq_buckets"), **kw)
    elif tier_lens:
        engine = TieredEngine(
            cfg, params, tier_lens=[int(t) for t in tier_lens],
            tier_slots=config.get("tier_slots"),
            seq_buckets=config.get("seq_buckets"), **kw)
    elif short_len:
        engine = TieredEngine(
            cfg, params, short_len=int(short_len),
            short_slots=config.get("short_pool_slots"),
            seq_buckets=config.get("seq_buckets"), **kw)
    else:
        engine = ContinuousEngine(
            cfg, params, seq_buckets=config.get("seq_buckets"), **kw)
    groups = config.get("warmup_groups")
    if groups != []:
        engine.warmup([tuple(g) for g in groups] if groups else None)
    return engine


class ContinuousLlamaGenerator(Model):
    """Serving runtime over :class:`ContinuousEngine`.

    Unlike ``LlamaGenerator`` this model is **self-batching**: the server
    bypasses the micro-batcher and calls it from each request thread
    directly; concurrent requests coalesce inside the engine's slot pool
    at token boundaries instead of at HTTP arrival time.

    config:
      params_ref:       "mem://key" holding (LlamaConfig, params)
      num_slots, decode_chunk, temperature, eos_id, max_new_tokens,
      seq_buckets:      engine knobs (see ContinuousEngine)
    """

    self_batching = True

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None,
                 engine: Optional["ContinuousEngine"] = None):
        super().__init__(name, config)
        #: a prebuilt engine (the serving gang's rank-0 GangEngine) —
        #: load() then skips construction and just marks ready
        self.engine: Optional[ContinuousEngine] = engine

    def load(self) -> None:
        if self.engine is not None:
            self.ready = True
            return
        cfg, params = resolve_model_source(self.config, name=self.name)
        self.engine = build_engine(cfg, params, self.config)
        self.ready = True

    def stop(self) -> None:
        if self.engine is not None:
            self.engine.stop()
            self.engine = None
        super().stop()

    def predict_batch(self, instances):
        assert self.engine is not None, "model not loaded"
        reqs = [self.engine.submit(inst) for inst in instances]
        return [r.wait(timeout=300.0) for r in reqs]
