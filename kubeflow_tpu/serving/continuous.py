"""Continuous batching for LLM serving: cross-request decode scheduling.

[upstream: kserve/kserve -> python/huggingfaceserver (vLLM backend)] — the
reference's LLM runtime delegates to vLLM, whose defining capability is
*continuous batching*: requests join and leave the running decode batch at
token boundaries instead of waiting for the current batch to finish
(SURVEY.md §2.2 per-framework runtimes row).  ``LlamaGenerator``
(runtimes.py) decodes each micro-batch to completion — a request arriving
one token after a 64-token batch started waits ~64 token-steps for its
first token.  This module removes that wait.

TPU-first design (vs vLLM's CUDA paged-attention pool):

- **Slot pool, not pages.**  A fixed-shape KV cache of ``num_slots`` rows
  (the per-row-position cache from models/llama.py `_decode_attend`):
  XLA wants static shapes, so the pool is compiled once and requests map
  onto *slots*.  A retired slot is reused without clearing — the per-row
  causal mask makes stale KV past a row's live front invisible, exactly
  the ragged-batch argument LlamaGenerator already relies on.
- **Prefill as a batch-1 bucketed program, merged by scatter.**  Prompt
  prefill runs on a [1, bucket] shape (cost ∝ prompt, not ∝ pool) and a
  separate jitted merge scatters the row cache into the pool at the slot
  index.  One compile per bucket, one for the merge.
- **Decode as a chunked scan over the whole pool.**  Each dispatch runs
  ``decode_chunk`` sampling steps for ALL slots in one ``lax.scan``
  program; inactive slots ride along with their cache writes dropped
  (position pinned past ``max_seq_len``).  Chunking amortizes the
  host round trip that dominates per-token latency on a remote-dispatch
  backend (PERF.md: 16.8 ms/token floor through the tunnel); admission
  happens between chunks, so ``decode_chunk=1`` gives strict
  token-boundary admission and larger chunks trade admission latency for
  dispatch amortization.

All buffers are donated across dispatches, so the pool cache exists in
HBM exactly once.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import llama as llamalib
from .model import Model
from .storage import fetch_mem


@dataclass
class Request:
    """One generation request tracked through the engine."""

    prompt: list[int]
    max_new_tokens: int
    submitted_at: float = field(default_factory=time.perf_counter)
    #: engine step counter when the request was submitted / admitted
    submitted_step: int = 0
    admitted_step: int = -1
    slot: int = -1
    tokens: list[int] = field(default_factory=list)
    first_token_at: Optional[float] = None
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[Exception] = None

    def wait(self, timeout: Optional[float] = None) -> list[int]:
        if not self.done.wait(timeout):
            raise TimeoutError("generation did not complete in time")
        if self.error is not None:
            raise self.error
        return self.tokens

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


class ContinuousEngine:
    """Slot-pool continuous-batching decode engine over a Llama model.

    Parameters
    ----------
    cfg, params:    model config + weights (as in LlamaGenerator).
    num_slots:      pool width — max requests decoding concurrently.
    decode_chunk:   sampling steps per dispatch; admission happens between
                    dispatches (1 = admit at every token boundary).
    temperature:    0 = greedy; >0 = categorical sampling.
    eos_id:         optional stop token (host-checked between chunks).
    """

    def __init__(
        self,
        cfg: llamalib.LlamaConfig,
        params: Any,
        *,
        num_slots: int = 8,
        decode_chunk: int = 1,
        temperature: float = 0.0,
        eos_id: Optional[int] = None,
        seq_buckets: Optional[list[int]] = None,
        default_max_new_tokens: int = 16,
        pipeline_depth: int = 2,
    ):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if decode_chunk < 1:
            raise ValueError("decode_chunk must be >= 1")
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        self.cfg = cfg
        self.params = params
        self.num_slots = num_slots
        self.decode_chunk = decode_chunk
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.default_max_new_tokens = default_max_new_tokens
        #: chunks in flight on the device before the host blocks on a
        #: fetch: depth 2 overlaps chunk k's host round trip with chunk
        #: k+1's device compute (the tunnel's ~100ms/fetch floor would
        #: otherwise serialize into the decode timeline — PERF.md).  The
        #: schedule advanced at dispatch time is value-independent, so
        #: only EOS retirement lags by up to depth-1 chunks.
        self.pipeline_depth = pipeline_depth
        self.model = llamalib.Llama(cfg)

        cap = cfg.max_seq_len - 1
        raw = seq_buckets or [
            s for s in (32, 64, 128, 256, 512, 1024, 2048, 4096) if s < cap
        ] + [cap]
        self.seq_buckets = tuple(sorted({int(b) for b in raw if 1 <= int(b) <= cap}))
        if not self.seq_buckets:
            raise ValueError(f"no usable seq bucket <= {cap}")

        self._build_programs()
        self._init_pool()

        # host-side scheduler state
        self._queue: "queue.Queue[Request]" = queue.Queue()
        self._slots: list[Optional[Request]] = [None] * num_slots
        self._active = np.zeros(num_slots, dtype=bool)
        self._positions = np.zeros(num_slots, dtype=np.int32)
        self._remaining = np.zeros(num_slots, dtype=np.int64)
        self.step_counter = 0          # decode dispatches so far
        self.tokens_emitted = 0        # useful (delivered) tokens
        self._error: Optional[Exception] = None
        self._stop = threading.Event()
        self._gate = threading.Lock()
        self._wake = threading.Event()
        self._base_key = jax.random.PRNGKey(int.from_bytes(os.urandom(4), "little"))
        self._thread = threading.Thread(
            target=self._loop, name="continuous-engine", daemon=True)
        self._thread.start()

    # -- compiled programs -------------------------------------------------

    def _build_programs(self) -> None:
        cfg, model, temperature = self.cfg, self.model, self.temperature
        chunk = self.decode_chunk
        slots = self.num_slots

        def forward(params, cache, tok, positions):
            logits, mutated = model.apply(
                {"params": params, "cache": cache}, tok, positions,
                decode=True, mutable=["cache"])
            return logits, mutated["cache"]

        #: decode-attention window buckets: each decode dispatch attends
        #: only over cache slots below the smallest bucket covering every
        #: live position (+ the chunk about to be generated) — the KV read
        #: is the decode step's HBM bill, and early conversation turns
        #: must not stream the whole max_seq_len buffer
        self.attend_buckets = tuple(
            [b for b in (128, 256, 512, 1024, 2048) if b < cfg.max_seq_len]
            + [cfg.max_seq_len])

        def cache_shapes(batch: int):
            return jax.eval_shape(
                lambda k, t, p: model.init(k, t, p, decode=True),
                jax.ShapeDtypeStruct((2,), jnp.uint32),
                jax.ShapeDtypeStruct((batch, 1), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1), jnp.int32),
            )["cache"]

        pool_proto = cache_shapes(slots)
        row_proto = cache_shapes(1)
        # per-leaf batch axis, probed with batch=2 vs batch=1 so it stays
        # well-defined even when num_slots == 1 (cache_index has no batch
        # axis — it is informational and left untouched)
        probe_proto = cache_shapes(2)

        def batch_axis(p, r):
            diff = [i for i, (a, b) in enumerate(zip(p.shape, r.shape)) if a != b]
            if not diff:
                return None
            if len(diff) != 1:
                raise RuntimeError(
                    f"ambiguous batch axis between {p.shape} and {r.shape}")
            return diff[0]

        self._pool_shapes = pool_proto
        self._batch_axes = jax.tree.map(batch_axis, probe_proto, row_proto)

        def make_prefill(attend: int):
            wmodel = llamalib.Llama(cfg, decode_attend_len=attend)

            def prefill(params, prompt, lengths):
                """[g, bucket] ragged prefill -> (last-token logits [g,v],
                row cache), attending only over [0, attend)."""
                b, length = prompt.shape
                cache = jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes(b))
                positions = jnp.broadcast_to(
                    jnp.arange(length, dtype=jnp.int32)[None, :], (b, length))
                logits_all, mutated = wmodel.apply(
                    {"params": params, "cache": cache}, prompt, positions,
                    decode=True, mutable=["cache"])
                last = jnp.take_along_axis(
                    logits_all, (lengths - 1)[:, None, None], axis=1)[:, 0]
                return last, mutated["cache"]

            return jax.jit(prefill)

        self._prefill_programs: dict[int, Any] = {}

        def prefill_for(bucket: int):
            attend = next(b for b in self.attend_buckets if b >= bucket)
            if attend not in self._prefill_programs:
                self._prefill_programs[attend] = make_prefill(attend)
            return self._prefill_programs[attend]

        self._prefill_for = prefill_for

        # the plain (windowless) prefill stays for shape probing
        def prefill(params, prompt, lengths):
            b, length = prompt.shape
            cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes(b))
            positions = jnp.broadcast_to(
                jnp.arange(length, dtype=jnp.int32)[None, :], (b, length))
            logits_all, cache = forward(params, cache, prompt, positions)
            last = jnp.take_along_axis(
                logits_all, (lengths - 1)[:, None, None], axis=1)[:, 0]
            return last, cache

        axes = self._batch_axes

        def merge(pool_cache, pool_logits, row_cache, row_logits, slots):
            """Scatter a BATCH of prefilled row caches + their next-token
            logits into the pool at ``slots`` [g].  Padded admission rows
            carry slot == num_slots, which mode="drop" discards — one
            merge dispatch admits a whole burst of requests."""
            def leaf(pool, row, axis):
                if axis is None:
                    return pool
                idx = (slice(None),) * axis + (slots,)
                return pool.at[idx].set(row, mode="drop")

            merged = jax.tree.map(leaf, pool_cache, row_cache, axes)
            return merged, pool_logits.at[slots].set(row_logits, mode="drop")

        def make_decode(attend: int):
            wmodel = llamalib.Llama(cfg, decode_attend_len=attend)

            def decode(params, cache, logits, positions, active, key):
                """``chunk`` sampling steps for the whole pool in one
                program, attending only over cache slots [0, attend).

                Inactive slots still compute (the price of a static pool)
                but their cache writes drop: position is pinned to
                max_seq_len, where the per-row scatter's mode="drop"
                discards the write and the causal mask hides the slot from
                every live row.
                """
                safe = jnp.where(active, positions, cfg.max_seq_len)

                def step(carry, key):
                    cache, logits, pos = carry
                    if temperature > 0:
                        tok = jax.random.categorical(
                            key, logits.astype(jnp.float32) / temperature,
                            axis=-1)
                    else:
                        tok = jnp.argmax(logits, axis=-1)
                    tok = tok.astype(jnp.int32)
                    l, mutated = wmodel.apply(
                        {"params": params, "cache": cache}, tok[:, None],
                        pos[:, None], decode=True, mutable=["cache"])
                    nxt = jnp.where(active, pos + 1, cfg.max_seq_len)
                    return (mutated["cache"], l[:, -1, :], nxt), tok

                keys = jax.random.split(key, chunk)
                (cache, logits, pos), toks = jax.lax.scan(
                    step, (cache, logits, safe), keys)
                return cache, logits, toks.T  # toks: [slots, chunk]

            # donate pool buffers: the pool cache must exist in HBM once
            return jax.jit(decode, donate_argnums=(1, 2))

        self._decode_programs: dict[int, Any] = {}

        def decode_for(needed: int):
            attend = next(
                (b for b in self.attend_buckets if b >= needed),
                cfg.max_seq_len)
            if attend not in self._decode_programs:
                self._decode_programs[attend] = make_decode(attend)
            return self._decode_programs[attend]

        self._decode_for = decode_for

        # logits dtype follows the model's activation dtype (bf16 on TPU;
        # the pool logits buffer must match or the decode scan carry
        # type-mismatches)
        self._logits_dtype = jax.eval_shape(
            prefill,
            self.params,
            jax.ShapeDtypeStruct((1, self.seq_buckets[0]), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        )[0].dtype

        # donate pool buffers: the pool cache must exist in HBM once, not
        # once per in-flight dispatch
        self._merge = jax.jit(merge, donate_argnums=(0, 1))

    def _init_pool(self) -> None:
        self._pool_cache = jax.jit(lambda: jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), self._pool_shapes))()
        self._pool_logits = jnp.zeros(
            (self.num_slots, self.cfg.vocab_size), self._logits_dtype)

    # -- public API --------------------------------------------------------

    def warmup(self, groups: Optional[list[tuple[int, int]]] = None) -> None:
        """Precompile the (admission-group, prompt-bucket) prefill/merge
        programs and the decode program so the first real burst doesn't
        pay compile time mid-request.  Warmup prefills merge into the
        out-of-range slot (dropped by the scatter) and the warmup decode
        runs with every slot inactive (cache writes dropped), so pool
        state is untouched for real traffic.

        ``groups``: list of (group_size, seq_bucket); default = group
        sizes 1 and num_slots at the smallest bucket.  ``attend_buckets``
        (optional): decode-window buckets to precompile; default = the
        windows the warmed prompt buckets will first decode in.
        """
        if groups is None:
            groups = [(1, self.seq_buckets[0]),
                      (self.num_slots, self.seq_buckets[0])]
        warm_attends = set()
        for g, bucket in groups:
            bucket = next(b for b in self.seq_buckets if b >= bucket)
            row_logits, row_cache = self._prefill_for(bucket)(
                self.params, jnp.zeros((g, bucket), jnp.int32),
                jnp.ones(g, np.int32))
            self._pool_cache, self._pool_logits = self._merge(
                self._pool_cache, self._pool_logits, row_cache, row_logits,
                jnp.full(g, self.num_slots, jnp.int32))
            warm_attends.add(bucket + self.decode_chunk)
        for needed in sorted(warm_attends):
            self._pool_cache, self._pool_logits, toks = self._decode_for(
                needed)(
                self.params, self._pool_cache, self._pool_logits,
                jnp.full(self.num_slots, self.cfg.max_seq_len, jnp.int32),
                jnp.zeros(self.num_slots, bool),
                jax.random.PRNGKey(0))
            jax.block_until_ready(toks)

    def submit(
        self, prompt: list[int], max_new_tokens: Optional[int] = None
    ) -> Request:
        req = Request(
            prompt=list(map(int, prompt)),
            # explicit None check: 0 is a real request ("no completion",
            # OpenAI max_tokens=0) and must not fall through to the default
            max_new_tokens=int(
                self.default_max_new_tokens
                if max_new_tokens is None else max_new_tokens),
        )
        req.submitted_step = self.step_counter
        with self._gate:
            if self._error is not None:
                raise RuntimeError(
                    f"engine failed: {self._error!r}") from self._error
            if self._stop.is_set():
                raise RuntimeError("engine is shutting down")
            self._queue.put(req)
        self._wake.set()
        return req

    def generate(self, prompt: list[int], max_new_tokens: Optional[int] = None,
                 timeout: float = 120.0) -> list[int]:
        return self.submit(prompt, max_new_tokens).wait(timeout)

    def stop(self) -> None:
        with self._gate:
            self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.error = RuntimeError("engine shut down")
            req.done.set()
        for req in self._slots:
            if req is not None and not req.done.is_set():
                req.error = RuntimeError("engine shut down")
                req.done.set()

    # -- scheduler loop ----------------------------------------------------

    def _admit(self) -> None:
        """Prefill queued requests into free slots (between decode chunks).

        Admissions are BATCHED: waiting requests group by prompt bucket and
        each group runs as one multi-row prefill + one multi-slot merge —
        a burst of 8 requests costs 2 dispatches, not 16 (each dispatch
        pays the remote-dispatch latency floor, PERF.md)."""
        free = [i for i, r in enumerate(self._slots) if r is None]
        taken: list[tuple[Request, list[int], int]] = []  # (req, prompt, slot)
        while free:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            # budget the KV cache: prompt + generated tokens must fit
            # max_seq_len — writes past it are silently dropped by the
            # per-row scatter and decode would return garbage from a
            # frozen cache (the same guard LlamaGenerator applies at load)
            if req.max_new_tokens >= self.cfg.max_seq_len:
                req.max_new_tokens = self.cfg.max_seq_len - 1
            cap = min(self.seq_buckets[-1],
                      self.cfg.max_seq_len - req.max_new_tokens)
            prompt = req.prompt[-cap:]  # left-truncate, keep the tail
            if not prompt:
                # empty prompt -> empty continuation (runtimes.py rule)
                req.done.set()
                continue
            taken.append((req, prompt, free.pop(0)))
        if not taken:
            return
        groups: dict[int, list[tuple[Request, list[int], int]]] = {}
        for req, prompt, slot in taken:
            bucket = next(b for b in self.seq_buckets if b >= len(prompt))
            groups.setdefault(bucket, []).append((req, prompt, slot))
        for bucket, members in groups.items():
            # pad the group size up to a power of two (bounded compile
            # count); pad rows target the out-of-range slot, which the
            # merge scatter drops
            g = 1
            while g < len(members):
                g *= 2
            g = min(g, self.num_slots)
            try:
                toks = np.zeros((g, bucket), np.int32)
                lengths = np.ones(g, np.int32)
                slots = np.full(g, self.num_slots, np.int32)
                for j, (req, prompt, slot) in enumerate(members):
                    toks[j, : len(prompt)] = prompt
                    lengths[j] = len(prompt)
                    slots[j] = slot
                row_logits, row_cache = self._prefill_for(bucket)(
                    self.params, jnp.asarray(toks), jnp.asarray(lengths))
                self._pool_cache, self._pool_logits = self._merge(
                    self._pool_cache, self._pool_logits,
                    row_cache, row_logits, jnp.asarray(slots))
                for req, prompt, slot in members:
                    self._slots[slot] = req
                    self._active[slot] = True
                    self._positions[slot] = len(prompt)
                    self._remaining[slot] = req.max_new_tokens
                    req.slot = slot
                    req.admitted_step = self.step_counter
            except Exception as e:  # noqa: BLE001 — fail this group only
                for req, _, _ in members:
                    req.error = e
                    req.done.set()

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except Exception as e:  # noqa: BLE001 — a dead engine thread must
            # not strand waiters: fail everything in flight and refuse new
            # submissions (submit() re-raises self._error)
            with self._gate:
                self._error = e
            for req in self._slots:
                if req is not None and not req.done.is_set():
                    req.error = e
                    req.done.set()
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                req.error = e
                req.done.set()

    def _loop_inner(self) -> None:
        # in-flight chunk dispatches: (device tokens, [(slot, req, take)])
        pending: list[tuple[Any, list[tuple[int, Request, int]]]] = []
        while not self._stop.is_set():
            self._admit()
            if not self._active.any():
                # drain the tail, then wait for work without spinning
                while pending:
                    self._process(*pending.pop(0))
                if self._active.any() or not self._queue.empty():
                    continue  # _process freed slots or work arrived
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            self.step_counter += 1
            key = jax.random.fold_in(self._base_key, self.step_counter)
            snapshot = [
                (slot, self._slots[slot],
                 int(min(self.decode_chunk, self._remaining[slot])))
                for slot in range(self.num_slots)
                if self._active[slot] and self._slots[slot] is not None
            ]
            # window = smallest attend bucket covering every live position
            # plus this chunk — early turns read KV proportional to the
            # conversation front, not max_seq_len
            needed = int(self._positions[self._active].max()) + self.decode_chunk
            # pass NUMPY COPIES that are never mutated again: the CPU
            # backend zero-copies numpy buffers across the jit boundary,
            # and the schedule advance below mutates self._positions /
            # self._active while the async-dispatched decode may not have
            # executed yet — an aliased input then reads ADVANCED
            # positions (writes land one slot off, intermittently, under
            # dispatch-ahead pipelining; reproduced 3/10 before this fix)
            self._pool_cache, self._pool_logits, toks = self._decode_for(
                needed)(
                self.params, self._pool_cache, self._pool_logits,
                self._positions.copy(), self._active.copy(), key)
            # advance the value-independent schedule NOW so the next chunk
            # can dispatch before this one's tokens are fetched
            for slot, req, take in snapshot:
                self._positions[slot] += self.decode_chunk
                self._remaining[slot] -= take
                if self._remaining[slot] <= 0:
                    # slot is schedulable for a new occupant immediately;
                    # the request itself resolves when its tokens arrive
                    self._slots[slot] = None
                    self._active[slot] = False
            pending.append((toks, snapshot))
            if len(pending) >= self.pipeline_depth:
                self._process(*pending.pop(0))
        while pending:
            self._process(*pending.pop(0))

    def _process(self, toks_dev, snapshot) -> None:
        """Fetch one chunk's tokens (blocks) and deliver them."""
        toks = np.asarray(jax.device_get(toks_dev))  # [slots, chunk]
        now = time.perf_counter()
        for slot, req, take in snapshot:
            if req.done.is_set():
                continue  # EOS-retired by an earlier chunk
            emitted = toks[slot, :take].tolist()
            done = False
            if self.eos_id is not None and self.eos_id in emitted:
                emitted = emitted[: emitted.index(self.eos_id) + 1]
                done = True
                # free the slot unless a new occupant already claimed it
                # (max_new-tokens freeing happens at dispatch time)
                if self._slots[slot] is req:
                    self._slots[slot] = None
                    self._active[slot] = False
                    self._remaining[slot] = 0
            if emitted and req.first_token_at is None:
                req.first_token_at = now
            req.tokens.extend(emitted)
            self.tokens_emitted += len(emitted)
            if done or len(req.tokens) >= req.max_new_tokens:
                req.done.set()


def build_engine(cfg, params, config: dict, *, default_eos=None,
                 default_max_new_tokens: int = 16) -> "ContinuousEngine":
    """Engine from a serving-config dict — the ONE construction site shared
    by every runtime that fronts the engine (token-level and text), so
    knobs stay in sync.  Honors "warmup_groups": [] to skip warmup."""
    engine = ContinuousEngine(
        cfg, params,
        num_slots=int(config.get("num_slots", 8)),
        decode_chunk=int(config.get("decode_chunk", 4)),
        temperature=float(config.get("temperature", 0.0)),
        eos_id=config.get("eos_id", default_eos),
        seq_buckets=config.get("seq_buckets"),
        pipeline_depth=int(config.get("pipeline_depth", 2)),
        default_max_new_tokens=int(
            config.get("max_new_tokens", default_max_new_tokens)),
    )
    groups = config.get("warmup_groups")
    if groups != []:
        engine.warmup([tuple(g) for g in groups] if groups else None)
    return engine


class ContinuousLlamaGenerator(Model):
    """Serving runtime over :class:`ContinuousEngine`.

    Unlike ``LlamaGenerator`` this model is **self-batching**: the server
    bypasses the micro-batcher and calls it from each request thread
    directly; concurrent requests coalesce inside the engine's slot pool
    at token boundaries instead of at HTTP arrival time.

    config:
      params_ref:       "mem://key" holding (LlamaConfig, params)
      num_slots, decode_chunk, temperature, eos_id, max_new_tokens,
      seq_buckets:      engine knobs (see ContinuousEngine)
    """

    self_batching = True

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None):
        super().__init__(name, config)
        self.engine: Optional[ContinuousEngine] = None

    def load(self) -> None:
        ref = self.config["params_ref"]
        cfg, params = fetch_mem(ref[len("mem://"):])
        self.engine = build_engine(cfg, params, self.config)
        self.ready = True

    def stop(self) -> None:
        if self.engine is not None:
            self.engine.stop()
            self.engine = None
        super().stop()

    def predict_batch(self, instances):
        assert self.engine is not None, "model not loaded"
        reqs = [self.engine.submit(inst) for inst in instances]
        return [r.wait(timeout=300.0) for r in reqs]
