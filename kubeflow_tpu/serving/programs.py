"""AOT program-artifact cache: persist the warmed program ladder to disk.

Every reactive path in the platform bottoms out on the same tax: cold
start, scale-from-zero, and gang resize all pay the full XLA compile
wall for a program ladder that is bit-identical to the one some other
replica (or the same replica, one boot ago) already compiled.  r18
measured cold start at ~3 s solo / ~9 s under contention and r13 showed
>98% of a gang resize is new-degree compiles — versus ~20 ms of actual
drain+resume.

This module closes that gap with two pieces:

``ProgramArtifactCache``
    A shared on-disk store of serialized XLA executables, keyed by
    (model fingerprint, mesh degree, program family, rung/shape
    signature, jax version, backend).  Entries are published with the
    same manifest-verified atomic protocol as :mod:`.storage`'s KV
    spill tier — payload fsync → manifest fsync → directory rename —
    so a reader either sees a complete, checksummed entry or nothing.
    A corrupt or torn entry is DETECTED (size+sha256 per file), counted,
    deleted, and degraded to a normal compile; it is never a crash.
    Replicas share one cache root, so the cluster compiles each
    (model, degree, rung) once.

``AotProgram``
    A per-program wrapper installed under the engine's
    :class:`~..analysis.runtime.RecompileGuard`.  While the engine is
    warming (guard unarmed), unseen shape signatures consult the cache:
    hit → deserialize and execute the stored artifact, miss → AOT
    lower+compile, execute, and publish.  Once the engine seals
    (``RecompileCounter.armed``), the wrapper never touches disk again
    — unknown signatures fall through to the plain jitted callable,
    exactly today's lazy-compile behaviour, so artifact I/O can never
    run on the scheduler thread.

Parity bars: greedy decode is bit-identical cache-on vs cache-off (the
executable serialized is the same one a plain ``jit`` would build), and
``jit_recompiles_total == 0`` post-warmup is preserved because loaded
artifacts bypass the jit cache entirely while misses are compiled via
the AOT ``lower().compile()`` path, which the guard does not count.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import shutil
import threading
import time
import uuid
from typing import Any, Callable, Optional

import jax

ARTIFACT_MANIFEST = "artifact.json"
PAYLOAD_NAME = "program.bin"

#: staging dirs older than this are presumed orphaned by a crashed
#: publisher and are swept before the next publish of the same key
STAGING_STALE_SECONDS = 3600.0

#: signature sentinel: this sig failed when executed from an artifact —
#: route it through the plain jitted callable forever (XLA validates
#: inputs before donating, so the failed call consumed nothing)
_POISONED = object()


def _fsync_dir(path: str) -> None:
    """Fsync a directory so the rename that published an entry is
    durable; degrades to a no-op on platforms without dir-fd fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def model_fingerprint(cfg: Any, params: Any) -> str:
    """Structural fingerprint of (model config, parameter tree).

    Hashes the config record plus the params treedef and per-leaf
    shape/dtype — NOT the weight values: two checkpoints of the same
    architecture share one program ladder because weights are runtime
    inputs to the compiled executable, not part of its HLO.
    """
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        rec = dataclasses.asdict(cfg)
    else:
        rec = {k: v for k, v in sorted(vars(cfg).items())
               if not k.startswith("_")}
    leaves, treedef = jax.tree_util.tree_flatten(params)
    h = hashlib.sha256()
    h.update(json.dumps(rec, sort_keys=True, default=str).encode())
    h.update(str(treedef).encode())
    for x in leaves:
        h.update(str(getattr(x, "shape", ())).encode())
        h.update(str(getattr(x, "dtype", type(x).__name__)).encode())
    return h.hexdigest()[:16]


def cache_key_base(cfg: Any, params: Any, mesh: Any = None,
                   **knobs: Any) -> str:
    """The per-engine half of the artifact key: model fingerprint, mesh
    degree, jax version, backend, and the program-shaping engine knobs
    (decode chunk, prefill budget, spec depth, block size, ...).  The
    per-program half — family and shape signature — is appended by
    :class:`AotProgram` at call time."""
    if mesh is not None:
        degree = "x".join(
            f"{k}{v}" for k, v in sorted(dict(mesh.shape).items()))
    else:
        degree = "1"
    knob_s = ",".join(f"{k}={knobs[k]}" for k in sorted(knobs))
    return "|".join([
        model_fingerprint(cfg, params), degree, jax.__version__,
        jax.default_backend(), knob_s,
    ])


class ProgramArtifactCache:
    """Verified on-disk store of serialized XLA executables.

    Publish protocol (the :mod:`.storage` idiom): write payload +
    fsync, write a manifest recording size and sha256 + fsync, fsync
    the staging dir, then a single atomic ``os.rename`` into place.
    Concurrent publishers of one key race on the rename; the loser
    verifies the winner's entry instead of clobbering it.  ``load``
    verifies size and sha256 against the manifest before returning
    bytes — a torn or corrupt entry is deleted and reported as a miss.
    """

    def __init__(self, root: str, *, fsync: bool = True,
                 chaos: Any = None):
        self.root = str(root)
        self.fsync = bool(fsync)
        self.chaos = chaos
        self._mu = threading.Lock()
        # bare += across threads loses increments; every counter bump
        # takes the lock
        self._hits = 0
        self._misses = 0
        self._load_failures = 0
        self._published = 0
        self._bytes_read = 0
        self._bytes_written = 0

    # -- key / path helpers -------------------------------------------

    @staticmethod
    def entry_key(base: str, family: str, sig: str) -> str:
        h = hashlib.sha256()
        h.update(base.encode())
        h.update(b"|")
        h.update(family.encode())
        h.update(b"|")
        h.update(sig.encode())
        return h.hexdigest()[:32]

    def _entry_dir(self, key: str) -> str:
        return os.path.join(self.root, key)

    # -- counters -----------------------------------------------------

    def _bump(self, attr: str, n: int = 1) -> None:
        with self._mu:
            setattr(self, attr, getattr(self, attr) + n)

    def note_hit(self) -> None:
        self._bump("_hits")

    def note_miss(self) -> None:
        self._bump("_misses")

    def note_load_failure(self) -> None:
        self._bump("_load_failures")

    # -- load ---------------------------------------------------------

    def load(self, key: str) -> Optional[bytes]:
        """Bytes of a verified entry, or None.

        Counts load failures (and deletes the offending entry so a
        later publish can replace it) but NOT hits/misses — the caller
        still has to deserialize, which can independently fail.
        """
        entry_dir = self._entry_dir(key)
        man_path = os.path.join(entry_dir, ARTIFACT_MANIFEST)
        if not os.path.exists(man_path):
            return None
        try:
            with open(man_path, "r", encoding="utf-8") as f:
                manifest = json.load(f)
            rec = manifest["files"][PAYLOAD_NAME]
            path = os.path.join(entry_dir, PAYLOAD_NAME)
            with open(path, "rb") as f:
                blob = f.read()
            if len(blob) != int(rec["size"]):
                raise ValueError(
                    f"torn payload: {len(blob)} != {rec['size']}")
            if hashlib.sha256(blob).hexdigest() != rec["sha256"]:
                raise ValueError("payload checksum mismatch")
        except Exception:  # analysis: ok swallowed-exception — counted in aot_cache_load_failures_total; any defect here degrades to a normal compile by contract
            # corrupt/torn entry: detected, counted, removed — the
            # caller degrades to a normal compile, never a crash
            self.note_load_failure()
            shutil.rmtree(entry_dir, ignore_errors=True)
            return None
        self._bump("_bytes_read", len(blob))
        return blob

    def invalidate(self, key: str) -> None:
        """Drop an entry that verified on disk but failed downstream
        (e.g. undeserializable after a jax minor bump the version key
        missed) so the next publish can replace it."""
        shutil.rmtree(self._entry_dir(key), ignore_errors=True)

    def verify(self, key: str) -> bool:
        """True iff the entry exists and passes manifest verification
        (reads the payload; used by publish losers and tests)."""
        entry_dir = self._entry_dir(key)
        man_path = os.path.join(entry_dir, ARTIFACT_MANIFEST)
        if not os.path.exists(man_path):
            return False
        try:
            with open(man_path, "r", encoding="utf-8") as f:
                manifest = json.load(f)
            rec = manifest["files"][PAYLOAD_NAME]
            path = os.path.join(entry_dir, PAYLOAD_NAME)
            if os.path.getsize(path) != int(rec["size"]):
                return False
            return _sha256_file(path) == rec["sha256"]
        except Exception:  # analysis: ok swallowed-exception — verify() IS the failure probe; any unreadable/torn state simply verifies False
            return False

    # -- publish ------------------------------------------------------

    def _sweep_stale_staging(self, key: str) -> None:
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        prefix = f".staging-{key}-"
        now = time.time()
        for name in names:
            if not name.startswith(prefix):
                continue
            path = os.path.join(self.root, name)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            if age > STAGING_STALE_SECONDS:
                shutil.rmtree(path, ignore_errors=True)

    def publish(self, key: str, payload: bytes,
                meta: Optional[dict] = None) -> bool:
        """Atomically publish ``payload`` under ``key``.

        Returns True if this call installed (or verified an already-
        installed) entry.  Crash-safe: a reader never observes a
        partially-written entry because the rename is the only step
        that makes it visible, and everything renamed was fsync'd.
        """
        entry_dir = self._entry_dir(key)
        if os.path.exists(os.path.join(entry_dir, ARTIFACT_MANIFEST)):
            return True
        os.makedirs(self.root, exist_ok=True)
        self._sweep_stale_staging(key)
        tmp_dir = os.path.join(
            self.root,
            f".staging-{key}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        os.makedirs(tmp_dir)
        try:
            path = os.path.join(tmp_dir, PAYLOAD_NAME)
            with open(path, "wb") as f:
                f.write(payload)
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            manifest = {
                "key": key,
                "files": {PAYLOAD_NAME: {
                    "size": len(payload),
                    "sha256": hashlib.sha256(payload).hexdigest(),
                }},
                "meta": dict(meta or {}),
            }
            man_path = os.path.join(tmp_dir, ARTIFACT_MANIFEST)
            with open(man_path, "w", encoding="utf-8") as f:
                json.dump(manifest, f)
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            if self.fsync:
                _fsync_dir(tmp_dir)
            try:
                os.rename(tmp_dir, entry_dir)
            except OSError:
                # lost the publish race: verify the winner instead of
                # clobbering a good entry with our duplicate
                shutil.rmtree(tmp_dir, ignore_errors=True)
                return self.verify(key)
            if self.fsync:
                _fsync_dir(self.root)
        except Exception:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
        self._bump("_published")
        self._bump("_bytes_written", len(payload))
        if self.chaos is not None:
            # fault-plan seam: tear the tail off a just-published
            # artifact so tier-1 proves torn entries degrade to a
            # normal compile (mirrors KvSpillStore's spill_torn seam)
            for torn in self.chaos.due_spill_torn():
                self._tear(entry_dir, torn)
        return True

    @staticmethod
    def _tear(entry_dir: str, torn_bytes: Optional[int]) -> None:
        path = os.path.join(entry_dir, PAYLOAD_NAME)
        try:
            size = os.path.getsize(path)
            cut = torn_bytes if torn_bytes is not None else max(
                1, size // 2)
            with open(path, "r+b") as f:
                f.truncate(max(0, size - cut))
        except OSError:
            pass

    # -- stats --------------------------------------------------------

    def entries(self) -> list:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(n for n in names if not n.startswith("."))

    def stats(self) -> dict:
        entries = self.entries()
        nbytes = 0
        for name in entries:
            path = os.path.join(self.root, name, PAYLOAD_NAME)
            try:
                nbytes += os.path.getsize(path)
            except OSError:
                pass
        with self._mu:
            return {
                "aot_cache_hits_total": self._hits,
                "aot_cache_misses_total": self._misses,
                "aot_cache_load_failures_total": self._load_failures,
                "aot_cache_published_total": self._published,
                "aot_cache_bytes_read_total": self._bytes_read,
                "aot_cache_bytes_written_total": self._bytes_written,
                "aot_cache_entries": len(entries),
                "aot_cache_bytes": nbytes,
            }


class AotProgram:
    """Wrap one engine program with artifact-backed AOT compilation.

    Sits UNDER the :class:`~..analysis.runtime.RecompileGuard` (the
    guard reads through to ``_jitted`` for its cache-size probe, and
    loaded artifacts never touch the jit cache, so the recompiles==0
    bar is judged on exactly the same evidence as without the cache).

    Call path per shape signature:

    * known signature  → stored executable (or, if poisoned, the plain
      jitted callable) — no disk I/O, no locks beyond a dict get;
    * unknown + UNSEALED → cache load (hit: deserialize + run) else
      AOT ``lower().compile()`` + run + publish;
    * unknown + SEALED → plain jitted callable: today's lazy-compile
      behaviour, counted by the guard exactly as before.  The seal
      predicate is the engine's ``RecompileCounter.armed``, which flips
      before the scheduler thread starts — so artifact I/O is
      structurally impossible on the dispatch path.
    """

    def __init__(self, fn: Callable, *, cache: ProgramArtifactCache,
                 key_base: str, family: str,
                 sealed: Callable[[], bool],
                 observer: Optional[Callable] = None):
        self._fn = fn
        # RecompileGuard compatibility: the guard probes
        # ``getattr(program, "_jitted", program)`` for its cache-size
        # counter — read through to the real jitted callable
        self._jitted = getattr(fn, "_jitted", fn)
        self.cache = cache
        self.key_base = key_base
        self.family = family
        self._sealed = sealed
        self._observer = observer
        self._execs: dict = {}

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    @staticmethod
    def _sig(args: tuple):
        leaves, treedef = jax.tree_util.tree_flatten(args)
        # arrays key on (shape, dtype); Python scalars key on their
        # TYPE only — jit shares one trace across scalar values, and
        # the traced value is a dynamic input, not baked into the HLO
        return treedef, tuple(
            (x.shape, x.dtype.name) if hasattr(x, "dtype")
            else type(x).__name__
            for x in leaves)

    def _disk_key(self, sig) -> str:
        treedef, avals = sig
        return ProgramArtifactCache.entry_key(
            self.key_base, self.family, f"{treedef}|{avals}")

    def __call__(self, *args):
        sig = self._sig(args)
        exe = self._execs.get(sig)
        if exe is not None:
            if exe is _POISONED:
                return self._fn(*args)
            try:
                return exe(*args)
            except Exception:  # analysis: ok swallowed-exception — counted via note_load_failure and retried on plain jit, which re-raises any real input error
                # a loaded artifact that will not execute here (backend
                # drift the version key missed, donation-layout skew):
                # poison the signature and serve it via plain jit from
                # now on.  Safe to retry because XLA validates inputs
                # before donating — the failed call consumed nothing.
                self._execs[sig] = _POISONED
                self.cache.note_load_failure()
                return self._fn(*args)
        if self._sealed():
            # post-seal unknown signature: exactly today's lazy
            # compile; never any disk I/O on the scheduler thread
            return self._fn(*args)
        return self._cold_call(sig, args)

    def _cold_call(self, sig, args):
        from jax.experimental import serialize_executable as se
        key = self._disk_key(sig)
        t0 = time.perf_counter()
        blob = self.cache.load(key)
        if blob is not None:
            try:
                payload, in_tree, out_tree = pickle.loads(blob)  # analysis: ok unsafe-pickle — blob is size+sha256-verified against the entry manifest before unpickling, same trust root as the artifact itself
                exe = se.deserialize_and_load(payload, in_tree,
                                              out_tree)
                out = exe(*args)
            except Exception:  # analysis: ok swallowed-exception — counted via note_load_failure; control falls through to the normal compile path below
                self.cache.note_load_failure()
                self.cache.invalidate(key)
            else:
                self._execs[sig] = exe
                self.cache.note_hit()
                self._note(t0, "aot.load")
                return out
        self.cache.note_miss()
        try:
            compiled = self._fn.lower(*args).compile()
        except Exception:  # analysis: ok swallowed-exception — the plain-jit fallback re-raises any real trace error; only AOT-specific lowering refusals are absorbed
            # a program that refuses AOT lowering falls back to plain
            # jit for good — parity over speed
            self._execs[sig] = _POISONED
            return self._fn(*args)
        out = compiled(*args)
        self._execs[sig] = compiled
        try:
            payload, in_tree, out_tree = se.serialize(compiled)
            self.cache.publish(
                key, pickle.dumps((payload, in_tree, out_tree)),
                meta={"family": self.family})
        except Exception:  # analysis: ok swallowed-exception — persistence is best-effort; the compiled program already served this call and stays in memory
            # unserializable executable (backend without AOT export):
            # the compile still served this call and future calls hit
            # the in-memory entry — only persistence is lost
            pass
        self._note(t0, "compile")
        return out

    def _note(self, t0: float, outcome: str) -> None:
        if self._observer is not None:
            self._observer(self.family, outcome, t0,
                           time.perf_counter())


class WarmObserver:
    """Cache-less stand-in for :class:`AotProgram`: times each first
    compile per shape signature during warmup so the ``engine.warmup``
    trace gets per-family/rung spans even with no artifact cache
    configured.  Post-seal it is a single predicate call of overhead."""

    def __init__(self, fn: Callable, *, family: str,
                 sealed: Callable[[], bool],
                 observer: Optional[Callable] = None):
        self._fn = fn
        self._jitted = getattr(fn, "_jitted", fn)
        self.family = family
        self._sealed = sealed
        self._observer = observer
        self._seen: set = set()

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    def __call__(self, *args):
        if self._sealed():
            return self._fn(*args)
        sig = AotProgram._sig(args)
        if sig in self._seen:
            return self._fn(*args)
        self._seen.add(sig)
        t0 = time.perf_counter()
        out = self._fn(*args)
        if self._observer is not None:
            self._observer(self.family, "compile", t0,
                           time.perf_counter())
        return out


# -- conf-freeze validation + construction ----------------------------

_AOT_KEYS = ("root", "fsync")


def validate_aot(spec: Any) -> None:
    """Conf-freeze validation of the ``aot:`` knob family — raises
    ``ValueError`` listing every problem so the controller reports ONE
    Failed status per bad freeze (the PR 4/7/9 convention)."""
    if not isinstance(spec, dict):
        raise ValueError(
            f"aot must be a mapping, got {type(spec).__name__}")
    problems = []
    unknown = sorted(set(spec) - set(_AOT_KEYS))
    if unknown:
        problems.append(
            f"unknown aot keys {unknown} (known: {list(_AOT_KEYS)})")
    root = spec.get("root")
    if not isinstance(root, str) or not root.strip():
        problems.append("aot.root must be a non-empty path string")
    fsync = spec.get("fsync", True)
    if not isinstance(fsync, bool):
        problems.append(
            f"aot.fsync must be a bool, got {type(fsync).__name__}")
    if problems:
        raise ValueError("; ".join(problems))


def build_program_cache(config: Optional[dict]):
    """The single construction seam: a validated
    :class:`ProgramArtifactCache` from a serving config's ``aot:``
    block, or None when the block is absent.  Kept OUT of
    ``engine_kwargs`` so config validation stays side-effect-free."""
    spec = (config or {}).get("aot")
    if not spec:
        return None
    validate_aot(spec)
    return ProgramArtifactCache(
        spec["root"], fsync=spec.get("fsync", True))
