"""InferenceService reconciler + router/autoscaler.

The KServe control plane rebuilt on this cluster (SURVEY.md §2.2, §3.3)
[upstream: kserve/kserve -> pkg/controller/v1beta1/inferenceservice]:

- reconcile InferenceService -> resolve ServingRuntime (explicit or
  model-format auto-selection) -> run the storage initializer -> host the
  predictor Model in ModelServer replicas -> phase Ready + url;
- a Router per ISvc gives the stable URL and round-robins replicas (the
  istio/knative routing tier), with knative-activator-style scale-from-zero:
  a request arriving with no live replica triggers scale-up and waits;
- the autoscaler loop (KPA analog) scales replicas between min/max on
  observed concurrency per replica, and to zero after an idle window when
  ``min_replicas == 0``;
- a transformer component chains in front of the predictor over HTTP,
  exactly KServe's transformer -> predictor hop.
"""

from __future__ import annotations

import importlib
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ..api.inference import (
    KIND_INFERENCE_SERVICE,
    KIND_SERVING_RUNTIME,
    ComponentSpec,
    InferenceService,
    InferenceServicePhase,
    ServingRuntime,
    select_runtime,
)
from ..controlplane.controller import Controller, Result
from ..controlplane.store import NotFound, Store
from ..utils.net import allocate_port
from .model import Model
from .server import ModelServer
from .storage import download

SCALE_IDLE_SECONDS = 2.0  # idle window before scale-down (KPA-ish)
ACTIVATION_TIMEOUT = 15.0


def resolve_class(ref: str) -> type:
    """'pkg.module:Class' -> class object (ServingRuntime.server_class)."""
    mod, _, cls = ref.partition(":")
    return getattr(importlib.import_module(mod), cls)


class Router:
    """Stable URL in front of N replica servers: round-robin + activator."""

    def __init__(self, activate: Callable[[], None], port: Optional[int] = None):
        self.port = port or allocate_port()
        self._backends: list[str] = []
        self._explain_backends: list[str] = []  # ``:explain`` verb tier
        self._rr = 0
        self._lock = threading.Lock()
        self._activate = activate
        self.last_request_time = 0.0
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _proxy(self) -> None:
                router.last_request_time = time.time()
                explain = self.path.endswith(":explain")
                backend = router._pick(explain)
                if backend is None:
                    router._activate()
                    deadline = time.time() + ACTIVATION_TIMEOUT
                    while backend is None and time.time() < deadline:
                        time.sleep(0.05)
                        backend = router._pick(explain)
                if backend is None:
                    self._respond(503, b'{"error": "no ready replicas"}')
                    return
                length = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(length) if length else None
                req = urllib.request.Request(
                    backend + self.path, data=body, method=self.command,
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=60) as resp:
                        self._respond(resp.status, resp.read())
                except urllib.error.HTTPError as e:
                    self._respond(e.code, e.read())
                except OSError as e:
                    self._respond(502, json.dumps({"error": str(e)}).encode())

            def _respond(self, code: int, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._proxy()

            def do_POST(self):
                self._proxy()

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=f"router-{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def set_backends(self, urls: list[str]) -> None:
        with self._lock:
            self._backends = list(urls)

    def set_explain_backends(self, urls: list[str]) -> None:
        """Backends for the ``:explain`` verb (KServe routes the verb to the
        explainer component, everything else to transformer/predictor)."""
        with self._lock:
            self._explain_backends = list(urls)

    def _pick(self, explain: bool = False) -> Optional[str]:
        with self._lock:
            pool = self._explain_backends if explain and self._explain_backends else self._backends
            if not pool:
                return None
            self._rr = (self._rr + 1) % len(pool)
            return pool[self._rr]

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)


class _Deployment:
    """Live serving state for one InferenceService."""

    def __init__(self) -> None:
        self.predictors: list[ModelServer] = []
        self.transformers: list[ModelServer] = []
        self.explainers: list[ModelServer] = []
        self.router: Optional[Router] = None
        self.wants_scale_up = False
        self.spec_fingerprint = ""


class InferenceServiceController(Controller):
    kind = KIND_INFERENCE_SERVICE
    # one worker: reconciles mutate live _Deployment state (servers, router
    # backends); two workers on the same key would race — the workqueue only
    # dedups queued keys, not in-flight ones
    workers = 1

    def __init__(self, store: Store) -> None:
        super().__init__(store)
        self._deployments: dict[str, _Deployment] = {}
        self._lock = threading.Lock()

    def stop(self) -> None:
        super().stop()
        for d in list(self._deployments.values()):
            self._teardown_deployment(d)
        self._deployments.clear()

    # -- reconcile --------------------------------------------------------

    def reconcile(self, namespace: str, name: str) -> Optional[Result]:
        key = f"{namespace}/{name}"
        isvc = self.store.try_get(KIND_INFERENCE_SERVICE, name, namespace)
        if isvc is None:
            with self._lock:
                dep = self._deployments.pop(key, None)
            if dep:
                self._teardown_deployment(dep)
            return None
        assert isinstance(isvc, InferenceService)

        try:
            runtime_cls, cfg = self._resolve(isvc)
        except Exception as e:  # noqa: BLE001 — config errors -> Failed phase
            self._set_status(
                isvc, phase=InferenceServicePhase.FAILED, message=f"{type(e).__name__}: {e}")
            return None

        with self._lock:
            dep = self._deployments.setdefault(key, _Deployment())
        fingerprint = json.dumps(isvc.spec.model_dump(mode="json"), sort_keys=True)
        if dep.spec_fingerprint and dep.spec_fingerprint != fingerprint:
            self._teardown_deployment(dep)
            with self._lock:
                dep = self._deployments.setdefault(key, _Deployment())
                self._deployments[key] = dep
        dep.spec_fingerprint = fingerprint

        pred = isvc.spec.predictor
        if dep.router is None:
            dep.router = Router(activate=lambda: self._request_scale_up(key))
            self._set_status(isvc, phase=InferenceServicePhase.LOADING,
                             message="starting predictor")

        desired = self._desired_replicas(dep, pred)
        changed = self._scale_predictors(isvc, dep, runtime_cls, cfg, desired)
        self._wire(isvc, dep)

        ready = bool(dep.predictors) or pred.min_replicas == 0
        self._set_status(
            isvc,
            phase=InferenceServicePhase.READY if ready else InferenceServicePhase.LOADING,
            url=dep.router.url,
            active_replicas=len(dep.predictors),
            message="",
        )
        # periodic requeue drives the autoscaler loop
        return Result(requeue_after=0.25)

    # -- scaling ----------------------------------------------------------

    def _desired_replicas(self, dep: _Deployment, pred: ComponentSpec) -> int:
        n = len(dep.predictors)
        if dep.wants_scale_up:
            dep.wants_scale_up = False
            return max(n, 1, pred.min_replicas)
        inflight = sum(
            s.metrics.inflight for s in dep.predictors
        )
        if n and inflight / n > pred.scale_target_concurrency:
            return min(n + 1, pred.max_replicas)
        idle = (
            dep.router is not None
            and time.time() - dep.router.last_request_time > SCALE_IDLE_SECONDS
        )
        if idle and n > pred.min_replicas:
            return max(n - 1, pred.min_replicas)
        return max(n, pred.min_replicas)

    def _scale_predictors(
        self, isvc, dep: _Deployment, runtime_cls, cfg: dict, desired: int
    ) -> bool:
        changed = False
        while len(dep.predictors) < desired:
            server = ModelServer()
            model = runtime_cls(isvc.metadata.name, cfg)
            pred = isvc.spec.predictor
            server.register(
                model,
                batch_max_size=pred.batch_max_size,
                batch_timeout_ms=pred.batch_timeout_ms,
            )
            server.start()
            dep.predictors.append(server)
            self.emit_event(isvc, "ReplicaStarted", server.url)
            changed = True
        while len(dep.predictors) > desired:
            server = dep.predictors.pop()
            self._wire(isvc, dep)  # drop from router before stopping
            # drain asynchronously: requests already dispatched to this
            # replica (or queued in its micro-batcher) finish rather than
            # surfacing as 5xx, and the reconcile worker is not blocked for
            # the (bounded) drain period.  The initial settle sleep covers
            # requests the router already picked this backend for but whose
            # handler has not yet reached _dispatch's inflight increment.
            def _drain_stop(srv=server, svc=isvc):
                time.sleep(0.1)
                deadline = time.monotonic() + 5.0
                while srv.metrics.inflight > 0 and time.monotonic() < deadline:
                    time.sleep(0.02)
                srv.stop()
                self.emit_event(svc, "ReplicaStopped", srv.url)

            threading.Thread(
                target=_drain_stop, name="replica-drain", daemon=True
            ).start()
            changed = True
        return changed

    def _wire(self, isvc, dep: _Deployment) -> None:
        """Point the router at the right tier (transformer else predictor);
        the ``:explain`` verb routes to the explainer component when one is
        specified [upstream: kserve routes verbs per component]."""
        espec = isvc.spec.explainer
        if espec and espec.handler:
            if not dep.explainers and dep.predictors:
                cls = resolve_class(espec.handler)
                server = ModelServer()
                model = cls(isvc.metadata.name, {
                    **dict(espec.config),
                    "predictor_urls": [s.url for s in dep.predictors],
                    "model_name": isvc.metadata.name,
                })
                server.register(model, batch_max_size=1, batch_timeout_ms=0.0)
                server.start()
                dep.explainers.append(server)
            if dep.explainers:
                urls = [s.url for s in dep.predictors]
                for es in dep.explainers:
                    for m in es.models().values():
                        if hasattr(m, "predictor_urls"):
                            m.predictor_urls = list(urls)
                # with zero predictors, :explain must fall through to the
                # activator (empty pool -> scale-from-zero) instead of
                # reaching an explainer that has nothing to call
                dep.router.set_explain_backends(
                    [s.url for s in dep.explainers] if urls else [])
        tspec = isvc.spec.transformer
        if tspec and tspec.handler:
            if not dep.transformers and dep.predictors:
                cls = resolve_class(tspec.handler)
                cfg = dict(tspec.config)
                cfg["predictor_url"] = None  # filled per request via backends
                server = ModelServer()
                model = cls(isvc.metadata.name, {
                    **cfg, "predictor_urls": [s.url for s in dep.predictors],
                    "model_name": isvc.metadata.name,
                })
                server.register(model, batch_max_size=tspec.batch_max_size,
                                batch_timeout_ms=tspec.batch_timeout_ms)
                server.start()
                dep.transformers.append(server)
            if dep.transformers:
                # keep the transformer's predictor list current: predictors
                # churn on every scale event and ports never come back
                urls = [s.url for s in dep.predictors]
                for ts in dep.transformers:
                    for m in ts.models().values():
                        if hasattr(m, "predictor_urls"):
                            m.predictor_urls = list(urls)
                dep.router.set_backends([s.url for s in dep.transformers])
                return
        if dep.router:
            dep.router.set_backends([s.url for s in dep.predictors])

    def _request_scale_up(self, key: str) -> None:
        with self._lock:
            dep = self._deployments.get(key)
        if dep is not None:
            dep.wants_scale_up = True
        self.queue.add(key)

    # -- resolution -------------------------------------------------------

    def _resolve(self, isvc: InferenceService):
        pred = isvc.spec.predictor
        runtime: Optional[ServingRuntime] = None
        if pred.runtime:
            rt = self.store.try_get(KIND_SERVING_RUNTIME, pred.runtime, "default")
            if rt is None:
                raise ValueError(f"runtime {pred.runtime!r} not found")
            assert isinstance(rt, ServingRuntime)
            runtime = rt
        elif pred.model_format is not None:
            runtimes = [
                r for r in self.store.list(KIND_SERVING_RUNTIME)
                if isinstance(r, ServingRuntime)
            ]
            runtime = select_runtime(pred.model_format, runtimes)
            if runtime is None:
                raise ValueError(
                    f"no ServingRuntime supports model format "
                    f"{pred.model_format.name!r}")
        elif pred.handler:
            cfg = dict(pred.config)
            if pred.storage_uri:
                cfg.setdefault("storage_path", download(
                    pred.storage_uri, cache_dir=cfg.get("model_cache_dir")))
                cfg.setdefault("storage_uri", pred.storage_uri)
            return resolve_class(pred.handler), cfg
        else:
            raise ValueError("predictor needs runtime, model_format, or handler")

        cfg = {**runtime.spec.config, **pred.config}
        if pred.storage_uri:
            # merged cfg so a ServingRuntime can enable the cache for all
            # of its models, with the component able to override
            cfg.setdefault("storage_path", download(
                    pred.storage_uri, cache_dir=cfg.get("model_cache_dir")))
            cfg.setdefault("storage_uri", pred.storage_uri)
        return resolve_class(runtime.spec.server_class), cfg

    # -- teardown / status -------------------------------------------------

    def _teardown_deployment(self, dep: _Deployment) -> None:
        for s in dep.explainers + dep.transformers + dep.predictors:
            s.stop()
        dep.explainers.clear()
        dep.transformers.clear()
        dep.predictors.clear()
        if dep.router:
            dep.router.stop()
            dep.router = None

    def _set_status(self, isvc, phase=None, url=None, active_replicas=None, message=None):
        def mut(o):
            assert isinstance(o, InferenceService)
            if phase is not None:
                o.status.phase = phase
            if url is not None:
                o.status.url = url
            if active_replicas is not None:
                o.status.active_replicas = active_replicas
            if message is not None:
                o.status.message = message

        try:
            self.store.update_with_retry(
                KIND_INFERENCE_SERVICE, isvc.metadata.name, isvc.metadata.namespace, mut)
        except NotFound:
            pass
