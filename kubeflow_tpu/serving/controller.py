"""InferenceService reconciler + router/autoscaler.

The KServe control plane rebuilt on this cluster (SURVEY.md §2.2, §3.3)
[upstream: kserve/kserve -> pkg/controller/v1beta1/inferenceservice]:

- reconcile InferenceService -> resolve ServingRuntime (explicit or
  model-format auto-selection) -> run the storage initializer -> host the
  predictor Model in ModelServer replicas -> phase Ready + url;
- a Router per ISvc gives the stable URL and round-robins replicas (the
  istio/knative routing tier), with knative-activator-style scale-from-zero:
  a request arriving with no live replica triggers scale-up and waits;
- the autoscaler loop (KPA analog) scales replicas between min/max on
  observed concurrency per replica, and to zero after an idle window when
  ``min_replicas == 0``;
- a transformer component chains in front of the predictor over HTTP,
  exactly KServe's transformer -> predictor hop.
"""

from __future__ import annotations

import importlib
import json
import logging
import math
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ..api.inference import (
    KIND_INFERENCE_SERVICE,
    KIND_SERVING_RUNTIME,
    ComponentSpec,
    InferenceService,
    InferenceServicePhase,
    ServingRuntime,
    select_runtime,
)
from ..controlplane.controller import Controller, Result
from ..controlplane.store import NotFound, Store
from ..utils.net import allocate_port
from .model import Model
from .server import ModelServer
from .storage import download

SCALE_IDLE_SECONDS = 2.0  # idle window before scale-down (KPA-ish)
ACTIVATION_TIMEOUT = 15.0

log = logging.getLogger("kubeflow_tpu.serving")


class _GangMetrics:
    """Concurrency probe for a gang replica: rank 0's /metrics exposes
    the server's ``kft_requests_inflight`` gauge — the same signal the
    in-process autoscaler reads directly, fetched over HTTP with a short
    cache (the reconcile loop runs at 4 Hz)."""

    def __init__(self, url: str) -> None:
        self._url = url
        self._val = 0
        self._ts = 0.0
        self._fail_ts = -10.0

    @property
    def inflight(self) -> int:
        now = time.monotonic()
        if now - self._fail_ts < 2.0:
            return 0  # negative cache: a booting/restarting gang must
            # not stall the shared reconcile worker on every pass
        if now - self._ts > 0.5:
            self._ts = now
            try:
                with urllib.request.urlopen(
                        self._url + "/metrics", timeout=0.3) as r:
                    val = 0
                    for line in r.read().decode().splitlines():
                        if line.startswith("kft_requests_inflight"):
                            val = int(float(line.split()[-1]))
                            break
                self._val = val
            except (OSError, ValueError):
                self._fail_ts = now
                self._val = 0
        return self._val


class _GangPredictor:
    """ModelServer-shaped handle for a gang-placed predictor.

    The data plane is N cooperating host processes launched as a JaxJob
    (serving/gang.py serve_main); rank 0's HTTP frontend lives at a port
    this handle allocates and freezes into the job's env, so ``url`` is
    known before the gang is even admitted — readiness is probed, not
    assumed.  Restarts belong to the JaxJob controller (gang semantics);
    this handle only creates/deletes the job.  Gang REPLICAS scale like
    in-process ones (min/max, concurrency via the /metrics probe,
    activator): one handle per gang, ordinal-named.
    """

    def __init__(self, store: Store, isvc, rev: int, gang, cfg: dict,
                 ordinal: int = 0):
        from ..api.common import (
            Container, ObjectMeta, ReplicaSpec, Resources, RestartPolicy,
            RunPolicy,
        )
        from ..api.jaxjob import WORKER, JaxJob, JaxJobSpec
        from .gang import ENV_SERVE_CONFIG

        self.store = store
        self.namespace = isvc.metadata.namespace
        self.job_name = f"{isvc.metadata.name}-gang-r{rev}-g{ordinal}"
        #: the GangSpec this handle placed — the elastic shrink path
        #: (ISSUE 10) reads it to compute the surviving shape
        self.gang = gang
        self.port = allocate_port()
        self.metrics = _GangMetrics(f"http://127.0.0.1:{self.port}")
        self._ready_at: float = 0.0
        self._ready_fail_at: float = -10.0
        import os
        import secrets
        import tempfile

        conf = dict(cfg)
        conf["serve_port"] = self.port
        conf["gang_port"] = allocate_port()
        # per-job shared secret guarding the gang control stream,
        # delivered over a side channel: a 0600 token FILE (the
        # Secret-mount analog), because the JaxJob env is cluster-readable
        # through the API server and an inline token would let any tenant
        # who can GET the job join the stream (ADVICE r5).  Only the
        # file's PATH enters the env.
        fd, token_path = tempfile.mkstemp(
            prefix=f"kft-gang-{self.job_name}-", suffix=".token")
        try:
            os.fchmod(fd, 0o600)
            os.write(fd, secrets.token_hex(16).encode())
        finally:
            os.close(fd)
        self._token_path = token_path
        conf["gang_token_file"] = token_path
        conf["mesh_axes"] = dict(gang.mesh_axes)
        conf.setdefault("model_name", isvc.metadata.name)
        logger = isvc.spec.predictor.logger
        if logger is not None:
            conf["logger_url"] = logger.url
            conf["logger_mode"] = logger.mode
        env = {ENV_SERVE_CONFIG: json.dumps(conf)}
        import os as _os

        if _os.environ.get("KFT_POD_JAX_PLATFORMS", "cpu") == "cpu":
            # local CPU stand-in: each gang pod fakes chips_per_host
            # devices (real TPU hosts discover their local chips)
            env["XLA_FLAGS"] = (
                "--xla_force_host_platform_device_count="
                f"{gang.chips_per_host}")
        job = JaxJob(
            metadata=ObjectMeta(name=self.job_name, namespace=self.namespace),
            spec=JaxJobSpec(
                run_policy=RunPolicy(backoff_limit=gang.backoff_limit),
                replica_specs={
                    WORKER: ReplicaSpec(
                        replicas=gang.hosts,
                        restart_policy=RestartPolicy.ON_FAILURE,
                        template=Container(
                            entrypoint="kubeflow_tpu.serving.gang:serve_main",
                            env=env,
                            resources=Resources(tpu=gang.chips_per_host),
                        ),
                    )
                },
            ),
        )
        store.create(job)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def ready(self) -> bool:
        """Rank 0 frontend answering its readiness probe (cached briefly —
        the reconcile loop runs at 4 Hz and a gang is not a thing to poll
        into the ground)."""
        now = time.monotonic()
        if now < self._ready_at + 1.0:
            return True
        if now < self._ready_fail_at + 1.0:
            return False  # negative cache: a booting gang must not stall
            # the shared reconcile worker on every 4 Hz pass
        try:
            with urllib.request.urlopen(
                    self.url + "/v2/health/ready", timeout=0.5) as resp:
                ok = resp.status == 200
        except OSError:
            ok = False
        if ok:
            self._ready_at = now
        else:
            self._ready_fail_at = now
        return ok

    def stop(self) -> None:
        import os

        from ..api.jaxjob import KIND_JAXJOB

        try:
            self.store.delete(KIND_JAXJOB, self.job_name, self.namespace)
        except NotFound:
            pass
        try:
            os.unlink(self._token_path)
        except OSError:
            pass


def resolve_class(ref: str) -> type:
    """'pkg.module:Class' -> class object (ServingRuntime.server_class)."""
    mod, _, cls = ref.partition(":")
    return getattr(importlib.import_module(mod), cls)


class Router:
    """Stable URL in front of N replica servers: traffic-aware routing.

    Baseline behavior is the smooth-WRR + activator tier; with a
    :class:`~.traffic.TrafficPlane` installed (``set_traffic``) the
    router becomes the cluster front door (ISSUE 9):

    - **per-tenant QoS**: token-bucket + bounded-queue admission per
      class; sheds are explicit 429s with ``Retry-After`` and a
      structured reason, never unbounded buffering (the SSE path blocks
      at this door inside the class's queue bound);
    - **prefix-affinity routing**: the prompt's prefix blocks hash to
      block-content keys (``paged.block_keys``) and the request routes
      to the replica that already holds them, least-loaded otherwise —
      the replica prefix caches only pay off when the router feeds
      them;
    - **connection-failure re-route**: a dead backend's affinity
      entries are forgotten and the request retries the surviving
      replicas (bounded by pool size) — a replica crash mid-storm costs
      a re-route, not a hang;
    - **observability**: per-backend request/error/inflight counters on
      the router's own ``/metrics``, with ``no_backend_total`` and the
      plane's shed/affinity gauges.
    """

    def __init__(self, activate: Callable[[], None],
                 port: Optional[int] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 rng=None, serve: bool = True):
        #: ``serve=False`` builds the full policy surface (pools, WRR
        #: state, circuits, budget, domains) WITHOUT the HTTP server —
        #: the digital twin (``sim/``) drives this exact object with a
        #: virtual ``clock``/seeded ``rng``, so routing decisions in
        #: simulation are the production code path by construction.
        self.port = port or (allocate_port() if serve else 0)
        self._clock = clock
        self._rng = rng
        #: weighted backend pools: [(urls, weight)] — one pool per
        #: revision (canary rollout splits traffic here, the
        #: virtualservice-weight analog); single-revision services have
        #: one pool at weight 100
        self._pools: list[tuple[list[str], int]] = []
        self._explain_pools: list[tuple[list[str], int]] = []
        self._rr: list[int] = []   # per-pool round-robin cursors
        self._err: list[int] = []
        self._wrr: list[int] = []  # smooth-WRR current weights
        self._ewrr: list[int] = []
        self._lock = threading.Lock()
        self._activate = activate
        self.last_request_time = 0.0
        #: optional traffic plane (QoS + affinity); None = classic WRR
        self.traffic = None
        #: optional request-lifecycle tracer (ISSUE 13): the router is
        #: the path's ROOT sampling decision — its trace context rides
        #: X-KFT-Trace to the replica, so one sampled request is traced
        #: end to end.  Installed via configure_tracing.
        self.tracer = None
        self._tracing_fp: Optional[str] = None
        #: optional cluster block-registry poller (ISSUE 13 satellite):
        #: scrapes replica /metrics prefix rows on a jittered interval
        #: and exports kft_cluster_prefix_replicas gauges
        self.prefix_poller = None
        #: per-backend counters: url -> {requests, errors, inflight}
        self._backend_stats: dict[str, dict[str, int]] = {}
        self.no_backend_total = 0
        # correlated-failure survival (ISSUE 16): per-backend health
        # circuits + the cluster retry budget.  Always active — with
        # ``domains`` unset every backend sits in one implicit domain
        # and only the circuit/budget behavior applies.
        from .traffic import BackendHealth, RetryBudget

        self.health = BackendHealth(clock=clock, rng=rng)
        self.retry_budget = RetryBudget(clock=clock)
        #: url -> failure-domain label (empty = single implicit domain)
        self._domains: dict[str, str] = {}
        #: domains currently declared down (mass-forget fired once)
        self._domains_down: set[str] = set()
        self.domain_outages_total = 0
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _proxy(self) -> None:
                if self.command == "GET" and self.path == "/metrics":
                    # exemplars only under negotiated OpenMetrics —
                    # the classic parser fails on the trailer
                    om = "application/openmetrics-text" in str(
                        self.headers.get("Accept") or "")
                    body = router.metrics_text(openmetrics=om).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8" if om
                        else "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.command == "GET" and (
                        self.path == "/traces"
                        or self.path.startswith("/traces?")):
                    # router-side trace view — the SAME query contract
                    # helper as the replica's /traces (observability
                    # GETs never tick the idle clock, the /metrics
                    # rule)
                    from .trace import parse_slowest, traces_body

                    ok, slowest = parse_slowest(self.path)
                    if not ok:
                        self._respond(400, json.dumps(
                            {"error": "slowest must be an "
                                      "int"}).encode())
                        return
                    body = ""
                    if router.tracer is not None:
                        router.tracer.reap()
                        body = traces_body([router.tracer.sink],
                                           slowest)
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Content-Length",
                                     str(len(body.encode())))
                    self.end_headers()
                    self.wfile.write(body.encode())
                    return
                # the idle clock ticks AFTER the /metrics early-return:
                # a monitoring poller scraping faster than
                # SCALE_IDLE_SECONDS would otherwise pin the
                # deployment's replica count forever (scale-down and
                # scale-to-zero key off this timestamp)
                router.last_request_time = time.time()
                explain = self.path.endswith(":explain")
                length = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(length) if length else None
                keys, tenant, session = router._request_context(
                    body, self.headers)
                plane = router.traffic
                ticket = None
                # the QoS door gates INFERENCE POSTs only: readiness /
                # metadata GETs and admin POSTs (repository load/unload
                # through the stable URL) are control-plane traffic —
                # shedding health probes would flap the controller's
                # view of its own replicas, and charging operators to
                # the "default" tenant's bucket couples control
                # operations to tenant rate limits
                infer = (self.path.startswith("/openai/")
                         or self.path.endswith((":predict", ":explain",
                                                "/infer")))
                trace = None
                if (router.tracer is not None and infer
                        and self.command == "POST"):
                    from .trace import TRACE_HEADER

                    # the ROOT sampling decision for the whole path: a
                    # replica honoring X-KFT-Trace inherits it, so one
                    # decision covers router door -> affinity pick ->
                    # replica door -> engine -> handoff -> decode
                    trace = router.tracer.start(
                        self.headers.get(TRACE_HEADER))
                    if trace is not None:
                        trace.meta["tenant"] = tenant
                        trace.phase("router.door")
                if plane is not None and self.command == "POST" and infer:
                    from .traffic import shed_http

                    if not plane.authenticate(
                            tenant, self.headers.get("Authorization")):
                        # a tenant whose Profile carries an api_token
                        # must prove the claim — otherwise any client
                        # could adopt a privileged class's rate and
                        # priority by naming it (the spoof the
                        # no-self-promotion rule exists to stop)
                        body401 = json.dumps({
                            "error": "tenant credential required",
                            "reason": "bad_tenant_credential",
                            "tenant": tenant,
                        }).encode()
                        if trace is not None:
                            trace.meta["stall"] = "bad_tenant_credential"
                            router.tracer.finish(trace)
                            trace = None
                        self._respond(401, body401)
                        return
                    ticket = plane.acquire(tenant)
                    if not ticket.ok:
                        if trace is not None:
                            # shed reason = the stall cause the
                            # autoscaler summary aggregates
                            trace.meta["stall"] = \
                                f"shed:{ticket.reason}"
                            router.tracer.finish(trace)
                            trace = None
                        shed_http(self, ticket)
                        return
                    if trace is not None and ticket.cls is not None:
                        trace.meta["class"] = ticket.cls.name
                try:
                    self._route_and_forward(
                        explain, body, keys, tenant, ticket, session,
                        trace=trace)
                finally:
                    if ticket is not None:
                        plane.release(ticket)
                    if trace is not None:
                        router.tracer.finish(trace)

            def _route_and_forward(self, explain, body, keys, tenant,
                                   ticket, session=None,
                                   trace=None) -> None:
                if trace is not None:
                    # door wait ends here; the pick (affinity lookup,
                    # possibly the scale-from-zero activation wait) is
                    # its own phase
                    trace.phase("router.route")
                backend = router._pick(explain, keys, session=session)
                if backend is None:
                    if trace is not None:
                        trace.meta["stall"] = "activation_wait"
                    router._activate()
                    deadline = time.time() + ACTIVATION_TIMEOUT
                    while backend is None and time.time() < deadline:
                        time.sleep(0.05)
                        backend = router._pick(explain, keys,
                                               session=session)
                if trace is not None:
                    trace.phase("router.forward",
                                backend=backend or "")
                tried: set[str] = set()
                while backend is not None:
                    headers = {"Content-Type": "application/json"}
                    if trace is not None:
                        # propagate the context: the replica's door
                        # continues THIS trace instead of sampling
                        headers["X-KFT-Trace"] = trace.header()
                    if self.headers.get("Authorization"):
                        # a replica-side plane may hold its own
                        # qos_tenant_tokens: the credential must
                        # survive the hop or routed requests from a
                        # credentialed tenant all 401 at the replica
                        headers["Authorization"] = \
                            self.headers["Authorization"]
                    if router.traffic is not None:
                        # forward the classification only when this
                        # router actually made one — a plane-less
                        # router's "default" must not override the
                        # payload's user field at a QoS-bearing replica
                        headers["X-KFT-Tenant"] = tenant
                    if ticket is not None:
                        # replica-side plane must not double-charge the
                        # tenant's token bucket.  Priority: the class
                        # tier when one classified the tenant; the
                        # "normal" cap when this door HAS classes but
                        # this tenant none (an anonymous caller must
                        # not outrank classed tenants); nothing for a
                        # class-free affinity-only plane (no ordering
                        # contract — the payload stands downstream)
                        headers["X-KFT-Admitted"] = "1"
                        if ticket.cls is not None:
                            headers["X-KFT-Priority"] = \
                                ticket.priority_name
                        elif router.traffic.classes():
                            headers["X-KFT-Priority"] = "normal"
                    elif self.headers.get("X-KFT-Priority"):
                        headers["X-KFT-Priority"] = \
                            self.headers["X-KFT-Priority"]
                    req = urllib.request.Request(
                        backend + self.path, data=body,
                        method=self.command, headers=headers)
                    router._note(backend, delta=+1)
                    try:
                        with urllib.request.urlopen(req, timeout=60) as resp:
                            payload = resp.read()
                            router._note(backend, delta=-1)
                            router._backend_up(backend)
                            self._respond(resp.status, payload)
                            return
                    except urllib.error.HTTPError as e:
                        router._note(backend, delta=-1,
                                     error=e.code >= 500)
                        # circuit evidence: a 5xx is an erroring-but-
                        # alive replica (error-rate trip); anything
                        # else (429 shed, 4xx) proves it answers
                        if e.code >= 500:
                            router.health.note_failure(backend)
                        else:
                            router._backend_up(backend)
                        self._respond(e.code, e.read(),
                                      retry_after=e.headers.get(
                                          "Retry-After"))
                        return
                    except OSError as e:
                        router._note(backend, delta=-1, error=True)
                        # re-route ONLY connection-level death (a
                        # crashed replica: refused/reset/aborted) —
                        # a slow-but-alive replica's read timeout must
                        # NOT re-POST the inference elsewhere (it is
                        # likely still computing; a duplicate doubles
                        # the work and the tokens billed) nor wipe a
                        # healthy replica's affinity
                        reason = getattr(e, "reason", e)
                        if not isinstance(reason, ConnectionError):
                            self._respond(502, json.dumps(
                                {"error": str(e)}).encode())
                            return
                        router._backend_down(backend)
                        tried.add(backend)
                        # the cluster retry budget (ISSUE 16): N dying
                        # replicas must not multiply a 2x storm into a
                        # 2(1+retries)x storm — past the budget, the
                        # client gets the jittered 503 below instead
                        # of another forwarded attempt
                        if not router.retry_budget.try_retry():
                            backend = None
                            break
                        # spread the re-route across SURVIVING domains
                        # with a small jittered backoff: the recovery
                        # herd from a domain-sized outage arrives at
                        # the survivors de-synchronized, not as a wave
                        time.sleep(random.uniform(0.01, 0.05))
                        avoid = {router.domain_of(u) for u in tried
                                 if router.domain_of(u)}
                        backend = router._pick(explain, keys,
                                               exclude=tried,
                                               session=session,
                                               avoid_domains=avoid)
                router.no_backend_total += 1
                from .traffic import jittered_retry_after

                # jittered, load-aware Retry-After (ISSUE 16
                # satellite): the more circuits are open, the longer
                # and more spread out the herd's retry horizon
                ra = jittered_retry_after(
                    1.0, load=len(router.health.open_backends()),
                    rng=router._rng)
                self._respond(
                    503, json.dumps({
                        "error": "no ready replicas",
                        "reason": "no_ready_replicas",
                        "retry_after": round(ra, 3),
                    }).encode(),
                    retry_after=str(max(1, math.ceil(ra))))

            def _respond(self, code: int, body: bytes,
                         retry_after: Optional[str] = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                if retry_after:
                    self.send_header("Retry-After", retry_after)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._proxy()

            def do_POST(self):
                self._proxy()

        if not serve:
            self._httpd = None
            self._thread = None
            return
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=f"router-{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def set_traffic(self, plane) -> None:
        """Install (or clear) the traffic plane — QoS admission +
        prefix-affinity routing from the next request on."""
        self.traffic = plane

    def _request_context(self, body: Optional[bytes],
                         headers) -> tuple[list, str, str]:
        """(affinity keys, tenant, session) for one request.  The
        tenant comes from the ``X-KFT-Tenant`` header or the OpenAI
        ``user`` field; the affinity keys hash the prompt's prefix in
        block quanta (byte-token ids — exactly the block-content
        identity for the byte tokenizer, a stable content proxy for
        any other); the session id (``X-KFT-Session`` header or
        payload ``session``, ISSUE 12) routes a durable conversation
        back to the replica still holding its KV."""
        tenant = headers.get("X-KFT-Tenant") or ""
        session = str(headers.get("X-KFT-Session") or "")
        keys: list = []
        plane = self.traffic
        if body and plane is not None:
            try:
                payload = json.loads(body)
            except (ValueError, UnicodeDecodeError):
                payload = None
            if isinstance(payload, dict):
                tenant = tenant or str(payload.get("user") or "")
                session = session or str(payload.get("session") or "")
                prompt = payload.get("prompt")
                if prompt is None and isinstance(
                        payload.get("messages"), list):
                    prompt = "\n".join(
                        f"{m.get('role', 'user')}: {m.get('content', '')}"
                        for m in payload["messages"])
                if isinstance(prompt, list):
                    prompt = prompt[0] if prompt else ""
                if isinstance(prompt, str) and prompt:
                    keys = plane.prefix_keys(list(prompt.encode("utf-8")))
        return keys, tenant or "default", session

    def _note(self, backend: str, delta: int, error: bool = False) -> None:
        with self._lock:
            st = self._backend_stats.setdefault(
                backend, {"requests": 0, "errors": 0, "inflight": 0})
            if delta > 0:
                st["requests"] += 1
            st["inflight"] = max(0, st["inflight"] + delta)
            if error:
                st["errors"] += 1

    def _backend_down(self, backend: str) -> None:
        # feed the health circuit FIRST (ISSUE 16): enough consecutive
        # connection failures open it and routing skips the corpse
        # until a jittered half-open probe proves it back — before the
        # circuit existed this forgot the backend's affinity but kept
        # routing connect attempts at it until membership churn
        self.health.note_failure(backend)
        if self.traffic is not None:
            self.traffic.affinity.forget(backend)
            # its hibernated/live sessions' KV died with it: resumes
            # re-route and thaw from the shared storage tier instead
            self.traffic.sessions.forget(backend)
        self._check_domain_outage(self.domain_of(backend))

    def _backend_up(self, backend: str) -> None:
        """One successful forward: recovery evidence for the circuit,
        a deposit into the cluster retry budget, and — if its domain
        was declared down — the all-clear for the domain."""
        self.health.note_success(backend)
        self.retry_budget.note_success()
        d = self.domain_of(backend)
        if d and d in self._domains_down:
            self._domains_down.discard(d)

    def domain_of(self, backend: str) -> str:
        """Failure-domain label for ``backend`` ('' = the single
        implicit domain when ``domains`` is unconfigured)."""
        return self._domains.get(backend, "")

    def set_domains(self, mapping: dict[str, str]) -> None:
        """Install the url -> failure-domain map (the controller's
        ``_wire`` keeps it in lockstep with the pools).  Domains whose
        members all churned away stop being tracked as down."""
        self._domains = dict(mapping or {})
        self._domains_down &= set(self._domains.values())

    def backends(self) -> list[str]:
        """Flat live data-plane backend list (pool order)."""
        with self._lock:
            return [u for us, _w in self._pools for u in us]

    def _check_domain_outage(self, domain: str) -> None:
        """Declare ``domain`` down when EVERY one of its live backends
        has an open circuit while another domain still serves, and
        mass-forget its sessions/affinity/registry rows in ONE pass —
        the herd of resumes then routes straight to survivors instead
        of each request rediscovering the outage one dead connect at
        a time.  Fires once per outage (re-armed by the first
        successful forward into the domain, or membership churn)."""
        if not domain or domain in self._domains_down:
            return
        members = [u for u in self.backends()
                   if self._domains.get(u, "") == domain]
        others = [u for u in self.backends()
                  if self._domains.get(u, "") != domain]
        # "another domain still serves" means a survivor with a
        # non-open circuit — a TOTAL collapse is not a domain outage
        # (mass-forgetting with nobody to re-route toward just throws
        # away the warm-resume state the comeback would want)
        if not members or not any(
                self.health.state(u) != "open" for u in others):
            return
        if any(self.health.state(u) != "open" for u in members):
            return
        self._domains_down.add(domain)
        self.domain_outages_total += 1
        for u in members:
            # trip is idempotent; the forgets are the mass action
            self.health.trip(u)
            if self.traffic is not None:
                self.traffic.affinity.forget(u)
                self.traffic.sessions.forget(u)
            if self.prefix_poller is not None:
                self.prefix_poller.registry.forget(u)
        log.warning("failure domain %r declared down "
                    "(%d backends, circuits open)", domain, len(members))

    def _inflight(self, backend: str) -> int:
        with self._lock:
            st = self._backend_stats.get(backend)
            return st["inflight"] if st else 0

    def backend_stats(self) -> dict[str, dict[str, int]]:
        with self._lock:
            return {b: dict(st) for b, st in self._backend_stats.items()}

    def metrics_text(self, openmetrics: bool = False) -> str:
        """Router observability in Prometheus text format: per-backend
        request/error/inflight gauges + the no-backend counter + the
        traffic plane's shed/affinity/preemption gauges.
        ``openmetrics`` (negotiated by the handler) enables exemplar
        trailers + the ``# EOF`` terminator."""
        from .traffic import prom_label

        lines = []
        for fam in ("requests", "errors", "inflight"):
            lines.append(f"# TYPE kft_router_backend_{fam} gauge")
            for b, st in sorted(self.backend_stats().items()):
                lines.append(
                    f'kft_router_backend_{fam}'
                    f'{{backend="{prom_label(b)}"}} {st[fam]}')
        lines.append("# TYPE kft_router_no_backend_total gauge")
        lines.append(f"kft_router_no_backend_total {self.no_backend_total}")
        # correlated-failure survival gauges (ISSUE 16): circuit
        # states per backend, trip/close/probe counters, the cluster
        # retry budget, and declared domain outages
        lines.append("# TYPE kft_router_circuit_open gauge")
        for b in sorted(self.backends()):
            state = self.health.state(b)
            lines.append(
                f'kft_router_circuit_open{{backend="{prom_label(b)}",'
                f'domain="{prom_label(self.domain_of(b))}"}} '
                f"{1 if state != 'closed' else 0}")
        from .traffic import prom_stat_lines as _psl

        fams = _psl({**self.health.stats(), **self.retry_budget.stats(),
                     "domain_outages_total": self.domain_outages_total},
                    "kft_router_")
        for fam in sorted(fams):
            lines.append(f"# TYPE {fam} gauge")
            lines.extend(fams[fam])
        if self.traffic is not None:
            from .traffic import prom_stat_lines

            fams = prom_stat_lines(self.traffic.stats(), "kft_router_")
            for fam in sorted(fams):
                lines.append(f"# TYPE {fam} gauge")
                lines.extend(fams[fam])
        if self.tracer is not None:
            from .traffic import prom_stat_lines

            fams = prom_stat_lines(self.tracer.stats(), "kft_router_")
            for fam in sorted(fams):
                lines.append(f"# TYPE {fam} gauge")
                lines.extend(fams[fam])
            # router-side phase histograms (door / route / forward) —
            # the scrape half of /traces (exemplar trace ids only on
            # a negotiated OpenMetrics scrape)
            lines.extend(self.tracer.sink.phase_metrics(
                exemplars=openmetrics))
        if self.prefix_poller is not None:
            # cluster prefix heat (ISSUE 13 satellite): how many
            # replicas hold each hot prefix chain — the placement
            # signal the autoscaler loop (ROADMAP item 2) consumes
            lines.extend(self.prefix_poller.metrics_lines())
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def configure_tracing(self, spec) -> None:
        """Install/refresh/clear the router's tracer from the ISvc
        ``tracing`` config (fingerprinted — the 4 Hz reconcile must not
        wipe the ring every pass)."""
        import json as jsonlib

        from .trace import Tracer, validate_tracing

        if not spec:
            self.tracer = None
            self._tracing_fp = None
            return
        kw = validate_tracing(spec)
        fp = jsonlib.dumps(kw, sort_keys=True)
        if fp == self._tracing_fp:
            return
        self.tracer = Tracer(**kw)
        self._tracing_fp = fp

    def start_prefix_poller(self, interval_s: float) -> None:
        """Start (idempotent) the cluster block-registry poller over
        this router's live data-plane backends."""
        if self.prefix_poller is not None:
            self.prefix_poller.interval_s = float(interval_s)
            return
        from .traffic import ClusterPrefixPoller

        def backends() -> list[str]:
            with self._lock:
                return [u for us, _w in self._pools for u in us]

        self.prefix_poller = ClusterPrefixPoller(
            backends, interval_s=float(interval_s))

    def set_backends(self, urls: list[str]) -> None:
        self.set_weighted_backends([(list(urls), 100)])

    def set_weighted_backends(self, pools: list[tuple[list[str], int]]) -> None:
        """Traffic-split backend pools; empty pools and zero weights are
        dropped (an empty stable pool must fall through to the activator,
        not eat the canary's share)."""
        with self._lock:
            new = [(list(u), int(w)) for u, w in pools if u and w > 0]
            if ([w for _, w in new] != [w for _, w in self._pools]
                    or len(self._wrr) != len(new)):
                self._wrr = [0] * len(new)  # weights changed: reset the WRR
            if [u for u, _ in new] != [u for u, _ in self._pools]:
                self._rr = [0] * len(new)  # membership changed: reset RR
            gone = ({u for us, _ in self._pools for u in us}
                    - {u for us, _ in new for u in us})
            self._pools = new
            for u in gone:
                # replica ports never come back: without pruning,
                # autoscale churn grows the per-backend /metrics rows
                # (and the dict behind them) without bound
                self._backend_stats.pop(u, None)
        # a removed replica's KV is gone with it: keep affinity from
        # steering same-prefix traffic at a corpse (outside the lock —
        # the affinity map has its own)
        for u in gone:
            self._backend_down(u)
            # membership churn, not a failure: the circuit record and
            # domain label die with the URL (ports never come back)
            self.health.forget(u)
            self._domains.pop(u, None)

    def set_explain_backends(self, urls: list[str]) -> None:
        """Backends for the ``:explain`` verb (KServe routes the verb to the
        explainer component, everything else to transformer/predictor)."""
        self.set_weighted_explain_backends([(list(urls), 100)])

    def set_weighted_explain_backends(
        self, pools: list[tuple[list[str], int]]
    ) -> None:
        with self._lock:
            new = [(list(u), int(w)) for u, w in pools if u and w > 0]
            if ([w for _, w in new] != [w for _, w in self._explain_pools]
                    or len(self._ewrr) != len(new)):
                self._ewrr = [0] * len(new)
            if [u for u, _ in new] != [u for u, _ in self._explain_pools]:
                self._err = [0] * len(new)
            # same cleanup as the data-plane pools: explain replicas
            # churn ports too, and their stats rows / affinity entries
            # must die with them
            gone = ({u for us, _ in self._explain_pools for u in us}
                    - {u for us, _ in new for u in us})
            self._explain_pools = new
            for u in gone:
                self._backend_stats.pop(u, None)
        for u in gone:
            self._backend_down(u)
            self.health.forget(u)
            self._domains.pop(u, None)

    def _pick(self, explain: bool = False, keys: Optional[list] = None,
              exclude: Optional[set] = None,
              session: Optional[str] = None,
              avoid_domains: Optional[set] = None) -> Optional[str]:
        # the pick pipeline is the pure policy pair in traffic.py
        # (ISSUE 20 extraction): smooth_wrr_pick mutates the cursor
        # state under this lock, live_candidates filters through the
        # health circuits — the sim twin calls the same functions on
        # the same objects
        from .traffic import live_candidates, smooth_wrr_pick

        with self._lock:
            use_explain = explain and self._explain_pools
            pools = self._explain_pools if use_explain else self._pools
            cur = self._ewrr if use_explain else self._wrr
            rrs = self._err if use_explain else self._rr
            if not pools:
                return None
            best = smooth_wrr_pick(pools, cur)

            def live(urls: list) -> list:
                return live_candidates(
                    urls, self.health.routable, exclude=exclude,
                    avoid_domains=avoid_domains,
                    domain_of=lambda u: self._domains.get(u, ""))

            pool = live(pools[best][0])
            if not pool:
                # crash-retry/circuits emptied the WRR-chosen pool:
                # any OTHER pool's live backend beats a 503 — a canary
                # split must not turn one stable-replica crash into
                # "no ready replicas" while the canary serves
                for us, _w in pools:
                    pool = live(us)
                    if pool:
                        break
                if not pool:
                    return None
            plane = self.traffic
            if plane is None or not (keys or session):
                # round-robin WITHIN the chosen pool, cursor per pool — a
                # shared cursor lets a 1-backend pool reset it and starve
                # backends of the other pool during a canary split
                rrs[best] = (rrs[best] + 1) % len(pool)
                choice = pool[rrs[best]]
                self.health.on_routed(choice)
                return choice
        # session/prefix-affinity pick (outside the WRR lock: the plane
        # has its own): a durable session resumes at the replica still
        # holding its KV (ISSUE 12); otherwise the replica already
        # holding this prompt's prefix blocks wins unless it is
        # overloaded vs its peers; least-inflight otherwise, and the
        # choice is recorded so the NEXT same-prefix request sticks
        backend, _depth = plane.route(keys or [], pool,
                                      load=self._inflight,
                                      session=session)
        self.health.on_routed(backend)
        return backend

    def stop(self) -> None:
        if self.prefix_poller is not None:
            self.prefix_poller.stop()
            self.prefix_poller = None
        if self._httpd is None:
            return  # serve=False twin router: nothing to tear down
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)


class _Revision:
    """One immutable rollout of an InferenceService spec.

    Canary rollout (KServe's canaryTrafficPercent over virtualservice
    weights) needs two revisions live at once, each serving the spec it
    was created from — so the resolved runtime + config are frozen here,
    not re-derived from the (possibly newer) object spec."""

    def __init__(self, rev: int, fingerprint: str, spec, runtime_cls, cfg: dict):
        self.rev = rev
        self.fingerprint = fingerprint
        self.spec = spec
        self.runtime_cls = runtime_cls
        self.cfg = cfg
        self.predictors: list[ModelServer] = []
        self.transformers: list[ModelServer] = []
        self.explainers: list[ModelServer] = []
        #: monotonically increasing ordinal for gang-replica job names
        self.gang_counter = 0

    @property
    def servers(self) -> list[ModelServer]:
        return self.explainers + self.transformers + self.predictors


class _Deployment:
    """Live serving state for one InferenceService."""

    def __init__(self) -> None:
        self.router: Optional[Router] = None
        self.stable: Optional[_Revision] = None
        self.canary: Optional[_Revision] = None
        self.rev_counter = 0
        self.pct = 0  # live canary traffic share
        self.wants_scale_up = False
        #: fingerprint of the traffic plane's merged config (cfg qos +
        #: Profile qos): the plane rebuilds only when this changes, so
        #: counters and affinity state survive the 4 Hz reconcile
        self.traffic_fp: Optional[str] = None
        #: Degraded-deadline tracking (ISSUE 10): when the deployment
        #: entered Degraded, and whether this episode already escalated
        #: (one DegradedTimeout + shrink per episode, not per 4 Hz tick)
        self.degraded_since: Optional[float] = None
        self.degraded_escalated = False
        #: predictive autoscaler (ISSUE 15), fingerprint-rebuilt like
        #: the traffic plane so predictor state and cooldown clocks
        #: survive the 4 Hz reconcile; its replica actuators write
        #: ``autoscale_desired`` and ``_desired_replicas`` applies it
        self.autoscaler = None
        self.autoscale_fp: Optional[str] = None
        self.autoscale_desired: Optional[int] = None
        #: mass-recovery thaw cap (ISSUE 16): one shared
        #: ConcurrencyGate attached to every engine's ``thaw_gate``
        #: when the policy sets ``thaw_concurrency`` > 0, so a dead
        #: domain's hibernated sessions re-materialize a few at a time
        #: instead of starving live decode
        self.thaw_gate = None
        #: wake-from-zero cold-start clock: stamped when the loop fires
        #: a placement at n=0, closed when the fleet reports ready —
        #: the measured budget scale-to-zero is held to
        self.cold_start_t0: Optional[float] = None
        #: (monotonic t, cumulative plane sheds) for the shed-rate sensor
        self.shed_mark: tuple[float, float] = (0.0, 0.0)

    @property
    def revisions(self) -> list[_Revision]:
        return [r for r in (self.stable, self.canary) if r is not None]


class InferenceServiceController(Controller):
    kind = KIND_INFERENCE_SERVICE
    # one worker: reconciles mutate live _Deployment state (servers, router
    # backends); the workqueue serializes per key, but one worker keeps the
    # cross-key server/port churn sequential too
    workers = 1

    def __init__(self, store: Store) -> None:
        super().__init__(store)
        self._deployments: dict[str, _Deployment] = {}
        self._lock = threading.Lock()
        # cold-start concurrency gate (ISSUE 16): serialize the
        # pre-warm/compile path so emergency grow-back after a domain
        # outage cannot stampede N simultaneous census+install sweeps
        # through one warm peer
        from .autoscale import ConcurrencyGate
        self._prewarm_gate = ConcurrencyGate(1)

    def stop(self) -> None:
        super().stop()
        for d in list(self._deployments.values()):
            self._teardown_deployment(d)
        self._deployments.clear()

    # -- reconcile --------------------------------------------------------

    @staticmethod
    def _fingerprint(spec) -> str:
        """Spec identity for revision tracking — the traffic split is
        routing config, not a new revision."""
        d = spec.model_dump(mode="json")
        d.pop("canary_traffic_percent", None)
        return json.dumps(d, sort_keys=True)

    #: engine knobs validated at conf-freeze (value below floor -> Failed)
    _ENGINE_KNOBS = ("num_slots", "decode_chunk", "pipeline_depth",
                     "prefill_budget", "spec_k", "spec_ngram",
                     "block_size", "num_blocks", "host_blocks")

    def _new_revision(self, isvc, dep: _Deployment, fingerprint: str) -> _Revision:
        runtime_cls, cfg = self._resolve(isvc)
        if (isvc.spec.predictor.gang is not None
                or any(k in cfg for k in self._ENGINE_KNOBS)
                or "role" in cfg or "disaggregation" in cfg):
            # validate the engine knobs HERE, inside the reconcile's
            # Failed-phase guard, where the revision config freezes: a
            # bad value (prefill_budget: -1, spec_k: -2, ...) otherwise
            # surfaces as N pods crash-looping through JaxJob restarts
            # (gang) or an in-process replica stuck Loading forever;
            # this way it is ONE Failed status with the message
            from .continuous import engine_kwargs

            zero_ok = ("prefill_budget", "spec_k", "block_size",
                       "num_blocks", "host_blocks")
            bad = {k: v for k, v in engine_kwargs(cfg).items()
                   if k in self._ENGINE_KNOBS
                   and v < (0 if k in zero_ok else 1)}
            if bad:
                raise ValueError(f"invalid engine knobs: {bad}")
            # migration/disaggregation knobs (ISSUE 8) freeze here too:
            # a bad role would otherwise be one ValueError per replica
            # process — crash-looping pods instead of ONE Failed status
            role = str(cfg.get("role", "mixed"))
            if role not in ("mixed", "prefill", "decode"):
                raise ValueError(
                    f"invalid engine knobs: role {role!r} "
                    "(mixed|prefill|decode)")
            if role != "mixed" and int(cfg.get("block_size", 0) or 0) <= 0:
                raise ValueError(
                    f"invalid engine knobs: role={role} requires the "
                    "paged pool (block_size > 0)")
            disagg = cfg.get("disaggregation")
            if disagg is not None:
                if not isinstance(disagg, dict):
                    raise ValueError(
                        "invalid engine knobs: disaggregation must be "
                        '{"prefill": n, "decode": m[, "wire": bool]}')
                if (int(disagg.get("prefill", 1)) < 1
                        or int(disagg.get("decode", 1)) < 1):
                    raise ValueError(
                        "invalid engine knobs: disaggregation needs "
                        ">= 1 replica per role")
                if int(cfg.get("block_size", 0) or 0) <= 0:
                    raise ValueError(
                        "invalid engine knobs: disaggregation requires "
                        "the paged pool (block_size > 0)")
        # traffic-plane knobs (ISSUE 9) freeze here too: a negative
        # rate or an unknown priority tier is ONE Failed status at
        # conf-freeze, not a replica exploding at load (the PR 4/7
        # convention); validate_qos is the one shared validator
        if cfg.get("qos") is not None:
            from .traffic import validate_qos

            try:
                validate_qos(cfg["qos"])
            except ValueError as e:
                raise ValueError(f"invalid engine knobs: {e}") from e
        # tenant maps validate even WITHOUT cfg qos: _sync_traffic
        # consumes them when the classes come from Profiles, and a
        # mistyped value would otherwise surface per-request at the
        # router door instead of as ONE Failed status here
        qt = cfg.get("qos_tenants")
        if qt is not None and not (
                isinstance(qt, dict)
                and all(isinstance(v, str) for v in qt.values())):
            raise ValueError(
                "invalid engine knobs: qos_tenants must map "
                "tenant -> class name")
        qtt = cfg.get("qos_tenant_tokens")
        if qtt is not None and not (
                isinstance(qtt, dict)
                and all(isinstance(v, str) for v in qtt.values())):
            raise ValueError(
                "invalid engine knobs: qos_tenant_tokens must map "
                "tenant -> bearer token string")
        ab = cfg.get("affinity_block")
        if ab is not None and int(ab) < 1:
            raise ValueError(
                f"invalid engine knobs: affinity_block {ab} (must be "
                ">= 1)")
        # failure-domain knobs (ISSUE 16) freeze here too: `domains`
        # maps domain name -> stripe weight (replicas are placed
        # round-robin across the weighted stripe, so spread is the
        # default); a mistyped map is ONE Failed status at conf-freeze,
        # not a router mis-labeling backends at the first outage
        doms = cfg.get("domains")
        if doms is not None:
            if not isinstance(doms, dict) or not doms:
                raise ValueError(
                    "invalid engine knobs: domains must be a non-empty "
                    "mapping of domain name -> stripe weight")
            for k, v in doms.items():
                if not isinstance(k, str) or not k:
                    raise ValueError(
                        "invalid engine knobs: domains keys must be "
                        f"non-empty strings (got {k!r})")
                if isinstance(v, bool) or not isinstance(v, int) or v < 1:
                    raise ValueError(
                        f"invalid engine knobs: domains[{k!r}] {v!r} "
                        "(stripe weight must be an int >= 1)")
        # hierarchical KV / durable-session knobs (ISSUE 12) freeze
        # here too — the PR 4/7/8 convention: a mistyped tier config is
        # ONE Failed status, not a replica exploding at load
        if "host_blocks" in cfg and int(cfg.get("host_blocks") or 0) > 0 \
                and int(cfg.get("block_size", 0) or 0) <= 0:
            raise ValueError(
                "invalid engine knobs: host_blocks requires the paged "
                "pool (block_size > 0)")
        hw = cfg.get("host_watermark")
        if hw is not None:
            try:
                ok = 0.0 <= float(hw) <= 1.0
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValueError(
                    f"invalid engine knobs: host_watermark {hw!r} "
                    "(must be a number in [0, 1])")
        hib = cfg.get("hibernation")
        if hib is not None:
            if not isinstance(hib, dict) or not str(hib.get("root", "")):
                raise ValueError(
                    "invalid engine knobs: hibernation must be "
                    '{"root": dir[, "fsync": bool, "reap_idle_s": s, '
                    '"reap_interval_s": s]}')
            unknown = set(hib) - {"root", "fsync", "reap_idle_s",
                                  "reap_interval_s"}
            if unknown:
                raise ValueError(
                    f"invalid engine knobs: hibernation keys "
                    f"{sorted(unknown)} unknown")
            # idle-session reaper knobs (ISSUE 15 satellite): a zero or
            # negative idle clock would hibernate sessions mid-decode
            for k in ("reap_idle_s", "reap_interval_s"):
                if hib.get(k) is not None:
                    try:
                        ok = float(hib[k]) > 0
                    except (TypeError, ValueError):
                        ok = False
                    if not ok:
                        raise ValueError(
                            f"invalid engine knobs: hibernation.{k} "
                            f"{hib[k]!r} (must be a positive number)")
            if int(cfg.get("block_size", 0) or 0) <= 0:
                raise ValueError(
                    "invalid engine knobs: hibernation requires the "
                    "paged pool (block_size > 0): the spill wire "
                    "format is the block-granular export snapshot")
        # tracing knobs (ISSUE 13) freeze here too — the PR 4/7/8
        # convention: a sample rate of 7 or a zero ring is ONE Failed
        # status at conf-freeze, not a replica (and the router) failing
        # at load; validate_tracing is the one shared validator
        if cfg.get("tracing") is not None:
            from .trace import validate_tracing

            try:
                validate_tracing(cfg["tracing"])
            except ValueError as e:
                raise ValueError(f"invalid engine knobs: {e}") from e
        # predictive autoscaler knobs (ISSUE 15) freeze here too — the
        # PR 4/7/8 convention: inverted hysteresis bands or a negative
        # cooldown is ONE Failed status at conf-freeze, not a decision
        # loop misbehaving at 4 Hz; validate_autoscale is the one
        # shared validator
        if cfg.get("autoscale") is not None:
            from .autoscale import validate_autoscale

            try:
                validate_autoscale(cfg["autoscale"])
            except (TypeError, ValueError) as e:
                raise ValueError(f"invalid engine knobs: {e}") from e
        # AOT program-artifact cache knobs (ISSUE 17) freeze here too —
        # the PR 4/7/9 convention: a missing root or a mistyped fsync
        # flag is ONE Failed status at conf-freeze, not every replica
        # failing its warmup at load; validate_aot is the one shared
        # validator
        if cfg.get("aot") is not None:
            from .programs import validate_aot

            try:
                validate_aot(cfg["aot"])
            except (TypeError, ValueError) as e:
                raise ValueError(f"invalid engine knobs: {e}") from e
        pps = cfg.get("prefix_poll_s")
        if pps is not None:
            try:
                ok = float(pps) > 0
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValueError(
                    f"invalid engine knobs: prefix_poll_s {pps!r} "
                    "(must be a positive number)")
        # elastic resize knobs (ISSUE 10) freeze here too — the PR 4/7/8
        # convention: a mistyped min_degree is ONE Failed status, not N
        # crash-looping gang pods (or a supervisor exploding at runtime).
        # The STANDALONE degraded_deadline_s fallback validates as well:
        # _track_degraded float()s it on every 4 Hz pass
        sddl = cfg.get("degraded_deadline_s")
        if sddl is not None:
            try:
                ok = float(sddl) > 0
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValueError(
                    f"invalid engine knobs: degraded_deadline_s "
                    f"{sddl!r} (must be a positive number)")
        elastic = cfg.get("elastic")
        if elastic is not None:
            if not isinstance(elastic, dict):
                raise ValueError(
                    "invalid engine knobs: elastic must be "
                    '{"min_degree": n, "resize_deadline_s": s, '
                    '"degraded_deadline_s": s}')
            unknown = set(elastic) - {"min_degree", "resize_deadline_s",
                                      "degraded_deadline_s"}
            if unknown:
                raise ValueError(
                    f"invalid engine knobs: elastic keys {sorted(unknown)}")
            if int(elastic.get("min_degree", 1)) < 1:
                raise ValueError(
                    "invalid engine knobs: elastic.min_degree "
                    f"{elastic['min_degree']} (must be >= 1)")
            for k in ("resize_deadline_s", "degraded_deadline_s"):
                if k in elastic and float(elastic[k]) <= 0:
                    raise ValueError(
                        f"invalid engine knobs: elastic.{k} "
                        f"{elastic[k]} (must be > 0)")
            if int(cfg.get("block_size", 0) or 0) <= 0:
                raise ValueError(
                    "invalid engine knobs: elastic requires the paged "
                    "pool (block_size > 0) — the resize snapshot unit "
                    "is the KV block")
        dep.rev_counter += 1
        return _Revision(
            dep.rev_counter, fingerprint, isvc.spec.model_copy(deep=True),
            runtime_cls, cfg)

    def reconcile(self, namespace: str, name: str) -> Optional[Result]:
        key = f"{namespace}/{name}"
        isvc = self.store.try_get(KIND_INFERENCE_SERVICE, name, namespace)
        if isvc is None:
            with self._lock:
                dep = self._deployments.pop(key, None)
            if dep:
                self._teardown_deployment(dep)
            return None
        assert isinstance(isvc, InferenceService)

        with self._lock:
            dep = self._deployments.setdefault(key, _Deployment())
        if dep.router is None:
            dep.router = Router(activate=lambda: self._request_scale_up(key))
            self._set_status(isvc, phase=InferenceServicePhase.LOADING,
                             message="starting predictor")

        fingerprint = self._fingerprint(isvc.spec)
        pct = isvc.spec.canary_traffic_percent
        try:
            if dep.stable is None:
                dep.stable = self._new_revision(isvc, dep, fingerprint)
            elif fingerprint != dep.stable.fingerprint:
                if pct is not None and pct < 100:
                    # canary: new revision serves pct%, stable keeps the rest
                    if dep.canary is None or dep.canary.fingerprint != fingerprint:
                        if dep.canary is not None:
                            self._drain_revision(isvc, dep.canary)
                        dep.canary = self._new_revision(isvc, dep, fingerprint)
                        self.emit_event(
                            isvc, "CanaryDeployed",
                            f"revision {dep.canary.rev} at {pct}%")
                elif dep.canary is not None and dep.canary.fingerprint == fingerprint:
                    # promote: the canary becomes the stable revision; the
                    # old stable drains (no cold start — the promoted
                    # replicas are already serving)
                    old = dep.stable
                    dep.stable, dep.canary = dep.canary, None
                    self._drain_revision(isvc, old)
                    self.emit_event(
                        isvc, "CanaryPromoted", f"revision {dep.stable.rev}")
                else:
                    # full rollout without a canary phase
                    old = dep.stable
                    dep.stable = self._new_revision(isvc, dep, fingerprint)
                    self._drain_revision(isvc, old)
            elif dep.canary is not None:
                # spec reverted to the stable revision: roll the canary back
                rolled = dep.canary
                dep.canary = None
                self._drain_revision(isvc, rolled)
                self.emit_event(
                    isvc, "CanaryRolledBack", f"revision {rolled.rev}")
        except Exception as e:  # noqa: BLE001 — config errors -> Failed phase
            self._set_status(
                isvc, phase=InferenceServicePhase.FAILED,
                message=f"{type(e).__name__}: {e}")
            return None

        dep.pct = max(0, min(100, pct or 0)) if dep.canary is not None else 0
        # predictive autoscaler (ISSUE 15): build/tick BEFORE the
        # scaling pass so this reconcile applies the tick's verdict
        self._sync_autoscaler(isvc, dep)
        for rev in dep.revisions:
            desired = self._desired_replicas(dep, rev)
            before = list(rev.predictors)
            self._scale_predictors(isvc, dep, rev, desired)
            if dep.autoscaler is not None and rev is dep.stable:
                # pre-warm placed replicas from a hot peer's registry
                # BEFORE _wire exposes them to traffic (the r12/r16
                # residual): first admissions hit a warm prefix cache
                for s in rev.predictors:
                    if s not in before:
                        try:
                            self._prewarm_replica(isvc, rev, s)
                        except Exception as e:  # noqa: BLE001 — warm
                            # cache is an optimization, never a gate
                            log.debug("replica pre-warm failed: %s", e)
        self._measure_cold_start(dep)
        self._wire(isvc, dep)
        self._sync_traffic(dep)

        def _up(rev: _Revision) -> bool:
            return any(getattr(s, "ready", True) for s in rev.predictors)

        stable_ready = (
            _up(dep.stable) or dep.stable.spec.predictor.min_replicas == 0)
        canary_ready = dep.canary is None or _up(dep.canary)
        ready = stable_ready and canary_ready
        # Degraded: serving (some replica answers) but below strength — a
        # gang re-forming after a member loss, say.  The router already
        # routes around the non-ready replicas (_wire_revision filters);
        # the phase makes the reduced capacity observable instead of
        # masquerading as fully Ready.
        total_preds = sum(len(r.predictors) for r in dep.revisions)
        ready_preds = sum(
            1 for r in dep.revisions for s in r.predictors
            if getattr(s, "ready", True))
        degraded = ready and ready_preds < total_preds
        self._track_degraded(isvc, dep, degraded)
        if degraded:
            phase = InferenceServicePhase.DEGRADED
        elif ready:
            phase = InferenceServicePhase.READY
        else:
            phase = InferenceServicePhase.LOADING
        stable_spec = dep.stable.spec.model_dump(mode="json")
        stable_spec.pop("canary_traffic_percent", None)
        self._set_status(
            isvc,
            phase=phase,
            url=dep.router.url,
            active_replicas=sum(len(r.predictors) for r in dep.revisions),
            message=(f"{total_preds - ready_preds}/{total_preds} replicas "
                     "re-forming; routing to healthy replicas"
                     if degraded else ""),
            stable_revision=dep.stable.rev,
            canary_revision=dep.canary.rev if dep.canary else None,
            canary_traffic=dep.pct,
            stable_spec=stable_spec,
        )
        # periodic requeue drives the autoscaler loop
        return Result(requeue_after=0.25)

    # -- degraded deadline / elastic escalation (ISSUE 10) ----------------

    def _track_degraded(self, isvc, dep: _Deployment,
                        degraded: bool) -> None:
        """Bound the Degraded phase.  Degraded used to be UNBOUNDED — a
        gang that lost a member permanently parked there forever,
        waiting for a re-form a dead chip can never grant.  With
        ``degraded_deadline_s`` configured (standalone or inside the
        ``elastic`` family), a deployment stuck Degraded past the
        deadline emits a structured ``DegradedTimeout`` event; with
        ``elastic`` configured, it additionally escalates into the
        shrink path — re-placing the degraded gang at the surviving
        degree (floored at ``elastic.min_degree``) and emitting
        ``GangResized`` instead of waiting forever."""
        if dep.stable is None:
            return
        if not degraded:
            dep.degraded_since = None
            dep.degraded_escalated = False
            return
        now = time.monotonic()
        if dep.degraded_since is None:
            dep.degraded_since = now
            return
        cfg = dep.stable.cfg
        elastic = cfg.get("elastic") or {}
        ddl = elastic.get("degraded_deadline_s",
                          cfg.get("degraded_deadline_s"))
        if ddl is None or dep.degraded_escalated:
            return
        try:
            ddl = float(ddl)
        except (TypeError, ValueError):
            return  # conf-freeze rejects this; a hand-rolled config
            # must not turn every 4 Hz reconcile into a raise
        waited = now - dep.degraded_since
        if waited <= ddl:
            return
        dep.degraded_escalated = True
        self.emit_event(
            isvc, "DegradedTimeout",
            f"degraded for {waited:.1f}s (deadline {ddl:.1f}s)",
            type_="Warning")
        if elastic:
            self._escalate_shrink(isvc, dep, elastic)

    def _escalate_shrink(self, isvc, dep: _Deployment,
                         elastic: dict) -> None:
        """Shrink-to-survive at the placement layer: a gang stuck
        Degraded past the deadline is re-placed with one fewer host and
        its TP degree scaled to the surviving shape.  (The in-gang
        weight/KV repartition path — serving/resize.py — handles the
        live-conversation case inside serve_main; this controller path
        is the escalate-or-give-up policy when the gang's own
        supervisor could not, e.g. a member lost before the gang ever
        formed.)"""
        from .resize import degree_of

        min_degree = int(elastic.get("min_degree", 1))
        for rev in dep.revisions:
            for i, handle in enumerate(list(rev.predictors)):
                gang = getattr(handle, "gang", None)
                if gang is None or getattr(handle, "ready", False):
                    continue
                hosts = int(gang.hosts)
                if hosts <= 1:
                    continue
                degree = degree_of(gang.mesh_axes)
                new_hosts = hosts - 1
                # compute the ACTUAL surviving mesh first and gate on
                # its product — gating on degree*new_hosts//hosts could
                # pass a min_degree the placed mesh then violates (the
                # scaling only touches one axis).  Scale the largest
                # axis (TP rides "model" by convention); an uneven
                # split means no clean surviving shape — skip rather
                # than place a mesh whose pods crash-loop (model-dim
                # feasibility itself surfaces at gang start, bounded by
                # backoff_limit; the in-gang resize path checks it at
                # plan time).
                axes = dict(gang.mesh_axes or {})
                if axes:
                    key = max(axes, key=lambda k: axes[k])
                    if (axes[key] * new_hosts) % hosts:
                        self.emit_event(
                            isvc, "ResizeSkipped",
                            f"mesh axis {key}={axes[key]} does not "
                            f"scale evenly to {new_hosts}/{hosts} "
                            "hosts; keeping the degraded gang",
                            type_="Warning")
                        continue
                    axes[key] = max(1, axes[key] * new_hosts // hosts)
                new_degree = degree_of(axes)
                if new_degree < min_degree:
                    self.emit_event(
                        isvc, "ResizeSkipped",
                        f"surviving degree {new_degree} < min_degree "
                        f"{min_degree}; keeping {hosts}-host gang",
                        type_="Warning")
                    continue
                new_gang = gang.model_copy(
                    update={"hosts": new_hosts, "mesh_axes": axes})
                handle.stop()
                rev.gang_counter += 1
                replacement = _GangPredictor(
                    self.store, isvc, rev.rev, new_gang, rev.cfg,
                    ordinal=rev.gang_counter - 1)
                rev.predictors[i] = replacement
                self.emit_event(
                    isvc, "GangResized",
                    f"degraded gang re-placed at the surviving shape: "
                    f"{hosts} hosts / TP {degree} -> {new_hosts} hosts "
                    f"/ TP {new_degree}")
                self._wire(isvc, dep)
                return

    # -- scaling ----------------------------------------------------------

    def _desired_replicas(self, dep: _Deployment, rev: _Revision) -> int:
        # gang replicas use the SAME policy as in-process ones: the unit
        # is just N host processes instead of one server, and inflight
        # concurrency comes from rank 0's /metrics probe (_GangMetrics)
        pred = rev.spec.predictor
        n = len(rev.predictors)
        # during a canary split BOTH revisions must hold the road: a
        # revision idling to zero would silently forfeit its traffic
        # share (the router drops empty pools, and with the other pool
        # still serving, the activator never fires to bring it back)
        floor = max(pred.min_replicas, 1 if dep.canary is not None else 0)
        if pred.gang is not None:
            # gangs do not scale to zero: cold start is a full JaxJob
            # placement + distributed init + model load, far beyond the
            # activator's wait — an idle-scaled gang would answer its
            # next caller with timeouts
            floor = max(floor, 1)
        if (dep.autoscaler is not None and rev is dep.stable
                and pred.gang is None):
            # predictive loop (ISSUE 15): the tick's replica actuators
            # wrote autoscale_desired and this branch REPLACES the
            # reactive idle clock below.  The activator's wake still
            # wins — demand at the door between ticks must not wait a
            # loop interval (a gated wake decision leaves
            # wants_scale_up set for exactly this backstop).
            if dep.wants_scale_up:
                dep.wants_scale_up = False
                dep.autoscale_desired = max(
                    dep.autoscale_desired or 0, 1, floor)
            target = (n if dep.autoscale_desired is None
                      else dep.autoscale_desired)
            return max(min(target, pred.max_replicas), floor)
        if dep.wants_scale_up and rev is dep.stable:
            dep.wants_scale_up = False
            return max(n, 1, floor)
        if n and n < pred.max_replicas:
            # only probe concurrency when another replica could actually
            # be added (the gang probe is an HTTP fetch; pointless work
            # stalls the shared reconcile worker)
            inflight = sum(s.metrics.inflight for s in rev.predictors)
            if inflight / n > pred.scale_target_concurrency:
                return min(n + 1, pred.max_replicas)
        idle = (
            dep.router is not None
            and time.time() - dep.router.last_request_time > SCALE_IDLE_SECONDS
        )
        if idle and n > floor:
            return max(n - 1, floor)
        return max(n, floor)

    @staticmethod
    def _domain_stripe(doms: dict) -> list[str]:
        """Expand the ``domains`` name -> stripe-weight map into an
        INTERLEAVED placement stripe (smooth WRR over sorted names, the
        router's own algorithm) — ``{"a": 2, "b": 1}`` yields
        ``[a, b, a]``, not ``[a, a, b]``, so the first two replicas
        land in different domains and spread is the default, not an
        afterthought."""
        names = sorted(doms)
        weights = {n: max(1, int(doms[n])) for n in names}
        total = sum(weights.values())
        cur = {n: 0 for n in names}
        stripe: list[str] = []
        for _ in range(total):
            for n in names:
                cur[n] += weights[n]
            best = max(names, key=lambda n: cur[n])
            cur[best] -= total
            stripe.append(best)
        return stripe

    def _assign_domain(self, rev: _Revision) -> str:
        """Failure domain for the NEXT replica of ``rev``: the least-
        filled domain relative to its stripe weight (ties: stripe
        order) — a replica placed after an outage grows back into the
        emptied domain first.  '' when ``domains`` is unconfigured
        (the single implicit domain)."""
        doms = rev.cfg.get("domains")
        if not isinstance(doms, dict) or not doms:
            return ""
        stripe = self._domain_stripe(doms)
        counts = {d: 0 for d in stripe}
        for s in rev.predictors:
            d = getattr(s, "domain", "")
            if d in counts:
                counts[d] += 1
        return min(stripe, key=lambda d: (counts[d] / stripe.count(d),
                                          stripe.index(d)))

    def _scale_predictors(
        self, isvc, dep: _Deployment, rev: _Revision, desired: int
    ) -> bool:
        gang = rev.spec.predictor.gang
        if gang is not None:
            changed = False
            while len(rev.predictors) < desired:
                rev.gang_counter += 1
                handle = _GangPredictor(
                    self.store, isvc, rev.rev, gang, rev.cfg,
                    ordinal=rev.gang_counter - 1)
                handle.domain = self._assign_domain(rev)
                rev.predictors.append(handle)
                self.emit_event(
                    isvc, "GangPlaced",
                    f"rev {rev.rev} JaxJob {handle.job_name} "
                    f"x{gang.hosts} hosts")
                changed = True
            while len(rev.predictors) > desired:
                handle = rev.predictors.pop()
                self._wire(isvc, dep)  # drop from router before deleting
                # same drain contract as in-process replicas: in-flight
                # requests (visible via the rank-0 metrics probe) finish
                # before the JaxJob is deleted
                self._drain_stop_server(isvc, handle)
                changed = True
            return changed
        changed = False
        while len(rev.predictors) < desired:
            server = ModelServer()
            model = rev.runtime_cls(isvc.metadata.name, rev.cfg)
            pred = rev.spec.predictor
            server.register(
                model,
                batch_max_size=pred.batch_max_size,
                batch_timeout_ms=pred.batch_timeout_ms,
            )
            if pred.logger is not None:
                # payload logging (kserve agent/logger analog)
                server.set_logger(
                    pred.logger.url, pred.logger.mode,
                    service=isvc.metadata.name)
            server.start()
            server.domain = self._assign_domain(rev)
            rev.predictors.append(server)
            self.emit_event(
                isvc, "ReplicaStarted", f"rev {rev.rev} {server.url}")
            changed = True
        while len(rev.predictors) > desired:
            server = rev.predictors.pop()
            self._wire(isvc, dep)  # drop from router before stopping
            # migrate-then-retire (ISSUE 8): live paged conversations
            # move to a surviving replica of the SAME revision instead
            # of racing the 5s drain deadline — a scale-down (or a node
            # making its replica unhealthy) stops costing long
            # conversations their KV.  Runs INSIDE the async drain
            # thread: per-sequence migration ops carry 60s timeouts,
            # and a wedged replica must not stall the shared reconcile
            # worker (the same invariant the bounded drain holds).
            self._drain_stop_server(isvc, server, migrate_rev=rev)
            changed = True
        return changed

    def _migrate_replica_conversations(self, isvc, rev: _Revision,
                                       server) -> int:
        """Drain a retiring in-process replica's live conversations onto
        a ready peer replica via live paged-KV migration.  The request
        handles are shared in-process, so streams in flight keep reading
        the same objects — the front server re-targets, clients never
        reconnect.  Best-effort: with no paged peer the replica falls
        back to the classic bounded drain (conversations finish or are
        cut at the deadline)."""
        engines = getattr(server, "engines", None)
        if engines is None:
            return 0  # gang handles drain via JaxJob semantics
        peers = [s for s in rev.predictors
                 if s is not server and getattr(s, "ready", True)
                 and getattr(s, "engines", None) is not None]
        moved_total = 0
        for name, eng in engines().items():
            if not getattr(eng, "paged", False):
                continue
            for peer in peers:
                dst = peer.engines().get(name)
                if dst is None or not getattr(dst, "paged", False):
                    continue
                from .continuous import migrate_live_sequences

                moved, failed = migrate_live_sequences(eng, dst)
                moved_total += moved
                if failed == 0:
                    break
        if moved_total:
            self.emit_event(
                isvc, "ConversationsMigrated",
                f"{moved_total} live conversations migrated off a "
                "retiring replica")
        return moved_total

    def _drain_stop_server(self, isvc, server: ModelServer,
                           migrate_rev: Optional[_Revision] = None) -> None:
        """Stop a replica after its in-flight requests finish.

        Drain runs asynchronously: requests already dispatched to this
        replica (or queued in its micro-batcher) finish rather than
        surfacing as 5xx, and the reconcile worker is not blocked for the
        (bounded) drain period.  The initial settle sleep covers requests
        the router already picked this backend for but whose handler has
        not yet reached _dispatch's inflight increment.  With
        ``migrate_rev`` set, live paged conversations first migrate to a
        ready peer of that revision (ISSUE 8) — on this thread, for the
        same reason the drain itself is here."""
        def _drain_stop(srv=server, svc=isvc, rev=migrate_rev):
            time.sleep(0.1)
            if rev is not None:
                try:
                    self._migrate_replica_conversations(svc, rev, srv)
                except Exception as e:  # noqa: BLE001 — migration is
                    # best-effort; the bounded drain below still runs
                    log.debug("drain migration failed: %s", e)
            deadline = time.monotonic() + 5.0
            while srv.metrics.inflight > 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            srv.stop()
            self.emit_event(svc, "ReplicaStopped", srv.url)

        threading.Thread(
            target=_drain_stop, name="replica-drain", daemon=True
        ).start()

    def _drain_revision(self, isvc, rev: _Revision) -> None:
        """Drain-and-stop every server of a retired revision (promote,
        rollback, or full replacement); the router was already rewired."""
        for server in rev.servers:
            self._drain_stop_server(isvc, server)
        rev.predictors.clear()
        rev.transformers.clear()
        rev.explainers.clear()

    def _wire_revision(self, isvc, rev: _Revision) -> tuple[list[str], list[str]]:
        """Build one revision's serving tier; returns (data-plane urls,
        explain urls) — the transformer fronts the predictors when one is
        specified, the ``:explain`` verb routes to the explainer component
        [upstream: kserve routes verbs per component]."""
        # a gang predictor exists before its rank-0 frontend answers; only
        # READY predictors take traffic (in-process ModelServers are ready
        # by construction)
        ready_predictors = [
            s for s in rev.predictors if getattr(s, "ready", True)]
        explain_urls: list[str] = []
        espec = rev.spec.explainer
        if espec and espec.handler:
            if not rev.explainers and rev.predictors:
                cls = resolve_class(espec.handler)
                server = ModelServer()
                model = cls(isvc.metadata.name, {
                    **dict(espec.config),
                    "predictor_urls": [s.url for s in ready_predictors],
                    "model_name": isvc.metadata.name,
                })
                server.register(model, batch_max_size=1, batch_timeout_ms=0.0)
                server.start()
                rev.explainers.append(server)
            if rev.explainers:
                urls = [s.url for s in ready_predictors]
                for es in rev.explainers:
                    for m in es.models().values():
                        if hasattr(m, "predictor_urls"):
                            m.predictor_urls = list(urls)
                # with zero predictors, :explain must fall through to the
                # activator (empty pool -> scale-from-zero) instead of
                # reaching an explainer that has nothing to call
                explain_urls = [s.url for s in rev.explainers] if urls else []
        tspec = rev.spec.transformer
        if tspec and tspec.handler:
            if not rev.transformers and rev.predictors:
                cls = resolve_class(tspec.handler)
                cfg = dict(tspec.config)
                cfg["predictor_url"] = None  # filled per request via backends
                server = ModelServer()
                model = cls(isvc.metadata.name, {
                    **cfg, "predictor_urls": [s.url for s in ready_predictors],
                    "model_name": isvc.metadata.name,
                })
                server.register(model, batch_max_size=tspec.batch_max_size,
                                batch_timeout_ms=tspec.batch_timeout_ms)
                server.start()
                rev.transformers.append(server)
            if rev.transformers:
                # keep the transformer's predictor list current: predictors
                # churn on every scale event and ports never come back
                urls = [s.url for s in ready_predictors]
                for ts in rev.transformers:
                    for m in ts.models().values():
                        if hasattr(m, "predictor_urls"):
                            m.predictor_urls = list(urls)
                return [s.url for s in rev.transformers], explain_urls
        return [s.url for s in ready_predictors], explain_urls

    def _wire(self, isvc, dep: _Deployment) -> None:
        """Point the router at every live revision, weighted by the canary
        split (the virtualservice-weight analog)."""
        if dep.router is None or dep.stable is None:
            return
        stable_urls, stable_explain = self._wire_revision(isvc, dep.stable)
        pools = [(stable_urls, 100 - dep.pct)]
        explain_pools = [(stable_explain, 100 - dep.pct)]
        if dep.canary is not None:
            canary_urls, canary_explain = self._wire_revision(isvc, dep.canary)
            pools.append((canary_urls, dep.pct))
            explain_pools.append((canary_explain, dep.pct))
        dep.router.set_weighted_backends(pools)
        dep.router.set_weighted_explain_backends(explain_pools)
        # failure-domain labels ride the same wiring pass (ISSUE 16):
        # the router's outage detection and re-route spreading key off
        # this map; with ``domains`` unset it stays empty and the
        # router behaves exactly as before (single implicit domain)
        mapping: dict[str, str] = {}
        for r in dep.revisions:
            for s in r.predictors:
                d = getattr(s, "domain", "")
                u = getattr(s, "url", None)
                if d and u:
                    mapping[u] = d
        dep.router.set_domains(mapping)

    def _sync_traffic(self, dep: _Deployment) -> None:
        """Keep the router's traffic plane (ISSUE 9) in sync with the
        stable revision's ``qos``/affinity knobs MERGED with every
        Profile carrying ``spec.qos`` — Profiles are the tenants, so a
        tenant's rate/priority contract follows it to every ISvc
        front door.  The plane rebuilds only when the merged config
        changes (fingerprinted): counters and the affinity map survive
        the 4 Hz reconcile loop."""
        if dep.router is None or dep.stable is None:
            return
        cfg = dep.stable.cfg
        # request tracing (ISSUE 13): the router is the path's root
        # sampling decision; the same cfg knob builds the replica-side
        # tracer inside TextGenerator.load.  Validated at conf-freeze;
        # a racing bad edit here must not stall the reconcile loop.
        try:
            dep.router.configure_tracing(cfg.get("tracing"))
        except ValueError as e:
            log.debug("router tracing config rejected: %s", e)
        if cfg.get("prefix_poll_s"):
            # cluster block-registry poller (ISSUE 13 satellite)
            try:
                dep.router.start_prefix_poller(float(cfg["prefix_poll_s"]))
            except (TypeError, ValueError) as e:
                log.debug("prefix poller config rejected: %s", e)
        qos = dict(cfg.get("qos") or {})
        tenants = dict(cfg.get("qos_tenants") or {})
        from ..api.platform import KIND_PROFILE

        from .traffic import TrafficPlane, validate_qos

        tokens: dict[str, str] = {}
        for prof in self.store.list(KIND_PROFILE):
            pq = getattr(prof.spec, "qos", None)
            if not pq:
                continue
            if prof.spec.api_token:
                # a credentialed Profile's class may only be claimed
                # with its Bearer token (plane.authenticate at the
                # door) — QoS classes are identity-scoped privilege
                tokens[prof.metadata.name] = prof.spec.api_token
            if prof.metadata.name in qos:
                continue  # explicit ISvc config wins over the Profile
            try:
                validate_qos({prof.metadata.name: pq})
            except (TypeError, ValueError):
                continue  # the Profile controller reports it (Failed);
                # _sync_traffic runs OUTSIDE reconcile's Failed-phase
                # guard, so one bad Profile must never break every
                # ISvc's status/scaling loop
            qos[prof.metadata.name] = dict(pq)
        # affinity_block doubles as the affinity-only opt-in: a config
        # with no qos classes but an explicit affinity granularity
        # still wants the prefix-aware router
        enabled = bool(qos) or cfg.get("affinity_block") is not None
        if not enabled:
            if dep.traffic_fp is not None:
                dep.router.set_traffic(None)
                dep.traffic_fp = None
            return
        fp = json.dumps(
            {"qos": qos, "tenants": tenants, "tokens": tokens,
             "block": cfg.get("affinity_block", 32)},
            sort_keys=True, default=str)
        if fp == dep.traffic_fp:
            return
        try:
            plane = TrafficPlane(
                qos, tenants=tenants, tenant_tokens=tokens,
                affinity_block=int(cfg.get("affinity_block", 32)))
        except (TypeError, ValueError) as e:
            # cfg qos was validated at conf-freeze; this can only be a
            # racing Profile edit — keep the previous plane
            log.debug("traffic plane rebuild rejected: %s", e)
            return
        dep.router.set_traffic(plane)
        dep.traffic_fp = fp

    def _request_scale_up(self, key: str) -> None:
        with self._lock:
            dep = self._deployments.get(key)
        if dep is not None:
            dep.wants_scale_up = True
        self.queue.add(key)

    # -- predictive autoscaler (ISSUE 15) ---------------------------------

    def _sync_autoscaler(self, isvc, dep: _Deployment) -> None:
        """Keep the deployment's :class:`~.autoscale.ClusterAutoscaler`
        in sync with the stable revision's ``autoscale:`` knob family
        (fingerprinted like the traffic plane — predictor window,
        cooldown clocks and retry state survive the 4 Hz reconcile),
        then run one tick.  The tick runs HERE, on the reconcile
        worker: this controller is single-worker precisely because
        reconciles mutate live deployment state, and the decision
        loop's actuators (victim ordering, tier rebalance, engine
        resize) are exactly such mutations — a free-running thread
        would race every reconcile.  ``ClusterAutoscaler.start()``
        remains the threaded mode for the bench/standalone path."""
        if dep.stable is None:
            return
        spec = dep.stable.cfg.get("autoscale")
        if spec is None:
            if dep.autoscaler is not None:
                dep.autoscaler = None
                dep.autoscale_fp = None
                dep.autoscale_desired = None
                dep.cold_start_t0 = None
            return
        fp = json.dumps(spec, sort_keys=True, default=str)
        if fp != dep.autoscale_fp:
            from .autoscale import AutoscalePolicy, ClusterAutoscaler

            try:
                policy = AutoscalePolicy.from_config(dict(spec))
            except (TypeError, ValueError) as e:
                # conf-freeze validated this; only a racing edit of a
                # live cfg dict can land here — keep the previous loop
                log.debug("autoscale config rejected: %s", e)
                return
            dep.autoscaler = ClusterAutoscaler(
                policy,
                sensors=lambda: self._autoscale_signals(dep),
                actuators=self._autoscale_actuators(isvc, dep))
            dep.autoscale_fp = fp
            dep.autoscale_desired = None
            from .autoscale import ConcurrencyGate
            dep.thaw_gate = (
                ConcurrencyGate(int(policy.thaw_concurrency))
                if policy.thaw_concurrency > 0 else None)
        # attach the thaw cap to every live engine each pass — engines
        # churn with replica placement, the gate survives via dep
        if dep.thaw_gate is not None and dep.stable is not None:
            for s in dep.stable.predictors:
                engines = getattr(s, "engines", None)
                if engines is None:
                    continue
                for eng in engines().values():
                    eng.thaw_gate = dep.thaw_gate
        dec = dep.autoscaler.tick()
        if dec.action != "none":
            self.emit_event(
                isvc, "AutoscaleDecision", f"{dec.action}: {dec.reason}")

    def _autoscale_signals(self, dep: _Deployment) -> dict:
        """One sensor snapshot for ``autoscale.decide`` — in-process
        stats reads only (plane counters, tracer summary, engine
        ``stats()``/``tier_pressure()``, the router idle clock).  No
        blocking HTTP: this runs on the shared reconcile worker."""
        rev = dep.stable
        pol = dep.autoscaler.policy
        preds = [] if rev is None else list(rev.predictors)
        spec = rev.spec.predictor if rev is not None else None
        n = len(preds)
        inflight = 0
        live = 0.0
        free_ratio = 1.0
        degree = 0
        pp = dp = 0.0
        pn = dn = 0
        for s in preds:
            try:
                inflight += int(s.metrics.inflight)
            except (AttributeError, TypeError):
                pass
            engines = getattr(s, "engines", None)
            if engines is None:
                continue
            for eng in engines().values():
                tier = getattr(eng, "tier_pressure", None)
                if tier is not None:
                    t = tier()
                    pp += t["prefill_pressure"]
                    dp += t["decode_pressure"]
                    pn += t["prefill_replicas"]
                    dn += t["decode_replicas"]
                st = eng.stats()
                live += float(st.get("slots_live", 0) or 0)
                total = float(st.get("kv_blocks_total", 0) or 0)
                if total > 0:
                    free_ratio = min(
                        free_ratio,
                        float(st.get("kv_blocks_free", 0)) / total)
                mesh = getattr(eng, "mesh", None)
                degree = max(degree,
                             int(mesh.size) if mesh is not None else 1)
        now = time.monotonic()
        shed_rate = 0.0
        plane = dep.router.traffic if dep.router is not None else None
        if plane is not None:
            total_sheds = sum(
                int(c.get("qos_shed_total", 0))
                for c in plane.stats().get("classes", {}).values())
            t0, s0 = dep.shed_mark
            if t0 and now > t0:
                shed_rate = max(0.0, (total_sheds - s0) / (now - t0))
            dep.shed_mark = (now, float(total_sheds))
        qwait = 0.0
        tracer = dep.router.tracer if dep.router is not None else None
        if tracer is not None:
            summary = tracer.sink.summary(pol.window_s)
            for c in summary.get("classes", {}).values():
                if c.get("traces"):
                    qwait = max(qwait,
                                c["queue_wait_sum_s"] / c["traces"])
        idle_s = 0.0
        if dep.router is not None and dep.router.last_request_time:
            idle_s = max(0.0, time.time()
                         - dep.router.last_request_time)
        # Correlated-failure sensor (ISSUE 16): fraction of the
        # router's backend pool whose health circuit is not closed.
        # Feeds the emergency surge rule in ``autoscale.decide`` —
        # absent circuits (no router yet) read as a healthy 0.0.
        unhealthy = 0.0
        if dep.router is not None:
            urls = dep.router.backends()
            if urls:
                bad = sum(1 for u in urls
                          if dep.router.health.state(u) != "closed")
                unhealthy = bad / len(urls)
        return {
            "replicas": n,
            "min_replicas": spec.min_replicas if spec else 0,
            "max_replicas": spec.max_replicas if spec else max(n, 1),
            "util": (inflight / max(n, 1)
                     / max(pol.target_concurrency, 1e-9)),
            "shed_rate": shed_rate,
            "queue_wait_s": qwait,
            "free_block_ratio": free_ratio,
            "idle_s": idle_s,
            "live": live,
            "pending": 1.0 if dep.wants_scale_up else 0.0,
            "degree": degree,
            "prefill_pressure": pp,
            "decode_pressure": dp,
            "prefill_replicas": pn,
            "decode_replicas": dn,
            "unhealthy_frac": unhealthy,
        }

    def _autoscale_actuators(self, isvc, dep: _Deployment) -> dict:
        """The controller's actuator channel map.  Replica channels
        write ``autoscale_desired`` — the SAME ``_scale_predictors``
        machinery the reactive path uses then applies it this pass, so
        scale-down stays the lossless migrate-then-retire drain and
        the canary/gang invariants hold unchanged."""

        def _replica_up(dec) -> None:
            rev = dep.stable
            cur = 0 if rev is None else len(rev.predictors)
            if cur == 0 and dep.cold_start_t0 is None:
                dep.cold_start_t0 = time.monotonic()
            dep.autoscale_desired = max(
                int(dec.replicas if dec.replicas is not None
                    else cur + 1), 1)
            dep.wants_scale_up = False

        def _replica_down(dec) -> None:
            rev = dep.stable
            if rev is None or len(rev.predictors) <= 1:
                raise RuntimeError("no replica to retire")
            self._order_scale_down_victim(dep, rev)
            dep.autoscale_desired = int(
                dec.replicas if dec.replicas is not None
                else len(rev.predictors) - 1)

        def _zero(dec) -> None:
            self._hibernate_for_zero(isvc, dep)
            dep.autoscale_desired = 0

        def _resize(dec) -> None:
            self._resize_replicas_to_degree(isvc, dep, int(dec.degree))

        def _tier(dec) -> None:
            rev = dep.stable
            for s in ([] if rev is None else rev.predictors):
                engines = getattr(s, "engines", None)
                if engines is None:
                    continue
                for eng in engines().values():
                    fn = getattr(eng, "rebalance", None)
                    if fn is None:
                        continue
                    npools = len(eng.pools)
                    fn(max(1, min(int(dec.prefill), npools - 1)))
                    return
            raise RuntimeError("no disaggregated pool to rebalance")

        return {"replica_up": _replica_up, "replica_down": _replica_down,
                "zero": _zero, "resize": _resize, "tier": _tier}

    def _order_scale_down_victim(self, dep: _Deployment,
                                 rev: _Revision) -> None:
        """Reorder ``rev.predictors`` so the least session/prefix-heat
        replica sits LAST — ``_scale_predictors`` pops from the tail,
        so the victim is the replica whose retirement invalidates the
        least cluster KV reuse (poller prefix census) and migrates the
        fewest live conversations.  Domain-spread guard (ISSUE 16):
        a candidate whose retirement would EMPTY its failure domain
        while another domain still holds >= 2 replicas is excluded —
        scale-down must never trade away the last replica of a domain
        the placement stripe deliberately spread into.  With
        ``domains`` unset every replica maps to the implicit ""
        domain and the guard is a no-op."""
        preds = rev.predictors
        if len(preds) < 2:
            return
        poller = (dep.router.prefix_poller
                  if dep.router is not None else None)
        heat = poller.heat_by_backend() if poller is not None else {}

        counts: dict[str, int] = {}
        for s in preds:
            counts[getattr(s, "domain", "")] = counts.get(
                getattr(s, "domain", ""), 0) + 1

        def allowed(s) -> bool:
            d = getattr(s, "domain", "")
            if counts.get(d, 0) > 1:
                return True
            # removing s empties domain d — only allowed when no OTHER
            # domain would keep >= 2 replicas (i.e. spread is already
            # as thin as it can be)
            return not any(c >= 2 for dd, c in counts.items()
                           if dd != d)

        candidates = [s for s in preds if allowed(s)] or preds

        def score(s) -> tuple:
            h = int(heat.get(getattr(s, "url", ""), 0))
            live = 0
            engines = getattr(s, "engines", None)
            if engines is not None:
                for eng in engines().values():
                    try:
                        live += int(eng.stats().get("slots_live", 0))
                    except (AttributeError, TypeError, RuntimeError):
                        pass
            return (h, live)

        victim = min(candidates, key=score)
        if preds[-1] is not victim:
            preds.remove(victim)
            preds.append(victim)

    def _prewarm_replica(self, isvc, rev: _Revision, server) -> int:
        """Warm a freshly placed replica's prefix registry from a hot
        peer before it takes traffic: registry-census the peer
        (``prefix_census``), export its block content
        (``export_prefix_blocks`` — the in-process ``kv_fetch``) and
        ``install_prefix`` into the new pool.  Bounded and best-effort:
        a cold replica that serves its first request un-warmed just
        prefills, exactly as before this path existed."""
        engines = getattr(server, "engines", None)
        if engines is None:
            return 0  # gang replicas warm through serve_main
        peers = [s for s in rev.predictors
                 if s is not server and getattr(s, "ready", True)
                 and getattr(s, "engines", None) is not None]
        installed = 0
        with self._prewarm_gate:
            for name, eng in engines().items():
                if not getattr(eng, "paged", False):
                    continue
                for peer in peers:
                    src = peer.engines().get(name)
                    if src is None or not getattr(src, "paged", False):
                        continue
                    try:
                        census = src.prefix_census(timeout=10.0)
                    except (RuntimeError, TimeoutError):
                        continue
                    # deepest records first; cap the copy budget so
                    # warm-up can never stall the reconcile pass
                    # behind a huge pool
                    census = sorted(census, key=len, reverse=True)[:8]
                    for toks in census:
                        try:
                            covered, blocks = src.export_prefix_blocks(
                                [int(t) for t in toks], timeout=10.0)
                            if covered and blocks and eng.install_prefix(
                                    covered, blocks, timeout=10.0):
                                installed += 1
                        except (RuntimeError, TimeoutError):
                            break
                    break  # one warm peer per engine is enough
        if installed:
            self.emit_event(
                isvc, "ReplicaPrewarmed",
                f"{installed} hot prefixes installed before traffic")
        return installed

    def _hibernate_for_zero(self, isvc, dep: _Deployment) -> int:
        """Scale-to-zero prologue: park every session durably in the
        spill store before the fleet tears down — a zero with live
        sessions would otherwise trade HBM for lost conversations.
        ``idle_sessions(0.0)`` enumerates every session-tagged
        sequence; a failed spill resumes in place (and the teardown
        still drains losslessly via the migrate-then-retire path)."""
        rev = dep.stable
        parked = 0
        for s in ([] if rev is None else rev.predictors):
            engines = getattr(s, "engines", None)
            if engines is None:
                continue
            for eng in engines().values():
                probe = getattr(eng, "idle_sessions", None)
                if (probe is None
                        or getattr(eng, "spill_store", None) is None):
                    continue
                for req in probe(0.0):
                    sid = getattr(req, "session_id", None)
                    if not sid:
                        continue
                    try:
                        if eng.hibernate_sequence(req, sid):
                            parked += 1
                    except (RuntimeError, TimeoutError) as e:
                        log.debug("pre-zero hibernate %s failed: %s",
                                  sid, e)
        if parked:
            self.emit_event(
                isvc, "SessionsHibernated",
                f"{parked} sessions hibernated ahead of scale-to-zero")
        return parked

    def _resize_replicas_to_degree(self, isvc, dep: _Deployment,
                                   degree: int) -> None:
        """TP-degree actuator for in-process replicas: run the PR 9
        copy-then-cutover resize on every plain paged engine behind the
        stable revision (``swap_engine`` re-points the runtime, the
        preemptors and the tracer follow the pool).  Tiered/disagg
        engines are skipped — their capacity knob is the tier split,
        not the degree.  Raises when nothing resized: the decision
        demanded throughput the fleet cannot deliver, and the loop's
        bounded-retry backoff must see that, not a silent no-op."""
        from .resize import GangResizer

        rev = dep.stable
        resized = 0
        err: Optional[Exception] = None
        for s in ([] if rev is None else rev.predictors):
            models = getattr(s, "models", None)
            if models is None:
                continue
            for model in models().values():
                eng = getattr(model, "engine", None)
                if (eng is None or not getattr(eng, "paged", False)
                        or getattr(eng, "pools", None) is not None):
                    continue
                try:
                    resizer = GangResizer(
                        eng,
                        set_engine=getattr(model, "swap_engine", None))
                    if resizer.degree() == int(degree):
                        continue
                    resizer.resize_to_degree(int(degree))
                    resized += 1
                except Exception as e:  # noqa: BLE001 — a failed resize
                    # already resumed the old degree in place; surface
                    # it to the actuator's retry budget below
                    err = e
        if err is not None:
            raise RuntimeError(
                f"TP resize to degree {degree} failed on a replica"
            ) from err
        if not resized:
            raise RuntimeError(
                f"no replica engine accepted a TP resize to {degree}")
        self.emit_event(
            isvc, "GangResized",
            f"{resized} replica engine(s) resized to TP degree {degree}"
            " by the autoscaler")

    def _measure_cold_start(self, dep: _Deployment) -> None:
        """Close the wake-from-zero clock once every stable replica
        reports ready — the measured budget ``decide`` holds
        scale-to-zero to (zero is only cheap if waking is)."""
        if (dep.autoscaler is None or dep.cold_start_t0 is None
                or dep.stable is None):
            return
        preds = dep.stable.predictors
        want = dep.autoscale_desired
        if (preds and (want is None or len(preds) >= want)
                and all(getattr(s, "ready", True) for s in preds)):
            dep.autoscaler.note_cold_start(
                time.monotonic() - dep.cold_start_t0,
                warm=self._wake_was_warm(preds))
            dep.cold_start_t0 = None

    @staticmethod
    def _wake_was_warm(preds) -> bool:
        """Did this wake-from-zero serve its program ladder out of the
        AOT artifact cache?  A build that compiled even one rung sits on
        the cold budget — mixing it into the warm EWMA would let
        ``decide`` scale to zero against a wake time the fleet cannot
        actually hit."""
        saw_cache = False
        for s in preds:
            engines = getattr(s, "engines", None)
            if engines is None:
                continue
            for eng in engines().values():
                try:
                    st = eng.stats()
                except (RuntimeError, TimeoutError):
                    return False
                hits = st.get("aot_cache_hits_total")
                if hits is None:
                    continue
                saw_cache = True
                if st.get("aot_cache_misses_total", 0) > 0 or hits <= 0:
                    return False
        return saw_cache

    # -- resolution -------------------------------------------------------

    def _resolve(self, isvc: InferenceService):
        pred = isvc.spec.predictor
        runtime: Optional[ServingRuntime] = None
        if pred.runtime:
            rt = self.store.try_get(KIND_SERVING_RUNTIME, pred.runtime, "default")
            if rt is None:
                raise ValueError(f"runtime {pred.runtime!r} not found")
            assert isinstance(rt, ServingRuntime)
            runtime = rt
        elif pred.model_format is not None:
            runtimes = [
                r for r in self.store.list(KIND_SERVING_RUNTIME)
                if isinstance(r, ServingRuntime)
            ]
            runtime = select_runtime(pred.model_format, runtimes)
            if runtime is None:
                raise ValueError(
                    f"no ServingRuntime supports model format "
                    f"{pred.model_format.name!r}")
        elif pred.handler:
            cfg = dict(pred.config)
            if pred.storage_uri:
                cfg.setdefault("storage_path", download(
                    pred.storage_uri, cache_dir=cfg.get("model_cache_dir"),
                    hf_root=cfg.get("hf_root")))
                cfg.setdefault("storage_uri", pred.storage_uri)
            return resolve_class(pred.handler), cfg
        else:
            raise ValueError("predictor needs runtime, model_format, or handler")

        cfg = {**runtime.spec.config, **pred.config}
        if pred.storage_uri:
            # merged cfg so a ServingRuntime can enable the cache for all
            # of its models, with the component able to override
            cfg.setdefault("storage_path", download(
                    pred.storage_uri, cache_dir=cfg.get("model_cache_dir"),
                    hf_root=cfg.get("hf_root")))
            cfg.setdefault("storage_uri", pred.storage_uri)
        return resolve_class(runtime.spec.server_class), cfg

    # -- teardown / status -------------------------------------------------

    def _teardown_deployment(self, dep: _Deployment) -> None:
        dep.autoscaler = None
        dep.autoscale_fp = None
        dep.autoscale_desired = None
        for rev in dep.revisions:
            for s in rev.servers:
                s.stop()
            rev.predictors.clear()
            rev.transformers.clear()
            rev.explainers.clear()
        dep.stable = dep.canary = None
        if dep.router:
            dep.router.stop()
            dep.router = None

    def _set_status(self, isvc, phase=None, url=None, active_replicas=None,
                    message=None, stable_revision=None, canary_revision=...,
                    canary_traffic=None, stable_spec=None):
        def mut(o):
            assert isinstance(o, InferenceService)
            if phase is not None:
                o.status.phase = phase
            if url is not None:
                o.status.url = url
            if active_replicas is not None:
                o.status.active_replicas = active_replicas
            if message is not None:
                o.status.message = message
            if stable_revision is not None:
                o.status.stable_revision = stable_revision
            if canary_revision is not ...:  # None is a real value (no canary)
                o.status.canary_revision = canary_revision
            if canary_traffic is not None:
                o.status.canary_traffic = canary_traffic
            if stable_spec is not None:
                o.status.stable_spec = stable_spec

        try:
            self.store.update_with_retry(
                KIND_INFERENCE_SERVICE, isvc.metadata.name, isvc.metadata.namespace, mut)
        except NotFound:
            pass
