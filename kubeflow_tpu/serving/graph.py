"""InferenceGraph: DAG routing over InferenceServices.

The kserve InferenceGraph capability [upstream: kserve ->
pkg/apis/serving/v1alpha1 InferenceGraph, cmd/router]: a graph CRD whose
router executes the full node set — Sequence (chain steps, each seeing the
previous response or the original request), Switch (first matching
condition wins), Ensemble (steps fan out in parallel, outputs merged under
step names), Splitter (weighted traffic split) — over live
InferenceServices.  The router resolves target URLs from the store at
request time, so ISvc redeploys/scaling never require a graph update.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from concurrent import futures
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ..api.inference import (
    KIND_INFERENCE_GRAPH,
    KIND_INFERENCE_SERVICE,
    GraphNode,
    InferenceGraph,
    InferenceService,
    InferenceServicePhase,
)
from ..controlplane.controller import Controller, Result
from ..controlplane.store import NotFound, Store
from ..utils.net import allocate_port


class GraphExecutionError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


def eval_condition(condition: str, payload: dict) -> bool:
    """``key == value`` / ``!=`` / ``>`` / ``<`` against the request JSON.

    Values compare as numbers when both sides parse as float, else as
    strings (quotes optional).  Missing keys never match.
    """
    for op in ("==", "!=", ">", "<"):
        if op in condition:
            key, _, raw = condition.partition(op)
            key, raw = key.strip(), raw.strip().strip("'\"")
            if key not in payload:
                return False
            actual = payload[key]
            try:
                a, b = float(actual), float(raw)
            except (TypeError, ValueError):
                a, b = str(actual), raw
            if op == "==":
                return a == b
            if op == "!=":
                return a != b
            if op == ">":
                return a > b
            return a < b
    raise GraphExecutionError(400, f"unparseable condition {condition!r}")


class GraphExecutor:
    """Executes one graph over live ISvc URLs (pure logic, no HTTP server)."""

    def __init__(
        self,
        graph: InferenceGraph,
        url_for: Callable[[str], Optional[str]],
        timeout: float = 60.0,
    ):
        self.graph = graph
        self.url_for = url_for
        self.timeout = timeout
        # shared pool for Ensemble fan-out: the executor is long-lived (one
        # per GraphRouter), so per-request pool churn is avoidable overhead
        self._pool = futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="graph-ensemble")

    def execute(self, payload: dict) -> dict:
        return self._run_node("root", payload, payload)

    def _node(self, name: str) -> GraphNode:
        node = self.graph.spec.nodes.get(name)
        if node is None:
            raise GraphExecutionError(500, f"graph node {name!r} not found")
        return node

    def _run_node(self, name: str, payload: dict, original: dict) -> dict:
        node = self._node(name)
        if node.router_type == "Switch":
            for step in node.steps:
                if step.condition is None or eval_condition(step.condition, payload):
                    return self._run_step(step, payload, original)
            raise GraphExecutionError(404, "no switch condition matched")
        if node.router_type == "Ensemble":
            # all steps see the same input concurrently; response maps step
            # name -> output [upstream: kserve router Ensemble semantics]
            keys = [
                step.name or step.service_name or step.node_name or str(i)
                for i, step in enumerate(node.steps)
            ]
            if len(set(keys)) != len(keys):
                raise GraphExecutionError(
                    500, "ensemble steps need distinct names (set step.name)")
            pending = {
                key: self._pool.submit(self._run_step, step, payload, original)
                for key, step in zip(keys, node.steps)
            }
            return {k: f.result() for k, f in pending.items()}
        if node.router_type == "Splitter":
            weights = [1 if s.weight is None else s.weight for s in node.steps]
            if any(w < 0 for w in weights):
                raise GraphExecutionError(500, "splitter weights must be >= 0")
            total = sum(weights)
            if total <= 0 or not node.steps:
                raise GraphExecutionError(500, "splitter has no weighted steps")
            # strict < so an explicit weight=0 step can never win (kserve
            # semantics: zero weight = drained, no traffic)
            pick = random.random() * total
            acc = 0.0
            for step, w in zip(node.steps, weights):
                acc += w
                if pick < acc:
                    return self._run_step(step, payload, original)
            return self._run_step(
                max(zip(node.steps, weights), key=lambda sw: sw[1])[0],
                payload, original)
        if node.router_type != "Sequence":
            raise GraphExecutionError(
                500, f"unknown router_type {node.router_type!r}")
        out = payload
        for step in node.steps:
            data = original if step.data == "$request" else out
            out = self._run_step(step, data, original)
        return out

    def _run_step(self, step, payload: dict, original: dict) -> dict:
        if step.node_name:
            return self._run_node(step.node_name, payload, original)
        if not step.service_name:
            raise GraphExecutionError(500, "step has neither service nor node")
        url = self.url_for(step.service_name)
        if url is None:
            raise GraphExecutionError(
                503, f"InferenceService {step.service_name!r} not ready")
        # V1 chaining: a previous step's {"predictions": ...} feeds the next
        # step as {"instances": ...}
        if "instances" not in payload and "predictions" in payload:
            payload = {**{k: v for k, v in payload.items() if k != "predictions"},
                       "instances": payload["predictions"]}
        req = urllib.request.Request(
            f"{url}/v1/models/{step.service_name}:predict",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise GraphExecutionError(e.code, e.read().decode()[:500])
        except OSError as e:
            raise GraphExecutionError(502, str(e))


class GraphRouter:
    """HTTP front door for one InferenceGraph."""

    def __init__(self, executor: GraphExecutor, port: Optional[int] = None):
        self.executor = executor
        self.port = port or allocate_port()
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    payload = json.loads(self.rfile.read(length)) if length else {}
                    out = router.executor.execute(payload)
                    body, code = json.dumps(out).encode(), 200
                except GraphExecutionError as e:
                    body, code = json.dumps({"error": str(e)}).encode(), e.code
                except (ValueError, TypeError) as e:
                    body, code = json.dumps({"error": str(e)}).encode(), 400
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                body = json.dumps({"graph": router.executor.graph.metadata.name,
                                   "nodes": list(router.executor.graph.spec.nodes)})
                raw = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"graph-router-{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)
        self.executor._pool.shutdown(wait=False, cancel_futures=True)


class InferenceGraphController(Controller):
    """Reconciles InferenceGraph -> running GraphRouter + status."""

    kind = KIND_INFERENCE_GRAPH

    def __init__(self, store: Store) -> None:
        super().__init__(store)
        self._routers: dict[str, GraphRouter] = {}

    def stop(self) -> None:
        super().stop()
        for r in self._routers.values():
            r.stop()
        self._routers.clear()

    def _url_for(self, namespace: str) -> Callable[[str], Optional[str]]:
        def lookup(service_name: str) -> Optional[str]:
            isvc = self.store.try_get(
                KIND_INFERENCE_SERVICE, service_name, namespace)
            if (
                isinstance(isvc, InferenceService)
                and isvc.status.phase == InferenceServicePhase.READY
            ):
                return isvc.status.url
            return None

        return lookup

    def reconcile(self, namespace: str, name: str) -> Optional[Result]:
        key = f"{namespace}/{name}"
        graph = self.store.try_get(KIND_INFERENCE_GRAPH, name, namespace)
        if graph is None:
            router = self._routers.pop(key, None)
            if router:
                router.stop()
            return None
        assert isinstance(graph, InferenceGraph)

        if "root" not in graph.spec.nodes:
            self._set_status(
                graph, InferenceServicePhase.FAILED, message="no 'root' node")
            return None

        router = self._routers.get(key)
        if router is None:
            executor = GraphExecutor(graph, self._url_for(namespace))
            router = GraphRouter(executor)
            self._routers[key] = router
            self.emit_event(graph, "RouterStarted", router.url)
        else:
            router.executor.graph = graph  # pick up spec edits in place

        # Ready once every referenced service is Ready (services referenced
        # from nested nodes included)
        missing = []
        for node in graph.spec.nodes.values():
            for step in node.steps:
                if step.service_name and self._url_for(namespace)(step.service_name) is None:
                    missing.append(step.service_name)
        if missing:
            self._set_status(
                graph, InferenceServicePhase.LOADING,
                url=router.url, message=f"waiting for {sorted(set(missing))}")
            return Result(requeue_after=0.1)
        self._set_status(graph, InferenceServicePhase.READY, url=router.url)
        return None

    def _set_status(self, graph, phase, url=None, message="") -> None:
        def mut(o):
            assert isinstance(o, InferenceGraph)
            o.status.phase = phase
            o.status.url = url
            o.status.message = message

        try:
            self.store.update_with_retry(
                KIND_INFERENCE_GRAPH, graph.metadata.name,
                graph.metadata.namespace, mut)
        except NotFound:
            pass
