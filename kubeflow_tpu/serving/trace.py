"""Request-lifecycle tracing: phase-attributed latency for the serving path.

The platform's mechanisms are deep (paged KV -> migration -> QoS doors ->
elastic resize -> KV tiering) but latency was only observable END TO END:
when a token is slow nothing could say whether the time went to the
router's QoS queue, the engine admission queue, a prefill chunk, a verify
dispatch, a COW copy, a host-tier spill, or a mid-stream resize.  Both
Tenplex (PAPERS.md — a resize cost is only schedulable once decomposed
into drain/reshard/resume) and the Gemma-on-TPU serving comparison
(PAPERS.md — TTFT/ITL *breakdowns*, not means, are the comparable
quantities) argue that per-phase attribution is the unit of serving
performance analysis; ROADMAP item 2's predictive autoscaler needs
exactly this signal (queue depth and stall CAUSES, not totals).

Design, pure stdlib and sampling by construction:

- :class:`Span` — monotonic start/end, parent id, structured attrs.
  Plain ``__slots__`` objects; opening/closing one is two clock reads
  and a list append.
- :class:`Trace` — one request's span tree, assembled LOCK-FREE on
  whichever thread currently owns the request's lifecycle: span/phase
  appends are single ``list.append`` calls (GIL-atomic), and ownership
  hands off at the same seams the engine already defines (HTTP thread
  -> scheduler thread via ``submit``, scheduler -> migration worker via
  the mailbox).  The PHASE TRACK is the load-bearing invariant: phases
  are sequential and CONTIGUOUS — ``phase(name)`` closes the current
  phase and opens the next at the same timestamp — so the per-phase
  durations of a trace tile its root span and sum to the end-to-end
  latency (pinned within 5% by tests/test_observability.py).  Detail
  spans (each prefill chunk, each decode/verify dispatch with its
  program family + warmed rung, a COW copy, a migration export) overlap
  freely underneath, parented to the phase active when they opened.
- :class:`TraceSink` — bounded ring buffer of COMPLETED traces plus the
  phase-latency histograms (``kft_phase_seconds{phase=...}`` with
  exemplar trace ids).  Finalization (histogram observation + ring
  append) runs on the FINISHING caller's thread — the HTTP worker that
  delivered the response, never the engine scheduler's dispatch path:
  the scheduler only ever stamps timestamps into already-allocated
  structures.
- :class:`Tracer` — the sampling front door (``sample`` in [0, 1],
  ``ring`` completed traces retained).  An unsampled request carries
  ``trace=None`` end to end and every instrumentation site is guarded
  by that None check, so ``sample=0`` allocates nothing on the dispatch
  path (asserted by test).
- Context propagation: ``X-KFT-Trace: <trace_id>:<parent_span>:<flag>``
  over HTTP (router -> replica), the same triple as a ``trace`` dict
  riding the ``kv_migrate``/``reshard`` wire headers and the gang
  ``kv_import`` replay meta — one trace follows a request through the
  router door, affinity pick, replica door, engine queue, prefill
  chunks, a disaggregation handoff, decode/verify dispatches,
  preemption park/unpark, migration, resize freeze/cutover, and
  hibernate/thaw.
- :meth:`TraceSink.summary` — the host API the future autoscaler
  consumes (ROADMAP item 2): per-tenant-class queue-wait / stall-cause
  aggregates over a sliding window, computed from the ring on demand.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Any, Optional

#: HTTP propagation header: "<trace_id>:<parent_span_id>:<sampled>"
TRACE_HEADER = "X-KFT-Trace"

#: spans retained per trace — a pathological request (thousands of
#: decode dispatches) must not grow one sampled trace without bound;
#: the drop is counted on the trace, never silent
MAX_SPANS_PER_TRACE = 512

#: phase-latency histogram bucket upper bounds, seconds.  Wide on
#: purpose: the same buckets must resolve a 2 ms decode dispatch and a
#: 30 s queue wait (fixed buckets are the scrape contract — Prometheus
#: cannot aggregate dynamic ones across replicas).
PHASE_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

def _new_id() -> str:
    """Process-unique hex id (trace ids add a random component so two
    replicas can never mint the same id)."""
    return f"{random.getrandbits(48):012x}"


class Span:
    """One timed operation inside a trace."""

    __slots__ = ("name", "span_id", "parent_id", "start", "end", "attrs")

    def __init__(self, name: str, span_id: str, parent_id: Optional[str],
                 start: float, attrs: Optional[dict] = None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end: Optional[float] = None
        self.attrs = attrs

    def done(self, at: Optional[float] = None, **attrs) -> "Span":
        if self.end is None:  # first close wins; re-closing is a no-op
            self.end = time.perf_counter() if at is None else at
            if attrs:
                self.set(**attrs)
        return self

    def set(self, **attrs) -> "Span":
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)
        return self

    @property
    def duration_s(self) -> float:
        return (self.end if self.end is not None
                else time.perf_counter()) - self.start

    def to_dict(self) -> dict:
        d = {"name": self.name, "span_id": self.span_id,
             "parent_id": self.parent_id,
             "start_s": round(self.start, 6),
             "duration_s": round(self.duration_s, 6)}
        if self.attrs:
            # COPY: a disconnect can finish (and serialize) a trace
            # while the scheduler still stamps late attrs on the live
            # Span — the ring entry must be immutable once taken
            d["attrs"] = dict(self.attrs)
        return d


class Trace:
    """One request's span tree + contiguous phase track.

    Thread contract: appends are GIL-atomic list/attr writes and the
    phase track is only advanced by the thread that currently owns the
    request lifecycle (the same ownership handoffs the engine already
    serializes), so no lock is needed or taken on any hot path.
    """

    __slots__ = ("trace_id", "root", "spans", "phases", "_cur_phase",
                 "meta", "dropped_spans", "finished_at")

    def __init__(self, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None, name: str = "request",
                 **attrs):
        self.trace_id = trace_id or _new_id()
        self.root = Span(name, _new_id(), parent_id,
                         time.perf_counter(), attrs or None)
        #: detail spans (the root is spans[0]; phases live separately)
        self.spans: list[Span] = [self.root]
        #: the contiguous phase track: each entry closes when the next
        #: opens, so durations tile the root span
        self.phases: list[Span] = []
        self._cur_phase: Optional[Span] = None
        #: structured request-scoped facts (tenant, shed reason, model)
        self.meta: dict[str, Any] = {}
        self.dropped_spans = 0
        self.finished_at: Optional[float] = None

    # -- spans -------------------------------------------------------------

    def begin(self, name: str, parent: Optional[Span] = None,
              **attrs) -> Span:
        """Open a detail span (caller closes with ``.done()``); parent
        defaults to the phase active right now, else the root."""
        if len(self.spans) >= MAX_SPANS_PER_TRACE:
            self.dropped_spans += 1
            return _NULL_SPAN
        p = parent if parent is not None else (self._cur_phase or self.root)
        sp = Span(name, _new_id(), p.span_id, time.perf_counter(),
                  attrs or None)
        self.spans.append(sp)
        return sp

    def span(self, name: str, **attrs) -> "_SpanCtx":
        return _SpanCtx(self.begin(name, **attrs))

    # -- the phase track ---------------------------------------------------

    def phase(self, name: str, **attrs) -> Span:
        """Advance the phase track: close the current phase and open
        ``name`` at the SAME timestamp (contiguity is what makes phase
        durations sum to the end-to-end latency)."""
        now = time.perf_counter()
        cur = self._cur_phase
        if cur is not None:
            if cur.name == name:
                return cur  # already there (idempotent re-entry)
            cur.done(now)
        sp = Span(name, _new_id(), self.root.span_id, now, attrs or None)
        self.phases.append(sp)
        self._cur_phase = sp
        return sp

    def end_phase(self, **attrs) -> None:
        cur = self._cur_phase
        if cur is not None:
            cur.done(**attrs)
            self._cur_phase = None

    @property
    def current_phase(self) -> Optional[Span]:
        return self._cur_phase

    # -- lifecycle ---------------------------------------------------------

    def finish(self) -> "Trace":
        """Close the phase track and the root (idempotent)."""
        if self.finished_at is None:
            self.end_phase()
            self.root.done()
            self.finished_at = time.time()
        return self

    @property
    def duration_s(self) -> float:
        return self.root.duration_s

    def phase_totals(self) -> dict[str, float]:
        """Phase name -> summed seconds (a phase may recur: decode ->
        preempted -> decode)."""
        out: dict[str, float] = {}
        for p in self.phases:
            out[p.name] = out.get(p.name, 0.0) + p.duration_s
        return out

    # -- propagation -------------------------------------------------------

    def header(self) -> str:
        """Value for the ``X-KFT-Trace`` HTTP header."""
        return f"{self.trace_id}:{self.root.span_id}:1"

    def wire_context(self) -> dict:
        """JSON-able context for the kv_migrate/reshard wire headers and
        the gang replay meta."""
        return {"id": self.trace_id, "parent": self.root.span_id}

    # -- export ------------------------------------------------------------

    def to_dict(self) -> dict:
        d = {
            "trace_id": self.trace_id,
            "root": self.root.to_dict(),
            "duration_s": round(self.duration_s, 6),
            "phases": [p.to_dict() for p in self.phases],
            "spans": [s.to_dict() for s in self.spans[1:]],
            # copied like span attrs: the ring entry must not alias
            # dicts a still-live thread may stamp after finish
            "meta": dict(self.meta),
        }
        if self.finished_at is not None:
            d["finished_at"] = self.finished_at
        if self.dropped_spans:
            d["dropped_spans"] = self.dropped_spans
        return d


class _SpanCtx:
    __slots__ = ("span",)

    def __init__(self, span: Span):
        self.span = span

    def __enter__(self) -> Span:
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.span.set(error=f"{type(exc).__name__}: {exc}")
        self.span.done()


#: shared do-nothing span for over-budget begins: callers may .done()/
#: .set() it freely; it is never recorded
_NULL_SPAN = Span("dropped", "0", None, 0.0)


def parse_header(value: Optional[str]) -> Optional[tuple[str, str]]:
    """``X-KFT-Trace`` value -> (trace_id, parent_span_id), or None for
    absent/unsampled/malformed (malformed context starts a fresh
    decision, never an error — tracing must not fail requests)."""
    if not value:
        return None
    parts = str(value).split(":")
    if len(parts) != 3 or parts[2] != "1" or not parts[0] or not parts[1]:
        return None
    return parts[0], parts[1]


def parse_wire_context(ctx) -> Optional[tuple[str, str]]:
    """Wire-header ``trace`` dict -> (trace_id, parent_span_id)."""
    if not isinstance(ctx, dict):
        return None
    tid, parent = ctx.get("id"), ctx.get("parent")
    if not tid or not parent:
        return None
    return str(tid), str(parent)


class TraceSink:
    """Bounded ring of completed traces + the phase histograms.

    ``finish`` is the ONE finalization site: it closes the trace,
    observes every phase into the fixed-bucket histograms (keeping the
    slowest observation's trace id as the family's exemplar) and
    appends to the ring — O(phases) work on the finishing caller's
    thread.  ``observe_phase`` ingests engine-level phase durations
    that have no request trace (a host-tier spill, a resize stage)."""

    def __init__(self, ring: int = 256):
        from collections import deque

        self.ring = int(ring)
        if self.ring < 1:
            raise ValueError("ring must be >= 1")
        self._traces: "deque[dict]" = deque(maxlen=self.ring)
        self._mu = threading.Lock()
        #: phase -> [bucket counts..., +inf count]
        self._counts: dict[str, list[int]] = {}
        self._sums: dict[str, float] = {}
        #: phase -> (duration, trace_id): the exemplar is the slowest
        #: observation since the last scrape-side reset (never reset
        #: here — exemplars are hints, not counters)
        self._exemplar: dict[str, tuple[float, str]] = {}
        self.finished_total = 0

    # -- finalization ------------------------------------------------------

    def finish(self, trace: Optional[Trace]) -> None:
        if trace is None:
            return
        trace.finish()
        d = trace.to_dict()
        with self._mu:
            self.finished_total += 1
            self._traces.append(d)
            for p in trace.phases:
                self._observe_locked(p.name, p.duration_s, trace.trace_id)

    def observe_phase(self, phase: str, seconds: float,
                      trace_id: str = "") -> None:
        with self._mu:
            self._observe_locked(phase, float(seconds), trace_id)

    def _observe_locked(self, phase: str, seconds: float,
                        trace_id: str) -> None:
        counts = self._counts.get(phase)
        if counts is None:
            counts = self._counts[phase] = [0] * (len(PHASE_BUCKETS) + 1)
            self._sums[phase] = 0.0
        for i, b in enumerate(PHASE_BUCKETS):
            if seconds <= b:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
        self._sums[phase] += seconds
        if trace_id:
            best = self._exemplar.get(phase)
            if best is None or seconds > best[0]:
                self._exemplar[phase] = (seconds, trace_id)

    # -- read side ---------------------------------------------------------

    def traces(self) -> list[dict]:
        with self._mu:
            return list(self._traces)

    def slowest(self, n: int = 10) -> list[dict]:
        return sorted(self.traces(), key=lambda d: -d["duration_s"])[:n]

    def jsonl(self, slowest: Optional[int] = None) -> str:
        rows = self.slowest(slowest) if slowest else self.traces()
        return "".join(json.dumps(r) + "\n" for r in rows)

    def phase_metrics(self, name: str = "kft_phase_seconds",
                      base_labels: str = "",
                      exemplars: bool = False) -> list[str]:
        """Prometheus text lines for the phase histograms: one
        ``# TYPE <name> histogram`` header, then per-phase ``_bucket``
        (cumulative), ``_count`` and ``_sum`` — rendered through the
        ONE shared histogram renderer
        (:func:`~.traffic.prom_histogram_lines`).  Empty list when
        nothing was observed (no noise families on idle replicas).

        ``exemplars=True`` attaches the slowest observation's trace id
        to the +Inf bucket in OpenMetrics exemplar syntax.  Callers
        must pass it ONLY on a scrape that negotiated
        ``application/openmetrics-text`` (Accept header): the classic
        ``text/plain`` parser reads the trailer as a malformed
        timestamp and fails the whole page."""
        from .traffic import prom_histogram_lines, prom_label

        with self._mu:
            items = [(ph, list(c), self._sums[ph], self._exemplar.get(ph))
                     for ph, c in sorted(self._counts.items())]
        if not items:
            return []
        lines = [f"# TYPE {name} histogram"]
        for ph, counts, s, ex in items:
            lbl = f'{base_labels},' if base_labels else ""
            lines.extend(prom_histogram_lines(
                name, f'{lbl}phase="{prom_label(ph)}"',
                PHASE_BUCKETS, counts, s,
                exemplar=(ex if exemplars else None)))
        return lines

    def summary(self, window_s: float = 60.0) -> dict:
        """The autoscaler-facing aggregate (ROADMAP item 2): per-class
        phase latency sums/counts/max and stall-cause counts over the
        trailing ``window_s`` of COMPLETED traces.  ``queue_wait_s``
        isolates the two admission queues (router door + engine queue)
        because that — with the shed counts the traffic plane already
        exports — is the predictive-scaling input."""
        cutoff = time.time() - float(window_s)
        out: dict[str, Any] = {"window_s": float(window_s), "classes": {}}
        queue_phases = ("router.door", "replica.door", "engine.queue")
        for d in self.traces():
            if d.get("finished_at", 0.0) < cutoff:
                continue
            cls = str(d.get("meta", {}).get("class")
                      or d.get("meta", {}).get("tenant") or "default")
            c = out["classes"].setdefault(cls, {
                "traces": 0, "e2e_sum_s": 0.0, "e2e_max_s": 0.0,
                "queue_wait_sum_s": 0.0, "phases": {}, "stalls": {}})
            c["traces"] += 1
            c["e2e_sum_s"] += d["duration_s"]
            c["e2e_max_s"] = max(c["e2e_max_s"], d["duration_s"])
            for p in d.get("phases", ()):
                ph = c["phases"].setdefault(
                    p["name"], {"count": 0, "sum_s": 0.0, "max_s": 0.0})
                ph["count"] += 1
                ph["sum_s"] += p["duration_s"]
                ph["max_s"] = max(ph["max_s"], p["duration_s"])
                if p["name"] in queue_phases:
                    c["queue_wait_sum_s"] += p["duration_s"]
            stall = d.get("meta", {}).get("stall")
            if stall:
                c["stalls"][stall] = c["stalls"].get(stall, 0) + 1
        return out

    def stats(self) -> dict:
        with self._mu:
            return {"traces_finished_total": self.finished_total,
                    "traces_retained": len(self._traces)}


def parse_slowest(path: str):
    """``/traces[?slowest=N]`` query -> (ok, N or None).  Shared by
    the router and ModelServer handlers so the query contract cannot
    drift between the two surfaces."""
    from urllib.parse import parse_qs, urlsplit

    q = parse_qs(urlsplit(path).query)
    if not q.get("slowest"):
        return True, None
    try:
        return True, max(1, int(q["slowest"][0]))
    except ValueError:
        return False, None


def traces_body(sinks, slowest: Optional[int] = None) -> str:
    """Merged JSONL for one /traces response: rows from every sink,
    sorted/sliced ONCE across them when ``slowest`` is set (a
    multi-model server must answer N rows total, not N per model)."""
    rows: list[dict] = []
    for s in sinks:
        rows.extend(s.traces())
    if slowest is not None:
        rows = sorted(rows, key=lambda d: -d["duration_s"])[:slowest]
    return "".join(json.dumps(r) + "\n" for r in rows)


class Tracer:
    """Sampling front door + sink, one per serving surface (a model
    runtime, the router).  ``sample`` is the fraction of NEW requests
    traced; a propagated ``X-KFT-Trace`` context is always honored (the
    router already paid the sampling decision for the whole path)."""

    #: adopted-trace watch list bound — a replica that only ever
    #: imports (and whose scrape surfaces are never read) must not
    #: grow the list without limit; overflow finishes the oldest
    MAX_WATCHED = 512

    def __init__(self, sample: float = 0.0, ring: int = 256):
        self.sample = float(sample)
        if not (0.0 <= self.sample <= 1.0):
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        self.sink = TraceSink(ring=ring)
        self.started_total = 0
        self._rng = random.Random()
        #: (done_event, trace) pairs for ADOPTED traces (wire imports
        #: onto fresh handles): no door owns their finalization, so
        #: the read surfaces reap them lazily (finish-on-done happens
        #: on the scrape/read caller's thread — never the scheduler's)
        self._watched: list[tuple[Any, Trace]] = []
        self._watch_mu = threading.Lock()

    def start(self, header: Optional[str] = None, name: str = "request",
              **attrs) -> Optional[Trace]:
        """A new Trace (continued from ``header`` when one rode in,
        freshly sampled otherwise), or None when unsampled — the
        None flows through every instrumentation guard untouched."""
        ctx = parse_header(header)
        if ctx is not None:
            tr = Trace(trace_id=ctx[0], parent_id=ctx[1], name=name,
                       **attrs)
        elif self.sample > 0.0 and self._rng.random() < self.sample:
            tr = Trace(name=name, **attrs)
        else:
            return None
        self.started_total += 1
        return tr

    def adopt(self, ctx) -> Optional[Trace]:
        """Continue a trace from a WIRE context dict (kv_migrate /
        reshard header ``trace`` field) — always honored, like the
        HTTP header."""
        parsed = parse_wire_context(ctx)
        if parsed is None:
            return None
        self.started_total += 1
        return Trace(trace_id=parsed[0], parent_id=parsed[1])

    def watch(self, done_event, trace: Optional[Trace]) -> None:
        """Register an adopted trace for lazy finalization: no serving
        door owns a fresh-handle wire import's trace, so ``reap()``
        (called by the read surfaces) finishes it once the request's
        done event is set — without this, cross-replica continued
        traces never reach the ring or the phase histograms."""
        if trace is None:
            return
        overflow: list[Trace] = []
        with self._watch_mu:
            self._watched.append((done_event, trace))
            while len(self._watched) > self.MAX_WATCHED:
                overflow.append(self._watched.pop(0)[1])
        for tr in overflow:  # finish outside the lock
            self.sink.finish(tr)

    def reap(self) -> int:
        """Finish every watched trace whose request completed; returns
        how many finalized.  Runs on the CALLER's thread (a /traces or
        /metrics scrape, a stats read) — the lazy half of the
        finalization-off-the-scheduler contract."""
        ready: list[Trace] = []
        with self._watch_mu:
            kept: list[tuple[Any, Trace]] = []
            for done, tr in self._watched:
                if done.is_set():
                    ready.append(tr)
                else:
                    kept.append((done, tr))
            self._watched = kept
        for tr in ready:
            self.sink.finish(tr)
        return len(ready)

    def finish(self, trace: Optional[Trace]) -> None:
        self.sink.finish(trace)

    def stats(self) -> dict:
        self.reap()  # scrape-driven finalization of adopted traces
        return {"traces_started_total": self.started_total,
                "trace_sample_rate": self.sample,
                **self.sink.stats()}


def validate_tracing(spec) -> dict:
    """``{"sample": f, "ring": n}`` -> normalized kwargs; raises
    ``ValueError`` naming the offending field.  The ONE validation
    site: conf-freeze (the ISvc controller) and runtime construction
    (text.py, the router) must reject identically."""
    if not isinstance(spec, dict):
        raise ValueError(
            f"tracing must be a mapping {{sample, ring}}, got "
            f"{type(spec).__name__}")
    unknown = set(spec) - {"sample", "ring"}
    if unknown:
        raise ValueError(
            f"tracing keys {sorted(unknown)} unknown "
            "(allowed: ['ring', 'sample'])")
    try:
        sample = float(spec.get("sample", 0.1))
    except (TypeError, ValueError) as e:
        raise ValueError(f"tracing.sample: {e}") from e
    if not (0.0 <= sample <= 1.0):
        raise ValueError(
            f"tracing.sample {sample} must be in [0, 1]")
    try:
        ring = int(spec.get("ring", 256))
    except (TypeError, ValueError) as e:
        raise ValueError(f"tracing.ring: {e}") from e
    if ring < 1:
        raise ValueError(f"tracing.ring {ring} must be >= 1")
    return {"sample": sample, "ring": ring}
