"""Storage initializer: resolve a storage URI to a local model directory.

[upstream: kserve/kserve -> pkg/agent/storage + python/kserve/kserve/storage]
— the init container that downloads ``gs://``/``s3://``/``pvc://`` into
``/mnt/models`` before the server starts.  Here a library call with the same
contract: ``download(uri) -> local path``.

Schemes:
  file:///abs/path   local directory/file (the PVC analog)
  mem://<key>        in-process registry (tests, zero-copy handoff)
  hf://org/name[@rev] LOCAL HuggingFace-hub-layout snapshots resolved
                     from $KFT_HF_HOME with revision pinning (resolve_hf)
  gs:// s3://        PLUGGABLE TRANSPORT (r4): resolved through a
                     registered transport (register_transport) that
                     fetches into a staging dir, then published through
                     the manifest-verified cache — the same pattern that
                     made hf:// coverable without egress.  With
                     KFT_REMOTE_TOOLS=1 the builtin transports shell out
                     to gsutil / aws-cli (egress-enabled deployments);
                     otherwise, with no registered transport, the scheme
                     raises the explicit zero-egress error instead of
                     letting a cloud CLI retry against a blackhole.

Cache tier (the kserve agent's local-model-cache capability): pass
``cache_dir`` (or set ``KFT_MODEL_CACHE``) and ``download`` stages the
source into a content-addressed entry with a ``manifest.json`` recording
every file's size + sha256.  Subsequent downloads of the same URI verify
the manifest instead of re-copying; a corrupted entry is re-staged.  New
replicas on the same host then share one staged copy of the weights.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import uuid
from typing import Any, Optional

_MEM_REGISTRY: dict[str, Any] = {}

MANIFEST_NAME = "manifest.json"


class StorageError(RuntimeError):
    pass


def register_mem(key: str, value: Any) -> str:
    """Publish an object under ``mem://<key>`` (test/bench convenience)."""
    _MEM_REGISTRY[key] = value
    return f"mem://{key}"


def fetch_mem(key: str) -> Any:
    try:
        return _MEM_REGISTRY[key]
    except KeyError:
        raise StorageError(f"mem://{key} not registered") from None


#: scheme ("gs://", "s3://", ...) -> transport(uri, dest_dir) that fetches
#: the object(s) at uri INTO dest_dir.  Injectable for tests and for
#: deployments with egress; download() stages the result through the
#: manifest cache so replicas share one verified copy.
_TRANSPORTS: dict[str, Any] = {}


def register_transport(scheme: str, fn) -> None:
    """Install (or override) the transport for a remote scheme.  Pass
    ``None`` to remove."""
    if fn is None:
        _TRANSPORTS.pop(scheme, None)
    else:
        _TRANSPORTS[scheme] = fn


def _tool_transport(tool_argv_prefix: list[str]):
    """Transport that shells out to a cloud CLI (gsutil / aws s3) when the
    binary exists on PATH — the reference's storage-initializer behavior
    [upstream: kserve pkg/agent/storage].  Returns None when absent so the
    caller falls through to the explicit zero-egress error."""
    import shutil as _shutil
    import subprocess

    if _shutil.which(tool_argv_prefix[0]) is None:
        return None

    def fetch(uri: str, dest_dir: str) -> None:
        proc = subprocess.run(
            [*tool_argv_prefix, uri, dest_dir],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise StorageError(
                f"{uri}: transfer failed: {proc.stderr.strip()[:500]}")

    return fetch


def _remote_transport_for(uri: str):
    scheme = uri.split("://", 1)[0] + "://"
    t = _TRANSPORTS.get(scheme)
    if t is not None:
        return t
    # CLI-tool fallbacks require an EXPLICIT opt-in: a gsutil/aws binary
    # may exist on a zero-egress host, where it retries against the
    # blackhole for minutes instead of failing fast — the hang the
    # scheme gating exists to prevent.  Deployments with real egress set
    # KFT_REMOTE_TOOLS=1 (or register a transport).
    if os.environ.get("KFT_REMOTE_TOOLS") != "1":
        return None
    if scheme == "gs://":
        return _tool_transport(["gsutil", "-m", "cp", "-r"])
    if scheme == "s3://":
        return _s3_tool_transport()
    return None


def _s3_tool_transport():
    """aws-cli transport.  `aws s3 cp --recursive` treats a single-object
    key as an (empty) prefix, so try the plain object copy first and fall
    back to recursive for prefix trees."""
    import shutil as _shutil
    import subprocess

    if _shutil.which("aws") is None:
        return None

    def fetch(uri: str, dest_dir: str) -> None:
        single = subprocess.run(
            ["aws", "s3", "cp", uri, dest_dir + "/"],
            capture_output=True, text=True)
        if single.returncode == 0 and os.listdir(dest_dir):
            return
        tree = subprocess.run(
            ["aws", "s3", "cp", "--recursive", uri, dest_dir],
            capture_output=True, text=True)
        if tree.returncode != 0:
            raise StorageError(
                f"{uri}: transfer failed: "
                f"{(tree.stderr or single.stderr).strip()[:500]}")

    return fetch


def _download_remote(uri: str, cache_dir: Optional[str]) -> str:
    """Fetch via the scheme's transport into a temp dir, then publish
    through the manifest cache (atomic, shared across replicas)."""
    import tempfile

    transport = _remote_transport_for(uri)
    if transport is None:
        raise StorageError(
            f"{uri}: remote storage requires network egress, which this "
            "deployment does not have; stage the model locally and use "
            "file:// (or register_transport() in an egress-enabled "
            "deployment)")
    cache_dir = cache_dir or os.path.join(
        tempfile.gettempdir(), "kft-remote-cache")
    # cache hit: a previously-staged, manifest-valid entry skips the
    # transport entirely (the kserve local-model-cache economy)
    key = hashlib.sha256(uri.encode()).hexdigest()[:16]
    entry_dir = os.path.join(cache_dir, key)
    if os.path.exists(os.path.join(entry_dir, MANIFEST_NAME)) and (
            verify_manifest(entry_dir)):
        _verified_entries.add(entry_dir)
        return os.path.join(entry_dir, "model")
    with tempfile.TemporaryDirectory(prefix="kft-fetch-") as tmp:
        dest = os.path.join(tmp, "payload")
        os.makedirs(dest, exist_ok=True)
        transport(uri, dest)
        if not os.listdir(dest):
            raise StorageError(f"{uri}: transport produced no files")
        # always stage the payload DIRECTORY: remote downloads resolve to
        # a model directory (single-file objects become a one-file dir),
        # which keeps the cache-hit path above unambiguous
        return stage_to_cache(uri, dest, cache_dir)


def download(
    uri: str, cache_dir: Optional[str] = None, hf_root: Optional[str] = None
) -> str:
    """Resolve ``uri`` to a local filesystem path (V1 storage contract).

    With ``cache_dir`` (or ``$KFT_MODEL_CACHE``), file sources are staged
    through the manifest-verified local cache and the cached path is
    returned instead of the source path.
    """
    cache_dir = cache_dir or os.environ.get("KFT_MODEL_CACHE")
    if uri.startswith("file://"):
        path = uri[len("file://"):]
        if not os.path.exists(path):
            raise StorageError(f"{uri}: no such path")
        if cache_dir:
            return stage_to_cache(uri, path, cache_dir)
        return path
    if uri.startswith("mem://"):
        # mem objects have no path; callers use fetch_mem directly
        key = uri[len("mem://"):]
        if key not in _MEM_REGISTRY:
            raise StorageError(f"{uri} not registered")
        return uri
    if uri.startswith("hf://"):
        path = resolve_hf(uri, hf_root=hf_root)
        if cache_dir:
            return stage_to_cache(uri, path, cache_dir)
        return path
    if uri.startswith(("gs://", "s3://")):
        return _download_remote(uri, cache_dir)
    for scheme in ("http://", "https://"):
        if uri.startswith(scheme):
            raise StorageError(
                f"{uri}: remote storage requires network egress, which this "
                "deployment does not have; stage the model locally and use file://"
            )
    raise StorageError(f"unsupported storage uri {uri!r}")


def resolve_hf(uri: str, hf_root: Optional[str] = None) -> str:
    """Resolve ``hf://org/name[@revision]`` against a LOCAL HuggingFace-hub
    layout snapshot root [upstream: kserve -> python/kserve storage hf://
    scheme; the reference downloads from the Hub — this deployment has
    zero egress, so the contract is covered by hub-layout directories
    staged locally (``$KFT_HF_HOME``, e.g. an exported HF_HOME/hub)]:

        <root>/models--org--name/
            refs/<revision>            # text file naming a commit
            snapshots/<commit>/...     # config.json + weights

    ``revision`` defaults to ``main``; it may be a named ref, a full
    commit, or a unique commit prefix — pinning a revision serves exactly
    that snapshot forever, the property the reference gets from commit-
    hash URLs.
    """
    hf_root = hf_root or os.environ.get("KFT_HF_HOME")
    if not hf_root:
        raise StorageError(
            f"{uri}: hf:// resolves against a local HuggingFace-hub layout "
            "(zero-egress deployment); set KFT_HF_HOME or pass hf_root")
    ref = uri[len("hf://"):]
    repo, _, revision = ref.partition("@")
    revision = revision or "main"
    repo = repo.strip("/")
    if repo.count("/") != 1:
        raise StorageError(f"{uri}: expected hf://<org>/<name>[@revision]")
    repo_dir = os.path.join(hf_root, "models--" + repo.replace("/", "--"))
    if not os.path.isdir(repo_dir):
        raise StorageError(f"{uri}: {repo!r} not present under {hf_root}")
    snapshots = os.path.join(repo_dir, "snapshots")
    commit: Optional[str] = None
    ref_file = os.path.join(repo_dir, "refs", revision)
    if os.path.isfile(ref_file):
        with open(ref_file) as f:
            commit = f.read().strip()
    else:
        try:
            known = sorted(os.listdir(snapshots))
        except OSError:
            known = []
        matches = [c for c in known if c.startswith(revision)]
        if len(matches) == 1:
            commit = matches[0]
        elif len(matches) > 1:
            raise StorageError(
                f"{uri}: revision {revision!r} is ambiguous ({matches})")
    if not commit:
        raise StorageError(f"{uri}: unknown revision {revision!r}")
    snap = os.path.join(snapshots, commit)
    if not os.path.isdir(snap):
        raise StorageError(
            f"{uri}: ref {revision!r} names missing snapshot {commit!r}")
    return snap


# ---------------------------------------------------------------------------
# Local model cache with manifests
# ---------------------------------------------------------------------------


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _walk_files(root: str) -> list[str]:
    """Relative paths of every regular file under root (root may be a file)."""
    if os.path.isfile(root):
        return [""]
    out = []
    for dirpath, _, names in os.walk(root):
        for n in names:
            out.append(os.path.relpath(os.path.join(dirpath, n), root))
    return sorted(out)


def build_manifest(uri: str, root: str) -> dict:
    files = []
    for rel in _walk_files(root):
        p = root if rel == "" else os.path.join(root, rel)
        st = os.stat(p)
        files.append({
            "path": rel or os.path.basename(root),
            "size": st.st_size,
            "mtime_ns": st.st_mtime_ns,
            "sha256": _sha256_file(p),
        })
    return {"uri": uri, "created": time.time(), "files": files}


def verify_manifest(entry_dir: str) -> bool:
    """True when every file named by the entry's manifest matches on size
    and sha256 (the cache-hit validity check)."""
    mpath = os.path.join(entry_dir, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    model_root = os.path.join(entry_dir, "model")
    for rec in manifest.get("files", []):
        p = os.path.join(model_root, rec["path"])
        try:
            if os.path.getsize(p) != rec["size"]:
                return False
            if _sha256_file(p) != rec["sha256"]:
                return False
        except OSError:
            return False
    return True


#: entry dirs fully hash-verified once by this process; later hits only
#: size-check, so warm-path cost is O(files), not O(bytes)
_verified_entries: set[str] = set()


def _sizes_ok(entry_dir: str) -> bool:
    """Cheap validity check: size + mtime match the manifest (catches
    rewrites without re-reading the bytes)."""
    mpath = os.path.join(entry_dir, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        model_root = os.path.join(entry_dir, "model")
        for rec in manifest.get("files", []):
            st = os.stat(os.path.join(model_root, rec["path"]))
            if st.st_size != rec["size"]:
                return False
            if "mtime_ns" in rec and st.st_mtime_ns != rec["mtime_ns"]:
                return False
        return True
    except (OSError, json.JSONDecodeError, KeyError):
        return False


def stage_to_cache(uri: str, src_path: str, cache_dir: str) -> str:
    """Stage ``src_path`` into the cache under a URI-keyed entry; return the
    staged model path.  A valid existing entry is reused without copying;
    an invalid one (interrupted copy, corruption) is re-staged."""
    key = hashlib.sha256(uri.encode()).hexdigest()[:16]
    entry_dir = os.path.join(cache_dir, key)
    model_root = os.path.join(entry_dir, "model")

    def staged_path() -> str:
        if os.path.isdir(src_path):
            return model_root
        return os.path.join(model_root, os.path.basename(src_path))

    if os.path.exists(os.path.join(entry_dir, MANIFEST_NAME)):
        if entry_dir in _verified_entries:
            # full-hash verified once this process; cheap size check after
            if _sizes_ok(entry_dir):
                return staged_path()
            _verified_entries.discard(entry_dir)
        if verify_manifest(entry_dir):
            _verified_entries.add(entry_dir)
            return staged_path()
        shutil.rmtree(entry_dir, ignore_errors=True)

    # hidden staging name: list_cache skips dot-entries; unique per attempt
    # so concurrent stagers (other processes OR other threads here) never
    # collide.  Only *stale* leftovers (dead stagers) are garbage-collected.
    tmp_dir = os.path.join(
        cache_dir, f".staging-{key}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
    for leftover in _stale_staging_dirs(cache_dir, key):
        shutil.rmtree(leftover, ignore_errors=True)
    tmp_model = os.path.join(tmp_dir, "model")
    if os.path.isdir(src_path):
        shutil.copytree(src_path, tmp_model)
    else:
        os.makedirs(tmp_model, exist_ok=True)
        shutil.copy2(src_path, os.path.join(tmp_model, os.path.basename(src_path)))
    # manifest is built from the STAGED copy so manifest and bytes agree by
    # construction even if the source mutates mid-copy
    manifest = build_manifest(uri, tmp_model)
    with open(os.path.join(tmp_dir, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f)
    # rename() publishes the entry atomically; never remove a published
    # entry here — a concurrent replica may already be serving from it
    try:
        os.rename(tmp_dir, entry_dir)
        _verified_entries.add(entry_dir)
    except OSError:
        # lost the publish race to a concurrent replica; use the winner's
        shutil.rmtree(tmp_dir, ignore_errors=True)
        if not verify_manifest(entry_dir):
            raise StorageError(f"cache entry for {uri} is invalid after race")
        _verified_entries.add(entry_dir)
    return staged_path()


#: a staging dir untouched this long is presumed orphaned by a dead stager
STAGING_STALE_SECONDS = 3600.0


def _stale_staging_dirs(cache_dir: str, key: str) -> list[str]:
    """Staging dirs for ``key`` old enough to be crash leftovers — live
    concurrent stagers are younger than this and must not be deleted."""
    out = []
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return out
    prefix = f".staging-{key}-"
    now = time.time()
    for n in names:
        if not n.startswith(prefix):
            continue
        p = os.path.join(cache_dir, n)
        try:
            if now - os.path.getmtime(p) > STAGING_STALE_SECONDS:
                out.append(p)
        except OSError:
            continue
    return out


def list_cache(cache_dir: str) -> list[dict]:
    """Manifests of every cache entry (the repository-listing surface)."""
    out = []
    try:
        entries = sorted(os.listdir(cache_dir))
    except OSError:
        return out
    for name in entries:
        if name.startswith("."):  # in-flight/orphaned staging dirs
            continue
        mpath = os.path.join(cache_dir, name, MANIFEST_NAME)
        try:
            with open(mpath) as f:
                m = json.load(f)
            m["entry"] = name
            m["valid"] = verify_manifest(os.path.join(cache_dir, name))
            out.append(m)
        except (OSError, json.JSONDecodeError):
            continue
    return out
