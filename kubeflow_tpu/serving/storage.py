"""Storage initializer: resolve a storage URI to a local model directory.

[upstream: kserve/kserve -> pkg/agent/storage + python/kserve/kserve/storage]
— the init container that downloads ``gs://``/``s3://``/``pvc://`` into
``/mnt/models`` before the server starts.  Here a library call with the same
contract: ``download(uri) -> local path``.

Schemes:
  file:///abs/path   local directory/file (the PVC analog)
  mem://<key>        in-process registry (tests, zero-copy handoff)
  hf://org/name[@rev] LOCAL HuggingFace-hub-layout snapshots resolved
                     from $KFT_HF_HOME with revision pinning (resolve_hf)
  gs:// s3://        PLUGGABLE TRANSPORT (r4): resolved through a
                     registered transport (register_transport) that
                     fetches into a staging dir, then published through
                     the manifest-verified cache — the same pattern that
                     made hf:// coverable without egress.  With
                     KFT_REMOTE_TOOLS=1 the builtin transports shell out
                     to gsutil / aws-cli (egress-enabled deployments);
                     otherwise, with no registered transport, the scheme
                     raises the explicit zero-egress error instead of
                     letting a cloud CLI retry against a blackhole.

Cache tier (the kserve agent's local-model-cache capability): pass
``cache_dir`` (or set ``KFT_MODEL_CACHE``) and ``download`` stages the
source into a content-addressed entry with a ``manifest.json`` recording
every file's size + sha256.  Subsequent downloads of the same URI verify
the manifest instead of re-copying; a corrupted entry is re-staged.  New
replicas on the same host then share one staged copy of the weights.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import uuid
from typing import Any, Optional

_MEM_REGISTRY: dict[str, Any] = {}

MANIFEST_NAME = "manifest.json"


class StorageError(RuntimeError):
    pass


class SpillCorrupt(StorageError):
    """A spill entry's MANIFEST is unreadable or self-inconsistent: the
    session cannot be reconstructed from this tier (payload corruption
    is softer — the manifest's token record still re-prefills)."""


def register_mem(key: str, value: Any) -> str:
    """Publish an object under ``mem://<key>`` (test/bench convenience)."""
    _MEM_REGISTRY[key] = value
    return f"mem://{key}"


def fetch_mem(key: str) -> Any:
    try:
        return _MEM_REGISTRY[key]
    except KeyError:
        raise StorageError(f"mem://{key} not registered") from None


#: scheme ("gs://", "s3://", ...) -> transport(uri, dest_dir) that fetches
#: the object(s) at uri INTO dest_dir.  Injectable for tests and for
#: deployments with egress; download() stages the result through the
#: manifest cache so replicas share one verified copy.
_TRANSPORTS: dict[str, Any] = {}


def register_transport(scheme: str, fn) -> None:
    """Install (or override) the transport for a remote scheme.  Pass
    ``None`` to remove."""
    if fn is None:
        _TRANSPORTS.pop(scheme, None)
    else:
        _TRANSPORTS[scheme] = fn


def _tool_transport(tool_argv_prefix: list[str]):
    """Transport that shells out to a cloud CLI (gsutil / aws s3) when the
    binary exists on PATH — the reference's storage-initializer behavior
    [upstream: kserve pkg/agent/storage].  Returns None when absent so the
    caller falls through to the explicit zero-egress error."""
    import shutil as _shutil
    import subprocess

    if _shutil.which(tool_argv_prefix[0]) is None:
        return None

    def fetch(uri: str, dest_dir: str) -> None:
        proc = subprocess.run(
            [*tool_argv_prefix, uri, dest_dir],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise StorageError(
                f"{uri}: transfer failed: {proc.stderr.strip()[:500]}")

    return fetch


def _remote_transport_for(uri: str):
    scheme = uri.split("://", 1)[0] + "://"
    t = _TRANSPORTS.get(scheme)
    if t is not None:
        return t
    # CLI-tool fallbacks require an EXPLICIT opt-in: a gsutil/aws binary
    # may exist on a zero-egress host, where it retries against the
    # blackhole for minutes instead of failing fast — the hang the
    # scheme gating exists to prevent.  Deployments with real egress set
    # KFT_REMOTE_TOOLS=1 (or register a transport).
    if os.environ.get("KFT_REMOTE_TOOLS") != "1":
        return None
    if scheme == "gs://":
        return _tool_transport(["gsutil", "-m", "cp", "-r"])
    if scheme == "s3://":
        return _s3_tool_transport()
    return None


def _s3_tool_transport():
    """aws-cli transport.  `aws s3 cp --recursive` treats a single-object
    key as an (empty) prefix, so try the plain object copy first and fall
    back to recursive for prefix trees."""
    import shutil as _shutil
    import subprocess

    if _shutil.which("aws") is None:
        return None

    def fetch(uri: str, dest_dir: str) -> None:
        single = subprocess.run(
            ["aws", "s3", "cp", uri, dest_dir + "/"],
            capture_output=True, text=True)
        if single.returncode == 0 and os.listdir(dest_dir):
            return
        tree = subprocess.run(
            ["aws", "s3", "cp", "--recursive", uri, dest_dir],
            capture_output=True, text=True)
        if tree.returncode != 0:
            raise StorageError(
                f"{uri}: transfer failed: "
                f"{(tree.stderr or single.stderr).strip()[:500]}")

    return fetch


def _download_remote(uri: str, cache_dir: Optional[str]) -> str:
    """Fetch via the scheme's transport into a temp dir, then publish
    through the manifest cache (atomic, shared across replicas)."""
    import tempfile

    transport = _remote_transport_for(uri)
    if transport is None:
        raise StorageError(
            f"{uri}: remote storage requires network egress, which this "
            "deployment does not have; stage the model locally and use "
            "file:// (or register_transport() in an egress-enabled "
            "deployment)")
    cache_dir = cache_dir or os.path.join(
        tempfile.gettempdir(), "kft-remote-cache")
    # cache hit: a previously-staged, manifest-valid entry skips the
    # transport entirely (the kserve local-model-cache economy)
    key = hashlib.sha256(uri.encode()).hexdigest()[:16]
    entry_dir = os.path.join(cache_dir, key)
    if os.path.exists(os.path.join(entry_dir, MANIFEST_NAME)) and (
            verify_manifest(entry_dir)):
        _verified_entries.add(entry_dir)
        return os.path.join(entry_dir, "model")
    with tempfile.TemporaryDirectory(prefix="kft-fetch-") as tmp:
        dest = os.path.join(tmp, "payload")
        os.makedirs(dest, exist_ok=True)
        transport(uri, dest)
        if not os.listdir(dest):
            raise StorageError(f"{uri}: transport produced no files")
        # always stage the payload DIRECTORY: remote downloads resolve to
        # a model directory (single-file objects become a one-file dir),
        # which keeps the cache-hit path above unambiguous
        return stage_to_cache(uri, dest, cache_dir)


def download(
    uri: str, cache_dir: Optional[str] = None, hf_root: Optional[str] = None
) -> str:
    """Resolve ``uri`` to a local filesystem path (V1 storage contract).

    With ``cache_dir`` (or ``$KFT_MODEL_CACHE``), file sources are staged
    through the manifest-verified local cache and the cached path is
    returned instead of the source path.
    """
    cache_dir = cache_dir or os.environ.get("KFT_MODEL_CACHE")
    if uri.startswith("file://"):
        path = uri[len("file://"):]
        if not os.path.exists(path):
            raise StorageError(f"{uri}: no such path")
        if cache_dir:
            return stage_to_cache(uri, path, cache_dir)
        return path
    if uri.startswith("mem://"):
        # mem objects have no path; callers use fetch_mem directly
        key = uri[len("mem://"):]
        if key not in _MEM_REGISTRY:
            raise StorageError(f"{uri} not registered")
        return uri
    if uri.startswith("hf://"):
        path = resolve_hf(uri, hf_root=hf_root)
        if cache_dir:
            return stage_to_cache(uri, path, cache_dir)
        return path
    if uri.startswith(("gs://", "s3://")):
        return _download_remote(uri, cache_dir)
    for scheme in ("http://", "https://"):
        if uri.startswith(scheme):
            raise StorageError(
                f"{uri}: remote storage requires network egress, which this "
                "deployment does not have; stage the model locally and use file://"
            )
    raise StorageError(f"unsupported storage uri {uri!r}")


def resolve_hf(uri: str, hf_root: Optional[str] = None) -> str:
    """Resolve ``hf://org/name[@revision]`` against a LOCAL HuggingFace-hub
    layout snapshot root [upstream: kserve -> python/kserve storage hf://
    scheme; the reference downloads from the Hub — this deployment has
    zero egress, so the contract is covered by hub-layout directories
    staged locally (``$KFT_HF_HOME``, e.g. an exported HF_HOME/hub)]:

        <root>/models--org--name/
            refs/<revision>            # text file naming a commit
            snapshots/<commit>/...     # config.json + weights

    ``revision`` defaults to ``main``; it may be a named ref, a full
    commit, or a unique commit prefix — pinning a revision serves exactly
    that snapshot forever, the property the reference gets from commit-
    hash URLs.
    """
    hf_root = hf_root or os.environ.get("KFT_HF_HOME")
    if not hf_root:
        raise StorageError(
            f"{uri}: hf:// resolves against a local HuggingFace-hub layout "
            "(zero-egress deployment); set KFT_HF_HOME or pass hf_root")
    ref = uri[len("hf://"):]
    repo, _, revision = ref.partition("@")
    revision = revision or "main"
    repo = repo.strip("/")
    if repo.count("/") != 1:
        raise StorageError(f"{uri}: expected hf://<org>/<name>[@revision]")
    repo_dir = os.path.join(hf_root, "models--" + repo.replace("/", "--"))
    if not os.path.isdir(repo_dir):
        raise StorageError(f"{uri}: {repo!r} not present under {hf_root}")
    snapshots = os.path.join(repo_dir, "snapshots")
    commit: Optional[str] = None
    ref_file = os.path.join(repo_dir, "refs", revision)
    if os.path.isfile(ref_file):
        with open(ref_file) as f:
            commit = f.read().strip()
    else:
        try:
            known = sorted(os.listdir(snapshots))
        except OSError:
            known = []
        matches = [c for c in known if c.startswith(revision)]
        if len(matches) == 1:
            commit = matches[0]
        elif len(matches) > 1:
            raise StorageError(
                f"{uri}: revision {revision!r} is ambiguous ({matches})")
    if not commit:
        raise StorageError(f"{uri}: unknown revision {revision!r}")
    snap = os.path.join(snapshots, commit)
    if not os.path.isdir(snap):
        raise StorageError(
            f"{uri}: ref {revision!r} names missing snapshot {commit!r}")
    return snap


# ---------------------------------------------------------------------------
# Local model cache with manifests
# ---------------------------------------------------------------------------


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _walk_files(root: str) -> list[str]:
    """Relative paths of every regular file under root (root may be a file)."""
    if os.path.isfile(root):
        return [""]
    out = []
    for dirpath, _, names in os.walk(root):
        for n in names:
            out.append(os.path.relpath(os.path.join(dirpath, n), root))
    return sorted(out)


def build_manifest(uri: str, root: str) -> dict:
    files = []
    for rel in _walk_files(root):
        p = root if rel == "" else os.path.join(root, rel)
        st = os.stat(p)
        files.append({
            "path": rel or os.path.basename(root),
            "size": st.st_size,
            "mtime_ns": st.st_mtime_ns,
            "sha256": _sha256_file(p),
        })
    return {"uri": uri, "created": time.time(), "files": files}


def verify_manifest(entry_dir: str) -> bool:
    """True when every file named by the entry's manifest matches on size
    and sha256 (the cache-hit validity check)."""
    mpath = os.path.join(entry_dir, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    model_root = os.path.join(entry_dir, "model")
    for rec in manifest.get("files", []):
        p = os.path.join(model_root, rec["path"])
        try:
            if os.path.getsize(p) != rec["size"]:
                return False
            if _sha256_file(p) != rec["sha256"]:
                return False
        except OSError:
            return False
    return True


#: entry dirs fully hash-verified once by this process; later hits only
#: size-check, so warm-path cost is O(files), not O(bytes)
_verified_entries: set[str] = set()


def _sizes_ok(entry_dir: str) -> bool:
    """Cheap validity check: size + mtime match the manifest (catches
    rewrites without re-reading the bytes)."""
    mpath = os.path.join(entry_dir, MANIFEST_NAME)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        model_root = os.path.join(entry_dir, "model")
        for rec in manifest.get("files", []):
            st = os.stat(os.path.join(model_root, rec["path"]))
            if st.st_size != rec["size"]:
                return False
            if "mtime_ns" in rec and st.st_mtime_ns != rec["mtime_ns"]:
                return False
        return True
    except (OSError, json.JSONDecodeError, KeyError):
        return False


def stage_to_cache(uri: str, src_path: str, cache_dir: str) -> str:
    """Stage ``src_path`` into the cache under a URI-keyed entry; return the
    staged model path.  A valid existing entry is reused without copying;
    an invalid one (interrupted copy, corruption) is re-staged."""
    key = hashlib.sha256(uri.encode()).hexdigest()[:16]
    entry_dir = os.path.join(cache_dir, key)
    model_root = os.path.join(entry_dir, "model")

    def staged_path() -> str:
        if os.path.isdir(src_path):
            return model_root
        return os.path.join(model_root, os.path.basename(src_path))

    if os.path.exists(os.path.join(entry_dir, MANIFEST_NAME)):
        if entry_dir in _verified_entries:
            # full-hash verified once this process; cheap size check after
            if _sizes_ok(entry_dir):
                return staged_path()
            _verified_entries.discard(entry_dir)
        if verify_manifest(entry_dir):
            _verified_entries.add(entry_dir)
            return staged_path()
        shutil.rmtree(entry_dir, ignore_errors=True)

    # hidden staging name: list_cache skips dot-entries; unique per attempt
    # so concurrent stagers (other processes OR other threads here) never
    # collide.  Only *stale* leftovers (dead stagers) are garbage-collected.
    tmp_dir = os.path.join(
        cache_dir, f".staging-{key}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
    for leftover in _stale_staging_dirs(cache_dir, key):
        shutil.rmtree(leftover, ignore_errors=True)
    tmp_model = os.path.join(tmp_dir, "model")
    if os.path.isdir(src_path):
        shutil.copytree(src_path, tmp_model)
    else:
        os.makedirs(tmp_model, exist_ok=True)
        shutil.copy2(src_path, os.path.join(tmp_model, os.path.basename(src_path)))
    # manifest is built from the STAGED copy so manifest and bytes agree by
    # construction even if the source mutates mid-copy
    manifest = build_manifest(uri, tmp_model)
    with open(os.path.join(tmp_dir, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(tmp_dir)
    # rename() publishes the entry atomically; never remove a published
    # entry here — a concurrent replica may already be serving from it
    try:
        os.rename(tmp_dir, entry_dir)
        _fsync_dir(cache_dir)
        _verified_entries.add(entry_dir)
    except OSError:
        # lost the publish race to a concurrent replica; use the winner's
        shutil.rmtree(tmp_dir, ignore_errors=True)
        if not verify_manifest(entry_dir):
            raise StorageError(f"cache entry for {uri} is invalid after race")
        _verified_entries.add(entry_dir)
    return staged_path()


#: a staging dir untouched this long is presumed orphaned by a dead stager
STAGING_STALE_SECONDS = 3600.0


def _stale_staging_dirs(cache_dir: str, key: str) -> list[str]:
    """Staging dirs for ``key`` old enough to be crash leftovers — live
    concurrent stagers are younger than this and must not be deleted."""
    out = []
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return out
    prefix = f".staging-{key}-"
    now = time.time()
    for n in names:
        if not n.startswith(prefix):
            continue
        p = os.path.join(cache_dir, n)
        try:
            if now - os.path.getmtime(p) > STAGING_STALE_SECONDS:
                out.append(p)
        except OSError:
            continue
    return out


# ---------------------------------------------------------------------------
# KV spill store: the storage tier of the paged-KV economy (ISSUE 12)
# ---------------------------------------------------------------------------


SPILL_MANIFEST = "spill.json"


def _np_spill_dtype(name: str):
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bf16/f8 dtype names register through ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _pack_spill_leaves(leaves) -> bytes:
    import numpy as np

    # analysis: ok host-sync-in-dispatch — snapshot leaves are host numpy (spill worker)
    return b"".join(np.ascontiguousarray(np.asarray(x)).tobytes()
                    for x in leaves)


def _unpack_spill_leaves(payload: bytes, specs: list) -> list:
    import numpy as np

    out, off = [], 0
    for s in specs:
        dt = _np_spill_dtype(s["dtype"])
        n = int(np.prod(s["shape"], dtype=np.int64)) * dt.itemsize
        out.append(np.frombuffer(
            payload[off:off + n], dtype=dt).reshape(s["shape"]).copy())
        off += n
    if off != len(payload):
        raise SpillCorrupt(
            f"spill payload {len(payload)}B != leaf specs {off}B")
    return out


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platforms without dir-fd fsync: rename is still atomic
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class KvSpillStore:
    """Manifest-verified storage tier for hibernated sessions (ISSUE 12).

    The spill wire format IS the PR 7 ``export_sequence`` snapshot:
    scheduler meta (tokens, position, budget, sampling knobs) in a JSON
    manifest, block leaf bytes + the next-token logits row in packed
    binary payloads.  Crash-safety is the PR 5 discipline one tier down:

    - WRITE: everything lands in a hidden ``.staging-`` dir (payloads
      fsync'd, then the manifest, then the dir), published by ONE atomic
      ``rename``.  A writer that dies mid-spill leaves a stale staging
      dir (garbage-collected later) and NO entry — the source engine
      still owns the sequence and resumes in place.
    - READ: the manifest records every payload file's size + sha256 AND
      the sequence's chained ``paged.block_keys`` content index.  A torn
      or corrupted payload is detected at thaw — the caller re-prefills
      from the manifest's token record instead of serving wrong KV
      (``kv_spill_verify_failures_total``).  An unreadable manifest
      raises :class:`SpillCorrupt`: that session is not recoverable from
      this tier.

    ``chaos`` takes a :class:`~kubeflow_tpu.chaos.plan.FaultPlan`: the
    store polls its ``due_spill_kills`` / ``due_spill_torn`` /
    ``due_tier_stalls`` actuators at the matching phase boundaries.
    All I/O here runs on hibernation worker threads — the analyzer
    roots ``*Spill`` classes so a path onto an engine scheduler thread
    fails tier-1.
    """

    def __init__(self, root: str, *, fsync: bool = True, chaos=None):
        import threading

        self.root = root
        self.fsync = bool(fsync)
        self.chaos = chaos
        os.makedirs(root, exist_ok=True)
        #: ONE store is shared by every engine behind a runtime and
        #: hibernations run on arbitrary caller threads — counters are
        #: locked (bare += across threads loses increments) and the
        #: per-write chaos kill set is threaded through LOCALS, never
        #: instance state (a concurrent write's cleanup would clear
        #: another write's drawn fault)
        self._mu = threading.Lock()
        self.writes_total = 0
        self.reads_total = 0
        self.verify_failures_total = 0

    # -- chaos seams -------------------------------------------------------

    def _stall(self) -> None:
        if self.chaos is not None:
            for s in self.chaos.due_tier_stalls():
                time.sleep(s)

    @staticmethod
    def _maybe_kill(phase: str, due: set) -> None:
        if phase in due:
            raise StorageError(f"chaos: spill writer killed mid-{phase}")

    # -- paths -------------------------------------------------------------

    def _entry_dir(self, session_id: str) -> str:
        key = hashlib.sha256(session_id.encode()).hexdigest()[:24]
        return os.path.join(self.root, key)

    def sessions(self) -> list[str]:
        """Session ids of every published spill entry."""
        out = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for name in names:
            if name.startswith("."):
                continue
            mpath = os.path.join(self.root, name, SPILL_MANIFEST)
            try:
                with open(mpath) as f:
                    out.append(json.load(f)["session"])
            except (OSError, json.JSONDecodeError, KeyError):
                continue
        return out

    def contains(self, session_id: str) -> bool:
        return os.path.exists(
            os.path.join(self._entry_dir(session_id), SPILL_MANIFEST))

    def session_count(self) -> int:
        """Published entries (cheap dir scan — the ``/metrics`` gauge
        ``kv_sessions_hibernated`` reads this per scrape)."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0
        return sum(
            1 for name in names
            if not name.startswith(".") and os.path.exists(
                os.path.join(self.root, name, SPILL_MANIFEST)))

    # -- write (spill) -----------------------------------------------------

    def write(self, session_id: str, snapshot: dict,
              block_keys: Optional[list] = None) -> str:
        """Persist one exported snapshot atomically; returns the entry
        dir.  Overwrites an existing entry for the session (the newest
        hibernation wins — the rename replaces nothing in place, the
        old entry is removed only after the new one published)."""
        import numpy as np

        self._stall()
        # the drawn kill is LOCAL to this write: a concurrent write's
        # completion must not clear it before the phase boundary fires
        due = set(self.chaos.due_spill_kills()) if self.chaos else set()
        entry_dir = self._entry_dir(session_id)
        key = os.path.basename(entry_dir)
        for leftover in _stale_staging_dirs(self.root, key):
            shutil.rmtree(leftover, ignore_errors=True)
        # displaced-entry debris: a crash between the two publish
        # renames below leaves a superseded copy under a hidden
        # ``.old-<key>-`` name — by construction garbage (the replace
        # only runs after the NEW entry staged fully), so any age GCs
        try:
            for name in os.listdir(self.root):
                if name.startswith(f".old-{key}-"):
                    shutil.rmtree(os.path.join(self.root, name),
                                  ignore_errors=True)
        except OSError:
            pass
        tmp_dir = os.path.join(
            self.root, f".staging-{key}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        os.makedirs(tmp_dir)
        try:
            blocks = snapshot.get("blocks", [])
            logits = snapshot.get("logits")
            # analysis: ok host-sync-in-dispatch — snapshot leaves are host numpy (spill worker)
            leaves = ([{"dtype": str(np.asarray(x).dtype),
                        "shape": list(np.shape(x))} for x in blocks[0]]
                      if blocks else [])
            files = []
            payload = b"".join(_pack_spill_leaves(blk) for blk in blocks)
            ppath = os.path.join(tmp_dir, "blocks.bin")
            with open(ppath, "wb") as f:
                f.write(payload)
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            files.append({"path": "blocks.bin", "size": len(payload),
                          "sha256": hashlib.sha256(payload).hexdigest()})
            self._maybe_kill("payload", due)
            logits_spec = None
            if logits is not None:
                # analysis: ok host-sync-in-dispatch — logits row is host numpy (spill worker)
                row = np.asarray(logits)
                logits_spec = {"dtype": str(row.dtype),
                               "shape": list(row.shape)}
                lpay = _pack_spill_leaves([row])
                lpath = os.path.join(tmp_dir, "logits.bin")
                with open(lpath, "wb") as f:
                    f.write(lpay)
                    if self.fsync:
                        f.flush()
                        os.fsync(f.fileno())
                files.append({"path": "logits.bin", "size": len(lpay),
                              "sha256": hashlib.sha256(lpay).hexdigest()})
            meta = {k: v for k, v in snapshot.items()
                    if k not in ("blocks", "logits", "blocks_dev",
                                 "logits_dev")}
            manifest = {
                "session": session_id, "created": time.time(),
                "meta": meta, "leaves": leaves, "nblocks": len(blocks),
                "logits": logits_spec,
                #: chained content keys (paged.block_keys) — the
                #: cluster-scope content-addressed index of this spill
                "block_keys": [int(k) for k in (block_keys or [])],
                "files": files,
            }
            mpath = os.path.join(tmp_dir, SPILL_MANIFEST)
            with open(mpath, "w") as f:
                json.dump(manifest, f)
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            self._maybe_kill("meta", due)
            if self.fsync:
                _fsync_dir(tmp_dir)
            self._maybe_kill("publish", due)
            old = None
            if os.path.exists(entry_dir):
                # replace: move the old entry to a HIDDEN .old- name
                # (session listings skip dotted dirs; a crash before
                # the rmtree leaves debris the next same-key write
                # GCs above), then rename the staged copy in.  The
                # gap between the two renames is a brief no-manifest
                # window — only a concurrent reader of the SAME
                # session could see it, and a session has one owner.
                old = os.path.join(
                    self.root, f".old-{key}-{uuid.uuid4().hex[:8]}")
                os.rename(entry_dir, old)
            os.rename(tmp_dir, entry_dir)
            if self.fsync:
                _fsync_dir(self.root)
            if old is not None:
                shutil.rmtree(old, ignore_errors=True)
        except BaseException:
            # a chaos kill (or real I/O error) publishes NOTHING; the
            # staging dir stays for the stale-GC, exactly as a kill -9
            # would leave it
            raise
        with self._mu:
            self.writes_total += 1
        if self.chaos is not None:
            for torn in self.chaos.due_spill_torn():
                self._tear(entry_dir, torn)
        return entry_dir

    @staticmethod
    def _tear(entry_dir: str, torn_bytes: int) -> None:
        """Chaos actuator: drop the last ``torn_bytes`` of the payload
        (a torn write at the device layer — the manifest survives, the
        hash check must catch the loss)."""
        p = os.path.join(entry_dir, "blocks.bin")
        try:
            size = os.path.getsize(p)
            with open(p, "r+b") as f:
                f.truncate(max(size - max(int(torn_bytes), 1), 0))
        except OSError:
            pass

    # -- read (thaw) -------------------------------------------------------

    def read(self, session_id: str) -> tuple[dict, bool]:
        """(snapshot, payload_ok) for a hibernated session.

        The snapshot always carries the manifest's scheduler meta —
        enough to RE-PREFILL the session from tokens.  ``payload_ok``
        is True only when every payload file matched its recorded
        size + sha256; then (and only then) ``blocks``/``logits`` are
        attached and the thaw may scatter them.  Raises
        :class:`SpillCorrupt` when the manifest itself is missing or
        unreadable."""
        self._stall()
        entry_dir = self._entry_dir(session_id)
        mpath = os.path.join(entry_dir, SPILL_MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            meta = dict(manifest["meta"])
            nblocks = int(manifest["nblocks"])
            specs = list(manifest["leaves"])
        except (OSError, json.JSONDecodeError, KeyError, TypeError,
                ValueError) as e:
            raise SpillCorrupt(
                f"session {session_id!r}: spill manifest unreadable: "
                f"{e}") from e
        with self._mu:
            self.reads_total += 1
        snapshot = dict(meta)
        ok = True
        payloads: dict[str, bytes] = {}
        for rec in manifest.get("files", []):
            p = os.path.join(entry_dir, rec["path"])
            try:
                with open(p, "rb") as f:
                    data = f.read()
                if len(data) != int(rec["size"]) or (
                        hashlib.sha256(data).hexdigest() != rec["sha256"]):
                    ok = False
                    break
                payloads[rec["path"]] = data
            except OSError:
                ok = False
                break
        if ok:
            try:
                per_block = _unpack_spill_leaves(
                    payloads.get("blocks.bin", b""),
                    [s for _ in range(nblocks) for s in specs])
                step = len(specs)
                snapshot["blocks"] = [
                    per_block[i * step:(i + 1) * step]
                    for i in range(nblocks)]
                if manifest.get("logits") is not None:
                    snapshot["logits"] = _unpack_spill_leaves(
                        payloads.get("logits.bin", b""),
                        [manifest["logits"]])[0]
            except SpillCorrupt:
                ok = False
                snapshot.pop("blocks", None)
                snapshot.pop("logits", None)
        if not ok:
            with self._mu:
                self.verify_failures_total += 1
        return snapshot, ok

    def read_manifest(self, session_id: str) -> dict:
        """The raw manifest (block_keys index, file records) — the
        cluster registry's probe surface."""
        mpath = os.path.join(self._entry_dir(session_id), SPILL_MANIFEST)
        try:
            with open(mpath) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise SpillCorrupt(
                f"session {session_id!r}: spill manifest unreadable: "
                f"{e}") from e

    def delete(self, session_id: str) -> None:
        shutil.rmtree(self._entry_dir(session_id), ignore_errors=True)

    def stats(self) -> dict:
        return {
            "kv_spill_writes_total": self.writes_total,
            "kv_spill_reads_total": self.reads_total,
            "kv_spill_verify_failures_total": self.verify_failures_total,
        }


def list_cache(cache_dir: str) -> list[dict]:
    """Manifests of every cache entry (the repository-listing surface)."""
    out = []
    try:
        entries = sorted(os.listdir(cache_dir))
    except OSError:
        return out
    for name in entries:
        if name.startswith("."):  # in-flight/orphaned staging dirs
            continue
        mpath = os.path.join(cache_dir, name, MANIFEST_NAME)
        try:
            with open(mpath) as f:
                m = json.load(f)
            m["entry"] = name
            m["valid"] = verify_manifest(os.path.join(cache_dir, name))
            out.append(m)
        except (OSError, json.JSONDecodeError):
            continue
    return out
