"""Storage initializer: resolve a storage URI to a local model directory.

[upstream: kserve/kserve -> pkg/agent/storage + python/kserve/kserve/storage]
— the init container that downloads ``gs://``/``s3://``/``pvc://`` into
``/mnt/models`` before the server starts.  Here a library call with the same
contract: ``download(uri) -> local path``.

Schemes:
  file:///abs/path   local directory/file (the PVC analog)
  mem://<key>        in-process registry (tests, zero-copy handoff)
  gs:// s3:// hf://  recognized but gated: this environment has zero egress,
                     so they raise with a clear message instead of hanging.
"""

from __future__ import annotations

import os
from typing import Any

_MEM_REGISTRY: dict[str, Any] = {}


class StorageError(RuntimeError):
    pass


def register_mem(key: str, value: Any) -> str:
    """Publish an object under ``mem://<key>`` (test/bench convenience)."""
    _MEM_REGISTRY[key] = value
    return f"mem://{key}"


def fetch_mem(key: str) -> Any:
    try:
        return _MEM_REGISTRY[key]
    except KeyError:
        raise StorageError(f"mem://{key} not registered") from None


def download(uri: str) -> str:
    """Resolve ``uri`` to a local filesystem path (V1 storage contract)."""
    if uri.startswith("file://"):
        path = uri[len("file://"):]
        if not os.path.exists(path):
            raise StorageError(f"{uri}: no such path")
        return path
    if uri.startswith("mem://"):
        # mem objects have no path; callers use fetch_mem directly
        key = uri[len("mem://"):]
        if key not in _MEM_REGISTRY:
            raise StorageError(f"{uri} not registered")
        return uri
    for scheme in ("gs://", "s3://", "hf://", "http://", "https://"):
        if uri.startswith(scheme):
            raise StorageError(
                f"{uri}: remote storage requires network egress, which this "
                "deployment does not have; stage the model locally and use file://"
            )
    raise StorageError(f"unsupported storage uri {uri!r}")
