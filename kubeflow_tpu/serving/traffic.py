"""Traffic plane: prefix-affinity routing, per-tenant QoS, preemption.

The front door ROADMAP item 4 names (ISSUE 9): nothing upstream of the
engines was traffic-aware — the Router smooth-WRRed replicas blind to
which one already holds a request's cached KV blocks, Profiles enforced
resource quotas at gang admission but carried no request-rate or
priority semantics, and overload meant unbounded queue growth inside
the engine.  This module is the missing subsystem, host-side and
stdlib-only on purpose (every decision here runs on router / HTTP
worker threads; the analyzer roots ``*TrafficPlane``/``*Admission``/
``*Preemptor`` classes in ``host-sync-in-dispatch`` so none of this
accounting can creep onto an engine scheduler thread):

- **Per-tenant QoS classes** (:class:`QosClass` / :class:`TrafficPlane`):
  token-bucket rate limiting, a priority tier (``high``/``normal``/
  ``low`` -> the engine's ``Request.priority``), a max-concurrent slot
  count, and a BOUNDED admission queue per class.  ``acquire`` returns
  an explicit shed decision (429 + ``Retry-After``) the HTTP layer
  writes to the client — the SSE path blocks at this front door inside
  the bound, so overload becomes explicit backpressure instead of
  unbounded buffering (the vLLM/apiserver bounded-queue rule the
  control plane already follows).

- **Prefix-affinity routing** (:class:`PrefixAffinity`): hash the
  request's prompt-prefix blocks (``paged.block_keys`` — the block
  economy's content identity) and route to the replica whose allocator
  registry already holds them; the prefix cache is only as good as the
  router that feeds it.  Falls back to least-loaded, and an affinity
  hit is overridden when the target is overloaded relative to its
  peers (a hot shared prefix must not melt one replica).

- **Priority preemption** (:class:`EnginePreemptor`): when a
  high-priority request is waiting and the pool is full of
  lower-priority sequences, export the lowest-priority live sequence
  (PR 7's ``export_sequence`` — tokens stay bit-identical on resume),
  release its slot + blocks, and park the snapshot; it re-imports the
  moment capacity frees and no higher-priority demand waits.
  Evict-and-requeue is cheap exactly because KV is paged and
  migratable — the parked state is the same snapshot a live migration
  ships.
"""

from __future__ import annotations

import collections
import logging
import random
import threading
import time
from typing import Any, Callable, Optional

log = logging.getLogger("kubeflow_tpu.serving")

#: process-default jitter source.  Every policy object in this module
#: takes ``rng=`` (and ``clock=``) so the digital twin (``sim/``) can
#: inject a seeded stream and a virtual clock; live deployments fall
#: back to this shared instance.  The ``wall-clock-in-policy`` analyzer
#: rule holds the line: policy code never calls module-level
#: ``random.*`` or ``time.*`` directly.
_RNG = random.Random()

#: priority tiers, best first — the names Profiles/configs use; the
#: ints are what the engine's admission sort and the preemptor compare
PRIORITY_TIERS = {"high": 0, "normal": 1, "low": 2}
_TIER_NAMES = {v: k for k, v in PRIORITY_TIERS.items()}


def priority_tier(value, default: int = 1) -> int:
    """Priority spec (name or int) -> tier int; raises on unknown."""
    if value is None:
        return default
    if isinstance(value, str):
        if value not in PRIORITY_TIERS:
            raise ValueError(
                f"unknown priority tier {value!r} "
                f"(one of {sorted(PRIORITY_TIERS)})")
        return PRIORITY_TIERS[value]
    tier = int(value)
    if tier not in _TIER_NAMES:
        raise ValueError(
            f"priority tier {tier} out of range "
            f"({sorted(_TIER_NAMES)})")
    return tier


class QosClass:
    """One tenant class's QoS contract (the Profile ``qos`` shape).

    ``rate``: sustained requests/second through a token bucket (0 =
    unlimited); ``burst``: bucket depth (defaults to max(1, rate));
    ``priority``: tier name; ``max_concurrent``: live requests allowed
    past the door at once (0 = unlimited); ``queue_depth``: how many
    requests may WAIT for a concurrency slot before the class sheds
    (the bounded admission queue — 0 disables waiting entirely).
    """

    FIELDS = ("rate", "burst", "priority", "max_concurrent",
              "queue_depth")

    def __init__(self, name: str, rate: float = 0.0,
                 burst: Optional[float] = None,
                 priority: Any = "normal", max_concurrent: int = 0,
                 queue_depth: int = 64):
        self.name = str(name)
        self.rate = float(rate)
        if self.rate < 0:
            raise ValueError(
                f"qos class {name!r}: rate must be >= 0, got {rate}")
        self.burst = float(burst) if burst is not None else max(
            1.0, self.rate)
        if self.burst < 1:
            raise ValueError(
                f"qos class {name!r}: burst must be >= 1, got {burst}")
        try:
            self.priority = priority_tier(priority)
        except ValueError as e:
            raise ValueError(f"qos class {name!r}: {e}") from e
        self.max_concurrent = int(max_concurrent)
        if self.max_concurrent < 0:
            raise ValueError(
                f"qos class {name!r}: max_concurrent must be >= 0")
        self.queue_depth = int(queue_depth)
        if self.queue_depth < 0:
            raise ValueError(
                f"qos class {name!r}: queue_depth must be >= 0")

    @property
    def priority_name(self) -> str:
        return _TIER_NAMES[self.priority]


def validate_qos(spec) -> dict[str, QosClass]:
    """``{"classname": {rate, burst, priority, max_concurrent,
    queue_depth}}`` -> classes; raises ``ValueError`` with the offending
    class + field named.  The ONE validation site: conf-freeze (the
    ISvc controller), the Profile controller, and plane construction
    all call this, so a negative rate or an unknown priority tier is
    rejected identically everywhere."""
    if not isinstance(spec, dict):
        raise ValueError(
            f"qos must be a mapping of class name -> spec, got "
            f"{type(spec).__name__}")
    out: dict[str, QosClass] = {}
    for name, cls_spec in spec.items():
        if not isinstance(cls_spec, dict):
            raise ValueError(
                f"qos class {name!r}: spec must be a mapping, got "
                f"{type(cls_spec).__name__}")
        unknown = set(cls_spec) - set(QosClass.FIELDS)
        if unknown:
            raise ValueError(
                f"qos class {name!r}: unknown fields {sorted(unknown)} "
                f"(allowed: {list(QosClass.FIELDS)})")
        try:
            out[str(name)] = QosClass(name, **cls_spec)
        except TypeError as e:
            # float(None) / int([...]) and friends raise TypeError —
            # callers are promised ValueError for ANY malformed spec
            # (the Failed-status paths catch exactly that; a TypeError
            # escaping here once stalled every ISvc reconcile)
            raise ValueError(f"qos class {name!r}: {e}") from e
    return out


class TokenBucket:
    """Classic token bucket on the monotonic clock.  ``try_take``
    returns 0.0 on grant, else the seconds until a token accrues (the
    client's ``Retry-After``)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = self.burst
        self._clock = clock
        self._t = clock()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> float:
        if self.rate <= 0:
            return 0.0  # unlimited
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate

    def refund(self, n: float = 1.0) -> None:
        """Return a token taken by a request that did no work (a
        concurrency-path shed after the bucket granted it) — without
        the refund, rejected requests drain the bucket and the tenant's
        ADMITTED throughput falls below its contracted rate."""
        if self.rate <= 0:
            return
        with self._lock:
            self._tokens = min(self.burst, self._tokens + n)


class PrefixAffinity:
    """Block-content-key -> backend map: where a prefix's KV blocks
    last landed.  Keys come from ``paged.block_keys`` (chained hashes,
    so ``keys[i]`` identifies the whole prefix through block ``i``);
    the map remembers the DEEPEST key per chain it has seen per
    backend, bounded LRU.  ``best`` walks a request's chain from the
    deepest key down and returns the first backend still live — the
    replica whose allocator registry (live slots, or the
    free-list-as-cache) holds the longest prefix of this prompt."""

    def __init__(self, capacity: int = 8192):
        self.capacity = int(capacity)
        #: key -> backend id (LRU: oldest observation evicts first)
        self._map: "collections.OrderedDict[int, str]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits_total = 0
        self.misses_total = 0

    def observe(self, keys: list[int], backend: str) -> None:
        """Record that ``backend`` is about to hold these prefix
        blocks (called after routing — the replica's prefill/registry
        will hold them by the time the next same-prefix request
        arrives)."""
        if not keys:
            return
        with self._lock:
            for k in keys:
                self._map.pop(k, None)
                self._map[k] = backend
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def forget(self, backend: str) -> None:
        """Drop every key pointing at a dead/removed backend — its KV
        is gone; routing to a corpse for affinity would trade a prefill
        for a connection error."""
        with self._lock:
            stale = [k for k, b in self._map.items() if b == backend]
            for k in stale:
                del self._map[k]

    def best(self, keys: list[int], candidates) -> tuple[Optional[str], int]:
        """(backend, matched block depth) for the deepest key any live
        candidate holds; (None, 0) on a miss.  Deepest-first: a chain
        match at depth i implies every shallower block matches too."""
        cand = set(candidates)
        with self._lock:
            for depth in range(len(keys), 0, -1):
                b = self._map.get(keys[depth - 1])
                if b is not None and b in cand:
                    self.hits_total += 1
                    return b, depth
        self.misses_total += 1
        return None, 0


class SessionAffinity:
    """Session id -> backend map: where a durable session's KV lives
    (ISSUE 12).  A resumed conversation routes to the replica whose
    pool (HBM/host tier) still holds its blocks — warm resume.  When
    that replica died, ``forget`` dropped it and the resume routes
    least-loaded instead: ANY replica can thaw the session from the
    shared storage tier, which is exactly the durability contract (the
    affinity is a latency optimization, never a correctness
    dependency).  Bounded LRU, same shape as :class:`PrefixAffinity`."""

    def __init__(self, capacity: int = 8192):
        self.capacity = int(capacity)
        self._map: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits_total = 0
        self.misses_total = 0

    def observe(self, session: str, backend: str) -> None:
        if not session:
            return
        with self._lock:
            self._map.pop(session, None)
            self._map[session] = backend
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def forget(self, backend: str) -> None:
        with self._lock:
            stale = [s for s, b in self._map.items() if b == backend]
            for s in stale:
                del self._map[s]

    def best(self, session: str, candidates) -> Optional[str]:
        if not session:
            return None
        with self._lock:
            b = self._map.get(session)
            if b is not None and b in set(candidates):
                self.hits_total += 1
                return b
        self.misses_total += 1
        return None


class KvBlockRegistry:
    """Cluster-scope content-addressed block registry (ISSUE 12, the
    r12 gang-affinity residual): chained block-content key -> the
    replica whose /metrics block-registry digest (rank-0 for gangs)
    last advertised it, with the advertised chain depth.

    ``probe``/``observe_metrics`` ingest ``kft_kv_prefix_key`` rows
    (serving/server.py renders them from ``paged.prefix_digest``);
    ``locate`` answers "which live replica holds the deepest prefix of
    this prompt" so a cold replica can ``kv_fetch`` the KV from a peer
    (serving/gang.py) and ``install_prefix`` it instead of recomputing
    — prefill-once-per-cluster.  Bounded LRU per the PrefixAffinity
    convention; blocking HTTP probes belong on controller/router
    threads, never an engine scheduler."""

    def __init__(self, capacity: int = 8192):
        self.capacity = int(capacity)
        #: key (int) -> (backend, depth)
        self._map: "collections.OrderedDict[int, tuple[str, int]]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.probes_total = 0
        self.hits_total = 0
        self.misses_total = 0

    def observe_metrics(self, backend: str, metrics_text: str) -> int:
        """Ingest one replica's /metrics exposition; returns the number
        of registry rows seen."""
        import re

        rows = re.findall(
            r'^kft_kv_prefix_key\{[^}]*key="([0-9a-f]+)"[^}]*\}\s+'
            r'(\d+)', metrics_text, re.MULTILINE)
        with self._lock:
            for key_hex, depth in rows:
                k = int(key_hex, 16)
                self._map.pop(k, None)
                self._map[k] = (backend, int(depth))
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)
        return len(rows)

    def probe(self, backend: str, url: str, timeout: float = 2.0) -> int:
        """Scrape ``url``/metrics and ingest its registry rows (the
        rank-0 probe).  Returns rows seen; 0 on any failure."""
        import urllib.request

        self.probes_total += 1
        try:
            with urllib.request.urlopen(
                    url.rstrip("/") + "/metrics", timeout=timeout) as r:
                return self.observe_metrics(backend, r.read().decode())
        except (OSError, ValueError):
            return 0

    def forget(self, backend: str) -> None:
        """A dead replica's KV died with it."""
        with self._lock:
            stale = [k for k, (b, _d) in self._map.items()
                     if b == backend]
            for k in stale:
                del self._map[k]

    def locate(self, keys: list[int],
               exclude=()) -> tuple[Optional[str], int]:
        """(backend, matched block depth) for the deepest key of this
        chain any known replica advertises; (None, 0) on a miss."""
        skip = set(exclude)
        with self._lock:
            for depth in range(len(keys), 0, -1):
                hit = self._map.get(keys[depth - 1])
                if hit is not None and hit[0] not in skip:
                    self.hits_total += 1
                    return hit[0], depth
        self.misses_total += 1
        return None, 0

    def heat_by_backend(self) -> dict[str, int]:
        """backend -> number of registry entries it advertises — the
        per-replica KV footprint the autoscaler's scale-down victim
        pick consumes (ISSUE 15): retiring the coldest backend
        invalidates the least cluster prefix reuse."""
        with self._lock:
            out: dict[str, int] = {}
            for b, _depth in self._map.values():
                out[b] = out.get(b, 0) + 1
        return out

    def stats(self) -> dict:
        with self._lock:
            entries = len(self._map)
        return {
            "kv_registry_entries": entries,
            "kv_registry_probes_total": self.probes_total,
            "kv_registry_hits_total": self.hits_total,
            "kv_registry_misses_total": self.misses_total,
        }


class BackendHealth:
    """Per-backend health circuit (ISSUE 16): closed -> open ->
    half-open -> closed.

    Before this existed, ``Router._backend_down`` forgot a backend's
    affinity forever and kept ROUTING to it until membership churn
    removed the URL — every request burned a connect attempt on the
    corpse.  The circuit makes death a first-class, RECOVERABLE state:

    - **closed**: traffic flows; failures are counted (consecutive +
      a sliding error-rate window).
    - **open**: ``fail_threshold`` consecutive failures (or the window
      error rate crossing ``error_rate``) trips the circuit; routing
      skips the backend until a JITTERED recovery deadline (jitter so
      N routers probing one recovering replica don't arrive as a
      synchronized wave).
    - **half-open**: past the deadline, exactly ONE live request is
      allowed through as the recovery probe (``on_routed`` arms it);
      success closes the circuit, failure re-opens it with doubled
      backoff up to ``open_cap_s``.

    Selection is two-phase so an unpicked candidate never strands a
    probe: ``routable(candidates)`` is a pure filter (no side
    effects), and the router calls ``on_routed(choice)`` on the ONE
    backend it actually forwards to.  All state sits under one lock;
    every caller is a router/HTTP worker thread."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, fail_threshold: int = 3, error_rate: float = 0.5,
                 window: int = 20, open_s: float = 1.0,
                 open_cap_s: float = 30.0, probe_jitter: float = 0.5,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        if int(fail_threshold) < 1:
            raise ValueError("fail_threshold must be >= 1")
        if not (0.0 < float(error_rate) <= 1.0):
            raise ValueError("error_rate must be in (0, 1]")
        if float(open_s) <= 0 or float(open_cap_s) < float(open_s):
            raise ValueError("need 0 < open_s <= open_cap_s")
        self.fail_threshold = int(fail_threshold)
        self.error_rate = float(error_rate)
        self.window = max(2, int(window))
        self.open_s = float(open_s)
        self.open_cap_s = float(open_cap_s)
        self.probe_jitter = max(0.0, float(probe_jitter))
        self._clock = clock
        self._rng = rng if rng is not None else _RNG
        #: url -> mutable record (state machine per backend)
        self._circuits: dict[str, dict] = {}
        self._lock = threading.Lock()
        self.opens_total = 0
        self.closes_total = 0
        self.probes_total = 0

    def _rec(self, backend: str) -> dict:
        rec = self._circuits.get(backend)
        if rec is None:
            rec = self._circuits[backend] = {
                "state": self.CLOSED, "consec": 0,
                "outcomes": collections.deque(maxlen=self.window),
                "reopen_at": 0.0, "open_for": self.open_s,
                "probe_inflight": False,
            }
        return rec

    def _trip(self, rec: dict, now: float) -> None:
        rec["state"] = self.OPEN
        rec["probe_inflight"] = False
        rec["reopen_at"] = now + rec["open_for"] * (
            1.0 + self._rng.random() * self.probe_jitter)
        self.opens_total += 1

    def note_failure(self, backend: str) -> None:
        now = self._clock()
        with self._lock:
            rec = self._rec(backend)
            rec["consec"] += 1
            rec["outcomes"].append(False)
            if rec["state"] == self.HALF_OPEN:
                # failed probe: re-open with doubled backoff — a
                # replica mid-restart must not eat a probe per open_s
                rec["open_for"] = min(rec["open_for"] * 2.0,
                                      self.open_cap_s)
                self._trip(rec, now)
                return
            if rec["state"] != self.CLOSED:
                return
            outcomes = rec["outcomes"]
            rate_hot = (len(outcomes) >= self.window
                        and outcomes.count(False) / len(outcomes)
                        >= self.error_rate)
            if rec["consec"] >= self.fail_threshold or rate_hot:
                rec["open_for"] = self.open_s
                self._trip(rec, now)

    def note_success(self, backend: str) -> None:
        with self._lock:
            rec = self._circuits.get(backend)
            if rec is None:
                return
            rec["consec"] = 0
            rec["outcomes"].append(True)
            if rec["state"] != self.CLOSED:
                # a successful probe (or an in-flight request that
                # outlived the trip) is recovery evidence either way
                rec["state"] = self.CLOSED
                rec["open_for"] = self.open_s
                rec["probe_inflight"] = False
                self.closes_total += 1

    def trip(self, backend: str) -> None:
        """Force-open one circuit NOW (the domain-outage mass action:
        when a whole domain is declared down, its other members must
        not each burn ``fail_threshold`` connect attempts first)."""
        now = self._clock()
        with self._lock:
            rec = self._rec(backend)
            if rec["state"] != self.OPEN:
                rec["open_for"] = self.open_s
                self._trip(rec, now)

    def forget(self, backend: str) -> None:
        """Membership churn removed the URL — ports never come back,
        so the record must die with it (unbounded growth otherwise)."""
        with self._lock:
            self._circuits.pop(backend, None)

    def state(self, backend: str) -> str:
        with self._lock:
            rec = self._circuits.get(backend)
            return rec["state"] if rec else self.CLOSED

    def routable(self, candidates) -> list:
        """Pure filter: the candidates traffic may reach this instant —
        closed circuits, plus open ones whose jittered recovery
        deadline has passed and half-open ones with no probe already
        in flight.  No side effects: arming the probe is
        :meth:`on_routed`'s job, on the ONE candidate actually
        picked."""
        now = self._clock()
        out = []
        with self._lock:
            for b in candidates:
                rec = self._circuits.get(b)
                if rec is None or rec["state"] == self.CLOSED:
                    out.append(b)
                elif rec["probe_inflight"]:
                    continue  # one probe at a time
                elif rec["state"] == self.HALF_OPEN or now >= rec["reopen_at"]:
                    out.append(b)
        return out

    def on_routed(self, backend: str) -> None:
        """The router picked ``backend``: if its circuit is non-closed
        this request IS the recovery probe — arm it (one at a time)."""
        with self._lock:
            rec = self._circuits.get(backend)
            if rec is None or rec["state"] == self.CLOSED:
                return
            rec["state"] = self.HALF_OPEN
            rec["probe_inflight"] = True
            self.probes_total += 1

    def open_backends(self) -> list[str]:
        with self._lock:
            return [b for b, rec in self._circuits.items()
                    if rec["state"] == self.OPEN]

    def stats(self) -> dict:
        with self._lock:
            states = [rec["state"] for rec in self._circuits.values()]
        return {
            "circuit_open_backends": states.count(self.OPEN),
            "circuit_half_open_backends": states.count(self.HALF_OPEN),
            "circuit_opens_total": self.opens_total,
            "circuit_closes_total": self.closes_total,
            "circuit_probes_total": self.probes_total,
        }


class RetryBudget:
    """Cluster retry budget (ISSUE 16): re-routes are permitted as a
    CAPPED FRACTION of recent successes, token-bucket style.

    The amplification bound the outage bench pins: N dying replicas
    under a 2x open-loop storm must not multiply into a
    2(1+retries)x storm — with the budget, total forwarded attempts
    stay <= (1 + ratio) * successes (plus the small ``floor_rate``
    trickle that keeps single-failure failover alive when the cluster
    is quiet and the success-funded bucket is empty).

    ``note_success`` deposits ``ratio`` tokens (capped at ``burst``);
    ``try_retry`` spends one, falling back to the floor bucket, and
    returns False when the budget is exhausted — the router then
    answers 503 with a jittered ``Retry-After`` instead of amplifying
    the storm."""

    def __init__(self, ratio: float = 0.2, burst: float = 5.0,
                 floor_rate: float = 0.5,
                 clock: Callable[[], float] = time.monotonic):
        if float(ratio) < 0:
            raise ValueError("ratio must be >= 0")
        if float(burst) < 1:
            raise ValueError("burst must be >= 1")
        self.ratio = float(ratio)
        self.burst = float(burst)
        #: start full: the first failure after a quiet period must be
        #: allowed to fail over without waiting for successes
        self._tokens = self.burst
        self._floor = TokenBucket(max(0.0, float(floor_rate)),
                                  burst=1.0, clock=clock)
        self._lock = threading.Lock()
        self.retries_granted_total = 0
        self.retries_denied_total = 0

    def note_success(self) -> None:
        if self.ratio <= 0:
            return
        with self._lock:
            self._tokens = min(self.burst, self._tokens + self.ratio)

    def try_retry(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.retries_granted_total += 1
                return True
        if self._floor.rate > 0 and self._floor.try_take() == 0.0:
            with self._lock:
                self.retries_granted_total += 1
            return True
        with self._lock:
            self.retries_denied_total += 1
        return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "retry_budget_tokens": round(self._tokens, 3),
                "retries_granted_total": self.retries_granted_total,
                "retries_denied_total": self.retries_denied_total,
            }


def jittered_retry_after(base: float = 1.0, load: float = 0.0,
                         spread: float = 0.5, cap: float = 30.0,
                         rng: Optional[random.Random] = None) -> float:
    """The ONE retry-after hint: a load-aware base, JITTERED so shed /
    503'd clients do not re-arrive as a synchronized wave (the
    constant ``retry_after=1`` at the router's no-ready-replicas path
    meant every client of a dead domain retried in lockstep —
    herd-safe recovery needs the herd spread out).  Uniform in
    ``[hint*(1-spread), hint*(1+spread)]`` where ``hint = base +
    load``, clamped to ``[0.05, cap]``.  Both the plane's concurrency
    shed ETA and the router's 503 ride this helper — one responder,
    no drifting copies (the PR 8 ``shed_http`` lesson)."""
    r = (_RNG if rng is None else rng).random()
    hint = min(float(cap), max(0.05, float(base) + float(load)))
    spread = max(0.0, min(float(spread), 1.0))
    lo = hint * (1.0 - spread)
    hi = hint * (1.0 + spread)
    return min(float(cap), max(0.05, lo + r * (hi - lo)))


def smooth_wrr_pick(pools: list, cur: list[int]) -> int:
    """Smooth weighted round-robin pool selection (nginx-style):
    deterministic, exact proportions over any window, and INTERLEAVED
    — a block split (first 80 of 100 to stable) would starve the
    canary on short request bursts.  ``pools`` is ``[(urls, weight)]``;
    ``cur`` is the per-pool current-weight state, mutated in place
    (the caller holds whatever lock guards it).  Returns the chosen
    pool index.  Extracted from ``Router._pick`` (ISSUE 20) so the
    live router and the sim twin share one pick policy by
    construction — pure arithmetic, no clock, no rng."""
    total = sum(w for _, w in pools)
    best = 0
    for i, (_, w) in enumerate(pools):
        cur[i] += w
        if cur[i] > cur[best]:
            best = i
    cur[best] -= total
    return best


def live_candidates(urls: list[str], routable: Callable[[list], list],
                    exclude=None, avoid_domains=None,
                    domain_of: Optional[Callable[[str], str]] = None
                    ) -> list[str]:
    """The candidate filter of the router pick (ISSUE 16 semantics,
    extracted for ISSUE 20): drop explicitly excluded urls (already
    tried this request), keep only circuit-routable ones (``routable``
    is :meth:`BackendHealth.routable` — a pure filter), then prefer
    SURVIVING domains over the ones that just failed — but only when
    at least one such candidate exists (with domains unset every url
    maps to ``''`` and the spread no-ops).  Pure given its inputs;
    arming a half-open probe stays the caller's job on the ONE
    backend actually picked."""
    out = [u for u in urls if not exclude or u not in exclude]
    out = routable(out)
    if avoid_domains and out and domain_of is not None:
        spread = [u for u in out
                  if domain_of(u) not in avoid_domains]
        if spread:
            out = spread
    return out


class ClusterPrefixPoller:
    """Router-side block-registry poller (ISSUE 13 satellite, the r16
    residual): scrape every live replica's ``/metrics``
    ``kft_kv_prefix_key`` rows on a JITTERED interval (synchronized
    scrapes across routers would thundering-herd the replicas), feed
    the :class:`KvBlockRegistry`, and keep a per-key replica census so
    the router exports cluster prefix-heat gauges
    (``kft_cluster_prefix_replicas{key=...}``) — placement decisions
    become observable before the autoscaler exists (ROADMAP item 2
    consumes exactly this).

    ``backends``: callable returning the live replica URL list (the
    router's pools are the membership truth).  Blocking HTTP runs on
    this poller's own daemon thread — never a scheduler or reconcile
    worker."""

    def __init__(self, backends: Callable[[], list[str]],
                 registry: Optional[KvBlockRegistry] = None,
                 interval_s: float = 5.0, jitter: float = 0.25,
                 capacity: int = 4096,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        self.interval_s = float(interval_s)
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.jitter = max(0.0, min(float(jitter), 0.9))
        self._clock = clock
        self._rng = rng if rng is not None else _RNG
        self._backends = backends
        self.registry = registry or KvBlockRegistry()
        self.capacity = int(capacity)
        #: key hex -> {backend: depth} — the census behind the gauges
        self._heat: "collections.OrderedDict[str, dict[str, int]]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        #: unreachable-backend backoff (ISSUE 16 satellite): url ->
        #: (skip-until monotonic deadline, consecutive failures).
        #: During a domain outage the sweep used to burn a full scrape
        #: timeout per dead backend per cycle; now a dead backend is
        #: skipped with per-backend jittered exponential backoff and
        #: re-probed cheaply once its deadline passes.
        self._unreachable: dict[str, tuple[float, int]] = {}
        self.polls_total = 0
        self.poll_skips_total = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="prefix-poller", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            # jittered sleep FIRST: construction must not scrape before
            # the router's pools are even wired
            delay = self.interval_s * (
                1.0 + self._rng.uniform(-self.jitter, self.jitter))
            if self._stop.wait(delay):
                return
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — a scrape cycle
                # failing (replica churn mid-walk) costs one interval,
                # never the poller thread
                log.debug("prefix poll failed: %s", e)

    def poll_once(self) -> int:
        """One scrape sweep over the current backends; returns total
        registry rows seen.  Public for tests and operator tooling."""
        import re
        import urllib.request

        self.polls_total += 1
        urls = list(self._backends() or [])
        now = self._clock()
        with self._lock:
            # membership churn prunes the backoff table with the pool
            self._unreachable = {
                u: v for u, v in self._unreachable.items() if u in urls}
            skipping = {u for u, (until, _n) in self._unreachable.items()
                        if now < until}
        seen: dict[str, dict[str, int]] = {}
        reached: set[str] = set()
        rows_total = 0
        for url in urls:
            if url in skipping:
                # unreachable last sweep(s): inside its jittered
                # backoff window — do NOT burn a scrape timeout on it
                self.poll_skips_total += 1
                continue
            try:
                with urllib.request.urlopen(
                        url.rstrip("/") + "/metrics", timeout=2.0) as r:
                    text = r.read().decode()
            except (OSError, ValueError):
                # timed out / down: keep its prior entries, back off
                # exponentially (jittered so N routers re-probe a
                # recovering replica spread out, not as one wave)
                with self._lock:
                    _until, fails = self._unreachable.get(url, (0.0, 0))
                    fails += 1
                    delay = min(self.interval_s * (2.0 ** (fails - 1)),
                                8.0 * self.interval_s)
                    delay *= 1.0 + self._rng.uniform(-self.jitter,
                                                     self.jitter)
                    self._unreachable[url] = (self._clock() + delay,
                                              fails)
                continue
            reached.add(url)
            with self._lock:
                self._unreachable.pop(url, None)
            rows_total += self.registry.observe_metrics(url, text)
            for key_hex, depth in re.findall(
                    r'^kft_kv_prefix_key\{[^}]*key="([0-9a-f]+)"'
                    r'[^}]*\}\s+(\d+)', text, re.MULTILINE):
                seen.setdefault(key_hex, {})[url] = int(depth)
        with self._lock:
            # merge rule per (key, backend): a REACHED backend's truth
            # is this sweep's rows (entries it stopped advertising
            # drop); a live-but-unreached backend (scrape timeout)
            # keeps its prior entries (one flaky scrape must not flap
            # the heat down); a backend no longer in the pool drops
            # everywhere (its KV died with it — phantom heat forever
            # was the alternative)
            live = set(urls)
            merged: "collections.OrderedDict[str, dict[str, int]]" = \
                collections.OrderedDict()
            for key_hex, per_old in self._heat.items():
                kept = {b: d for b, d in per_old.items()
                        if b in live and b not in reached}
                if kept:
                    merged[key_hex] = kept
            for key_hex, per in seen.items():
                cur = merged.pop(key_hex, {})
                cur.update(per)
                merged[key_hex] = cur  # freshly seen keys are MRU
            self._heat = merged
            while len(self._heat) > self.capacity:
                self._heat.popitem(last=False)
        return rows_total

    def heat(self) -> dict[str, int]:
        """key hex -> number of replicas advertising it."""
        with self._lock:
            return {k: len(v) for k, v in self._heat.items()}

    def heat_by_backend(self) -> dict[str, int]:
        """backend URL -> number of prefix keys it advertises — the
        placement-side view of the census (ISSUE 15): the autoscaler
        retires the replica carrying the LEAST heat, so a scale-down
        costs the fewest warm prefixes."""
        with self._lock:
            out: dict[str, int] = {}
            for per in self._heat.values():
                for b in per:
                    out[b] = out.get(b, 0) + 1
        return out

    def hottest(self, n: int = 8) -> list[tuple[str, int]]:
        """Top-``n`` (key hex, replica count) rows, hottest first —
        the pre-warm working set a freshly placed replica should fetch
        before taking traffic."""
        heat = self.heat()
        return sorted(heat.items(), key=lambda kv: (-kv[1], kv[0]))[:n]

    def metrics_lines(self) -> list[str]:
        """The cluster prefix-heat gauge lines for the router's
        /metrics (TYPE header included; empty when nothing scraped)."""
        heat = self.heat()
        if not heat:
            return []
        lines = ["# TYPE kft_cluster_prefix_replicas gauge"]
        for key_hex in sorted(heat):
            lines.append(
                f'kft_cluster_prefix_replicas{{key="{key_hex}"}} '
                f"{heat[key_hex]}")
        lines.append("# TYPE kft_cluster_prefix_keys gauge")
        lines.append(f"kft_cluster_prefix_keys {len(heat)}")
        return lines

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def prom_label(value) -> str:
    """Escape a Prometheus label VALUE (backslash, quote, newline per
    the text exposition format) — class names and model names are
    arbitrary operator strings, and one stray quote must not poison an
    entire /metrics scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prom_histogram_lines(name: str, labels: str, buckets, counts,
                         total: float, exemplar=None) -> list[str]:
    """Render ONE labeled series of a fixed-bucket Prometheus histogram
    (`_bucket` cumulative + `+Inf`, `_count`, `_sum`).  ``counts`` is
    per-bucket (len(buckets) + 1, last = overflow); ``exemplar`` is an
    optional ``(value_seconds, trace_id)`` attached to the +Inf bucket
    in OpenMetrics syntax.  The ONE histogram renderer — ServerMetrics'
    request-latency histograms and the trace sink's phase histograms
    must stay byte-compatible (the prom_stat_lines rule, one shape
    up)."""
    lines = []
    lbl = f"{labels}," if labels else ""
    cum = 0
    for b, c in zip(buckets, counts):
        cum += c
        lines.append(f'{name}_bucket{{{lbl}le="{b:g}"}} {cum}')
    cum += counts[len(buckets)]
    inf = f'{name}_bucket{{{lbl}le="+Inf"}} {cum}'
    if exemplar is not None:
        inf += (f' # {{trace_id="{prom_label(exemplar[1])}"}}'
                f" {exemplar[0]:.6f}")
    lines.append(inf)
    tail = f"{{{labels}}}" if labels else ""
    lines.append(f"{name}_count{tail} {cum}")
    lines.append(f"{name}_sum{tail} {total:.6f}")
    return lines


def prom_stat_lines(stats: dict, prefix: str,
                    base_labels: str = "") -> dict[str, list[str]]:
    """Render a plane's ``stats()`` into Prometheus families: scalar
    gauges as ``<prefix><key>``, per-class counters with the class as
    an ADDED label.  The ONE renderer — the Router and ModelServer
    exporters must emit byte-compatible lines, so neither carries its
    own walk."""
    fams: dict[str, list[str]] = {}
    for k, v in stats.items():
        if isinstance(v, (int, float)):
            fam = f"{prefix}{k}"
            lbl = f"{{{base_labels}}}" if base_labels else ""
            fams.setdefault(fam, []).append(f"{fam}{lbl} {v}")
    for cname, cvals in stats.get("classes", {}).items():
        cl = f'class="{prom_label(cname)}"'
        lbl = f"{{{base_labels},{cl}}}" if base_labels else f"{{{cl}}}"
        for k, v in cvals.items():
            fam = f"{prefix}{k}"
            fams.setdefault(fam, []).append(f"{fam}{lbl} {v}")
    return fams


def bound_priority(payload: dict, ticket=None,
                   header: Optional[str] = None,
                   classed: bool = False) -> None:
    """Apply the no-self-promotion rule to ``payload['priority']`` in
    place — the ONE enforcement site (the ModelServer door calls it
    with whatever contract it has).  The authoritative tier is the
    ticket's CLASS when this plane classified the tenant, else the
    router's ``X-KFT-Priority`` cluster classification.  When the
    door defines classes (``classed``) but could not classify THIS
    tenant, the cap is "normal" — an anonymous caller must not
    outrank the classed tenants the config exists to order.  A client
    may self-demote below its tier, never outrank it.  Only with no
    ordering contract at all (no class anywhere, no header, or a
    class-free affinity/token-only plane) does the payload stand."""
    auth: Optional[int] = None
    if ticket is not None and ticket.cls is not None:
        auth = ticket.priority
    elif header:
        try:
            auth = priority_tier(header)
        except ValueError:
            auth = None
    if auth is None and ticket is not None and classed:
        auth = PRIORITY_TIERS["normal"]  # classless-at-a-QoS-door cap
    if auth is None:
        return
    asked = payload.get("priority")
    if asked is not None:
        try:
            auth = max(auth, priority_tier(asked))
        except ValueError:
            pass
    payload["priority"] = auth


def shed_http(handler, ticket) -> None:
    """Write the explicit-overload 429 to an http.server handler: a
    ``Retry-After`` header (integer seconds, RFC 7231) + a structured
    reason body.  The ONE shed responder — the Router door and the
    ModelServer door must stay byte-compatible, so neither carries its
    own copy."""
    import json
    import math

    body = json.dumps({
        "error": "request shed by QoS admission",
        "reason": ticket.reason,
        "qos_class": ticket.cls.name if ticket.cls else "",
        "retry_after": round(ticket.retry_after, 3),
    }).encode()
    handler.send_response(429)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Retry-After",
                        str(max(1, math.ceil(ticket.retry_after))))
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


class _Ticket:
    """One admitted request's pass through the front door."""

    __slots__ = ("ok", "cls", "tenant", "retry_after", "reason",
                 "waiter")

    def __init__(self, ok: bool, cls: Optional[QosClass], tenant: str,
                 retry_after: float = 0.0, reason: str = "",
                 waiter: Any = None):
        self.ok = ok
        self.cls = cls
        self.tenant = tenant
        self.retry_after = retry_after
        self.reason = reason
        #: queue token for a non-blocking offer() still waiting for a
        #: concurrency slot (promote()/abandon() consume it)
        self.waiter = waiter

    @property
    def priority(self) -> int:
        return self.cls.priority if self.cls is not None else 1

    @property
    def priority_name(self) -> str:
        return _TIER_NAMES[self.priority]


#: door verdicts — what the pure admission policy can say
ADMIT, SHED_RATE, SHED_QUEUE_FULL, QUEUE = (
    "admit", "rate_limited", "queue_full", "queue")


def door_decision(rate_wait: float, live: int, max_concurrent: int,
                  waiting: int, queue_depth: int) -> str:
    """The ONE front-door admission policy (ISSUE 20 extraction):
    given a class's instantaneous state, decide ADMIT / SHED_RATE /
    SHED_QUEUE_FULL / QUEUE.  Pure — no clock, no locks, no counters;
    the blocking :meth:`TrafficPlane.acquire` and the event-driven
    :meth:`TrafficPlane.offer` (the sim twin's door) both actuate
    exactly this verdict, so live and simulated admission cannot
    drift.

    Decision order mirrors the reverse of cost: the token bucket sheds
    instantly (``rate_wait`` > 0 is the tenant's contract), then the
    concurrency gate passes (the fast path DEFERS to the queue — a
    fresh arrival must not snipe a freed slot from a waiter), queues
    (bounded by ``queue_depth``) or sheds."""
    if rate_wait > 0.0:
        return SHED_RATE
    if max_concurrent <= 0 or (live < max_concurrent and not waiting):
        return ADMIT
    if waiting >= queue_depth:
        return SHED_QUEUE_FULL
    return QUEUE


class _ClassState:
    """Live accounting for one QoS class (plane-lock-protected)."""

    def __init__(self, cls: QosClass,
                 clock: Callable[[], float] = time.monotonic):
        self.cls = cls
        self.bucket = TokenBucket(cls.rate, cls.burst, clock=clock)
        self.live = 0
        #: FIFO of waiter tokens — admission order for queued
        #: requests; its head owns the next freed slot
        self.queue: "collections.deque" = collections.deque()
        self.cond: Optional[threading.Condition] = None  # set by plane
        self.admitted_total = 0
        self.shed_total = 0
        self.queued_total = 0

    @property
    def waiting(self) -> int:
        return len(self.queue)


class TrafficPlane:
    """Per-tenant QoS admission + prefix-affinity routing state.

    One instance fronts either the cluster Router (HTTP door: sheds
    with 429 + ``Retry-After`` before a request ever reaches a
    replica) or one ModelServer (in-process door: concurrency slots +
    the engine preemptor).  All state is host-side under one lock;
    ``acquire`` may BLOCK (bounded, timed) when the class queues — that
    blocking is the SSE path's backpressure, and it happens on the
    caller's HTTP thread, never a scheduler thread.

    ``classes``: name -> :class:`QosClass`; ``tenants``: tenant id ->
    class name (a tenant with no mapping and no class of its own name
    falls to ``default_class``, or rides unlimited when that class is
    not defined).
    """

    def __init__(self, qos: Optional[dict] = None,
                 tenants: Optional[dict[str, str]] = None,
                 default_class: str = "default",
                 affinity_block: int = 32,
                 affinity_capacity: int = 8192,
                 tenant_tokens: Optional[dict[str, str]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Optional[random.Random] = None):
        classes = validate_qos(qos or {})
        self._lock = threading.Lock()
        self._clock = clock
        self._rng = rng if rng is not None else _RNG
        self._classes: dict[str, _ClassState] = {}
        for name, cls in classes.items():
            st = _ClassState(cls, clock=clock)
            st.cond = threading.Condition(self._lock)
            self._classes[name] = st
        self._tenants = {}
        for k, v in (tenants or {}).items():
            if not isinstance(v, str):
                # class_for would .get() an unhashable/mistyped value
                # at REQUEST time — fail construction instead (the
                # conf-freeze/Failed-status paths catch ValueError)
                raise ValueError(
                    f"qos tenants[{k!r}] must name a class (string), "
                    f"got {type(v).__name__}")
            self._tenants[str(k)] = v
        #: tenant -> bearer secret (Profile.spec.api_token): a tenant
        #: with a registered token must PROVE its claim at the door —
        #: QoS classes are identity-scoped, and an unauthenticated
        #: claim would let any client adopt a privileged tenant's rate
        #: and priority.  Tenants without a token stay open (the
        #: hand-wired/test deployments that never minted credentials).
        self._tenant_tokens = {
            k: v for k, v in (tenant_tokens or {}).items() if v}
        self.default_class = default_class
        #: prompt-prefix affinity granularity, in TOKENS of the byte
        #: tokenizer / block-economy quanta (block_keys units)
        self.affinity_block = int(affinity_block)
        self.affinity = PrefixAffinity(affinity_capacity)
        #: durable-session affinity (ISSUE 12): a resume routes to the
        #: replica still holding the session's KV; a dead replica's
        #: entries are forgotten with its prefix affinity, and the
        #: resume then lands anywhere — the storage tier thaws it
        self.sessions = SessionAffinity(affinity_capacity)
        self.preemptors: list[EnginePreemptor] = []

    # -- class resolution --------------------------------------------------

    def class_for(self, tenant: str) -> Optional[_ClassState]:
        name = self._tenants.get(tenant, tenant)
        st = self._classes.get(name)
        if st is None:
            st = self._classes.get(self.default_class)
        return st

    def classes(self) -> dict[str, QosClass]:
        return {n: st.cls for n, st in self._classes.items()}

    def authenticate(self, tenant: str, authorization) -> bool:
        """True when ``tenant``'s claim is acceptable: either no token
        is registered for it (open tenant), or the ``Authorization``
        header carries the matching Bearer secret (constant-time
        compare)."""
        import hmac

        want = self._tenant_tokens.get(tenant)
        if not want:
            return True
        got = str(authorization or "")
        if got.startswith("Bearer "):
            got = got[len("Bearer "):]
        return hmac.compare_digest(got, want)

    # -- admission (the front door) ---------------------------------------

    def acquire(self, tenant: str = "default", *, charge_rate: bool = True,
                wait_timeout: float = 30.0) -> _Ticket:
        """Admit one request for ``tenant``; the caller MUST
        :meth:`release` the returned ticket iff ``ticket.ok``.

        Decision order mirrors the reverse of cost: the token bucket
        sheds instantly (rate is the tenant's contract), then the
        concurrency gate either passes, queues (bounded by the class's
        ``queue_depth``, timed by ``wait_timeout``) or sheds.  A shed
        ticket carries ``retry_after`` seconds and a structured
        ``reason`` for the 429 body."""
        st = self.class_for(tenant)
        if st is None:
            return _Ticket(True, None, tenant)  # no QoS configured
        cls = st.cls
        rate_wait = st.bucket.try_take() if charge_rate else 0.0
        with self._lock:
            verdict = door_decision(rate_wait, st.live,
                                    cls.max_concurrent, st.waiting,
                                    cls.queue_depth)
            if verdict == SHED_RATE:
                st.shed_total += 1
                return _Ticket(False, cls, tenant,
                               retry_after=max(rate_wait, 0.05),
                               reason="rate_limited")
            if verdict == ADMIT:
                st.live += 1
                st.admitted_total += 1
                return _Ticket(True, cls, tenant)
            if verdict == SHED_QUEUE_FULL:
                st.shed_total += 1
                if charge_rate:
                    # the bucket granted a token but no work happened:
                    # refund it, or concurrency sheds drain the rate
                    # a tenant contracted for
                    st.bucket.refund()
                return _Ticket(False, cls, tenant,
                               retry_after=self._slot_eta(st),
                               reason="queue_full")
            # QUEUE: bounded FIFO admission queue — wait (timed) for a
            # slot; this blocking IS the SSE path's backpressure.  Only
            # the HEAD waiter may take a freed slot (release notifies
            # all: a woken non-head waiter just re-waits), so admission
            # order is arrival order within the class.
            me = object()
            st.queue.append(me)
            st.queued_total += 1
            deadline = self._clock() + wait_timeout
            try:
                while not (st.live < cls.max_concurrent
                           and st.queue[0] is me):
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        st.queue.remove(me)
                        # our departure may make the new head eligible
                        st.cond.notify_all()
                        st.shed_total += 1
                        if charge_rate:
                            st.bucket.refund()
                        return _Ticket(False, cls, tenant,
                                       retry_after=self._slot_eta(st),
                                       reason="queue_timeout")
                    st.cond.wait(remaining)
                st.queue.popleft()
                st.live += 1
                st.admitted_total += 1
                return _Ticket(True, cls, tenant)
            except BaseException:
                if me in st.queue:
                    st.queue.remove(me)
                    st.cond.notify_all()
                raise

    def _slot_eta(self, st: _ClassState) -> float:
        """Honest-ish Retry-After for a concurrency shed: with no
        completion-rate estimate, ~1s per queued-ahead requester is a
        bounded hint, never a promise — JITTERED through the shared
        helper so shed clients of one hot class do not re-arrive in a
        synchronized wave (ISSUE 16 satellite)."""
        return jittered_retry_after(1.0, load=st.waiting,
                                    rng=self._rng)

    # -- the event-driven door (the sim twin's admission) -----------------

    def offer(self, tenant: str = "default", *,
              charge_rate: bool = True) -> _Ticket:
        """Non-blocking :meth:`acquire`: the SAME :func:`door_decision`
        policy, but a would-queue arrival gets a WAITING ticket
        (``ok=False``, ``reason="queued"``, ``waiter`` set) instead of
        blocking this thread — the caller owns the wait (the digital
        twin's event loop models it in virtual time, calling
        :meth:`promote` when capacity frees and :meth:`abandon` on its
        own timeout).  Counters move exactly as the blocking path's
        do, so live and simulated stats stay comparable."""
        st = self.class_for(tenant)
        if st is None:
            return _Ticket(True, None, tenant)
        cls = st.cls
        rate_wait = st.bucket.try_take() if charge_rate else 0.0
        with self._lock:
            verdict = door_decision(rate_wait, st.live,
                                    cls.max_concurrent, st.waiting,
                                    cls.queue_depth)
            if verdict == SHED_RATE:
                st.shed_total += 1
                return _Ticket(False, cls, tenant,
                               retry_after=max(rate_wait, 0.05),
                               reason="rate_limited")
            if verdict == ADMIT:
                st.live += 1
                st.admitted_total += 1
                return _Ticket(True, cls, tenant)
            if verdict == SHED_QUEUE_FULL:
                st.shed_total += 1
                if charge_rate:
                    st.bucket.refund()
                return _Ticket(False, cls, tenant,
                               retry_after=self._slot_eta(st),
                               reason="queue_full")
            me = object()
            st.queue.append(me)
            st.queued_total += 1
            return _Ticket(False, cls, tenant, reason="queued",
                           waiter=me)

    def promote(self, ticket: _Ticket) -> bool:
        """Admit a queued :meth:`offer` ticket iff it is HEAD of its
        class queue and a slot is free — the same only-the-head rule
        the blocking path's Condition loop enforces.  True = the
        ticket is now ok/admitted (caller must release() it)."""
        if ticket.waiter is None or ticket.cls is None:
            return False
        st = self._classes.get(ticket.cls.name)
        if st is None:
            return False
        with self._lock:
            if (st.queue and st.queue[0] is ticket.waiter
                    and st.live < st.cls.max_concurrent):
                st.queue.popleft()
                st.live += 1
                st.admitted_total += 1
                ticket.ok = True
                ticket.reason = ""
                ticket.waiter = None
                return True
        return False

    def abandon(self, ticket: _Ticket, *,
                charge_rate: bool = True) -> None:
        """A queued :meth:`offer` ticket gave up (the caller's
        wait_timeout in virtual time): leave the queue with the same
        accounting as the blocking path's ``queue_timeout`` shed."""
        if ticket.waiter is None or ticket.cls is None:
            return
        st = self._classes.get(ticket.cls.name)
        if st is None:
            return
        with self._lock:
            if ticket.waiter in st.queue:
                st.queue.remove(ticket.waiter)
                # our departure may make the new head eligible
                st.cond.notify_all()
                st.shed_total += 1
                if charge_rate:
                    st.bucket.refund()
                ticket.retry_after = self._slot_eta(st)
        ticket.waiter = None
        ticket.reason = "queue_timeout"

    def release(self, ticket: _Ticket) -> None:
        if not ticket.ok or ticket.cls is None:
            return
        st = self._classes.get(ticket.cls.name)
        if st is None:
            return
        with self._lock:
            st.live = max(0, st.live - 1)
            # notify_all: only the HEAD waiter may take the slot, and
            # Condition wakes an arbitrary waiter — waking just one
            # could wake a non-head that re-waits while the head sleeps
            st.cond.notify_all()

    # -- routing -----------------------------------------------------------

    def prefix_keys(self, tokens) -> list[int]:
        """Prompt tokens (byte-token ids at the router, engine token
        ids at a replica) -> chained block-content keys."""
        from .paged import block_keys

        return block_keys(tokens, self.affinity_block)

    def route(self, keys: list[int], backends: list[str],
              load: Optional[Callable[[str], int]] = None,
              session: Optional[str] = None) -> tuple[str, int]:
        """(backend, affinity depth): the replica already holding the
        deepest prefix of this request, unless it is overloaded
        relative to its peers (> 2x the mean load + 1 — a hot shared
        prefix must not melt one replica); otherwise least-loaded
        (``load`` callable; index 0 on ties/no signal).  The choice is
        recorded so the NEXT same-prefix request finds it.

        ``session`` (ISSUE 12) outranks prefix affinity: a durable
        session's resume goes to the replica whose pool still holds
        its blocks — warm, no thaw.  No overload veto here: moving the
        resume elsewhere pays a storage thaw, strictly worse than a
        busy-but-alive replica.  A session whose replica died routes
        like any fresh request (the storage tier thaws anywhere)."""
        if not backends:
            raise ValueError("route needs at least one backend")
        if session:
            sticky = self.sessions.best(session, backends)
            if sticky is not None:
                self.affinity.observe(keys, sticky)
                self.sessions.observe(session, sticky)
                return sticky, 0
        choice, depth = self.affinity.best(keys, backends)
        if choice is not None and load is not None and len(backends) > 1:
            # overload check against the PEERS' mean: including the
            # chosen backend's own load in the mean made the guard
            # unsatisfiable at 2 replicas (L > L + other + 1)
            others = [load(b) for b in backends if b != choice]
            if others and load(choice) > 2 * (sum(others)
                                              / len(others)) + 1:
                choice, depth = None, 0  # overloaded: fall through
        if choice is None:
            if load is not None:
                choice = min(backends, key=lambda b: (load(b),
                                                      backends.index(b)))
            else:
                choice = backends[0]
        self.affinity.observe(keys, choice)
        if session:
            self.sessions.observe(session, choice)
        return choice, depth

    # -- preemption --------------------------------------------------------

    def attach_engine(self, engine, **kw) -> "EnginePreemptor":
        """Start a priority preemptor over ``engine`` (paged pools
        only — eviction is only cheap because sequences are
        exportable).  Idempotent per engine; ``kw`` tunes
        ``preempt_after_s``/``poll_s`` on first attach."""
        for p in self.preemptors:
            if p.engine is engine:
                return p
        p = EnginePreemptor(engine, **kw)
        self.preemptors.append(p)
        return p

    def stop(self) -> None:
        for p in self.preemptors:
            p.stop()
        self.preemptors = []

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """Scalar gauges plus a ``classes`` dict (class name -> its
        counters).  Class names are tenant/Profile names — arbitrary
        strings — so exporters must render them as a ``class`` LABEL,
        never splice them into the metric name (a hyphenated tenant
        would produce an invalid Prometheus name and poison the whole
        exposition)."""
        out: dict[str, Any] = {
            "qos_affinity_hits_total": self.affinity.hits_total,
            "qos_affinity_misses_total": self.affinity.misses_total,
            "session_affinity_hits_total": self.sessions.hits_total,
            "session_affinity_misses_total": self.sessions.misses_total,
        }
        with self._lock:
            out["classes"] = {
                name: {
                    "qos_admitted_total": st.admitted_total,
                    "qos_shed_total": st.shed_total,
                    "qos_queued_total": st.queued_total,
                    "qos_live": st.live,
                    "qos_waiting": st.waiting,
                }
                for name, st in self._classes.items()
            }
        if self.preemptors:
            out["qos_preemptions_total"] = sum(
                p.preemptions_total for p in self.preemptors)
            out["qos_preempt_resumes_total"] = sum(
                p.resumes_total for p in self.preemptors)
            out["qos_preempted_parked"] = sum(
                p.parked() for p in self.preemptors)
        return out


def blocks_needed(prompt_len: int, max_new_tokens: int,
                  block_size: int) -> int:
    """Worst-case block span a request may occupy (ceil division) —
    the capacity question both the live preemptor and the sim twin's
    modeled pool ask."""
    return -(-(int(prompt_len) + int(max_new_tokens))
             // int(block_size))


def best_pending(waiting, now: float, preempt_after_s: float,
                 policy: Optional[Callable] = None):
    """(tier, req) of the best-tier submitted-but-unadmitted request
    that has waited past the preemption threshold AND whose wait
    eviction could actually end, else (None, None).  Pure given its
    inputs (``now`` is the caller's injected clock) — the ISSUE 20
    extraction of ``EnginePreemptor._pending_best``.

    A request deferred by the engine's ``admission_policy`` (the tier
    ladder's class quota, say) is blocked by POLICY, not capacity:
    evicting a victim frees nothing it may use, and the freed slot
    would be re-consumed by other traffic — serial eviction churn of
    healthy streams.  The probe requires the policy to be read-only
    host logic; a raising policy skips the demand rather than
    evicting on a guess."""
    best: Optional[int] = None
    best_req = None
    for req in waiting:
        if req.done.is_set():
            continue
        if now - req.submitted_at < preempt_after_s:
            continue
        if policy is not None:
            try:
                if not policy(req):
                    continue  # policy-deferred, not capacity-blocked
            except Exception:  # noqa: BLE001 — never evict on a guess
                continue
        tier = getattr(req, "priority", 1)
        if best is None or tier < best:
            best, best_req = tier, req
    return best, best_req


def choose_victim(slots, better_than: int, frozen=()):
    """The live victim with the WORST tier strictly greater than
    ``better_than`` (ties: fewest generated tokens — the cheapest
    snapshot), or None.  ``slots`` is ``(slot_index, req)`` pairs;
    ``frozen`` slots (mid-migration/resize) are never victims —
    another orchestrator owns their cutover, and evicting one here
    would fork ownership (two snapshots, one handle, double-decode on
    whichever side wins).  Pure — the ISSUE 20 extraction of
    ``EnginePreemptor._live_worst``, shared with the sim twin's
    modeled preemption."""
    frozen = set(frozen)
    worst = None
    key = None
    for slot, req in slots:
        if req is None or req.done.is_set():
            continue
        if slot in frozen:
            continue
        tier = getattr(req, "priority", 1)
        if tier <= better_than:
            continue
        k = (-tier, len(req.tokens))
        if key is None or k < key:
            worst, key = req, k
    return worst


class EnginePreemptor:
    """Evict-and-requeue for priority inversion on a full paged pool.

    A worker thread watches the engine: when a request of tier T waits
    unadmitted past ``preempt_after_s`` while a live sequence of a
    WORSE tier occupies the pool, the worst such victim is exported
    (PR 7 snapshot — the parity suite's guarantee that resumed tokens
    are bit-identical), released (slot + blocks free instantly), and
    PARKED.  The engine's priority-sorted waiting list then admits the
    high request first.  Parked sequences re-import — same Request
    handle, so streams just resume — as soon as no better-tier demand
    waits and the pool has their span again; import-side exhaustion is
    retried, never fatal.  All of this runs on the preemptor thread:
    export/import are the engine's own mailbox ops, so the scheduler
    thread never blocks here (the analyzer's ``*Preemptor`` root walk
    keeps it that way).
    """

    def __init__(self, engine, preempt_after_s: float = 0.05,
                 poll_s: float = 0.01,
                 clock: Callable[[], float] = time.perf_counter):
        if not getattr(engine, "paged", False):
            raise ValueError(
                "priority preemption requires the paged pool "
                "(block_size > 0) — eviction is only cheap when the "
                "sequence is exportable")
        self.engine = engine
        self.preempt_after_s = float(preempt_after_s)
        self.poll_s = float(poll_s)
        self._clock = clock
        #: parked snapshots: (tier, parked_at, req, snapshot)
        self._parked: list[tuple[int, float, Any, dict]] = []
        self._lock = threading.Lock()
        self.preemptions_total = 0
        self.resumes_total = 0
        self._stopping = threading.Event()
        self._thread = threading.Thread(
            target=self._loop_preempt, name="qos-preemptor", daemon=True)
        self._thread.start()

    # -- demand / victim observation (reads scheduler-owned state the
    # same way migrate_live_sequences does: list() copies under the GIL,
    # decisions double-checked by the mailbox ops themselves) ----------

    def _pending_best(self):
        """Delegates to the pure :func:`best_pending` policy with the
        injected clock and this engine's admission-policy probe."""
        return best_pending(
            list(self.engine._waiting), self._clock(),
            self.preempt_after_s,
            policy=getattr(self.engine, "admission_policy", None))

    def _capacity_blocked(self, req) -> bool:
        """True when ``req`` genuinely cannot admit — no free slot, or
        the block pool cannot host its worst-case span.  Without this
        gate the preemptor would evict a victim every poll while the
        scheduler is merely one cycle away from admitting naturally."""
        eng = self.engine
        if not any(r is None for r in list(eng._slots)):
            return True
        need = blocks_needed(len(req.prompt), req.max_new_tokens,
                             eng.block_size)
        return eng._alloc.free_blocks < need

    def _live_worst(self, better_than: int):
        """Delegates to the pure :func:`choose_victim` policy over a
        snapshot of the slot table (list() copies under the GIL; the
        mailbox ops double-check the decision)."""
        return choose_victim(
            enumerate(list(self.engine._slots)), better_than,
            frozen=set(self.engine._migrating))

    # -- the loop ----------------------------------------------------------

    def _loop_preempt(self) -> None:
        # idle backoff: a QoS-enabled but quiet deployment must not
        # burn a 100 Hz poll per engine forever.  Doubling up to the
        # preemption threshold adds at most ~one threshold of extra
        # detection latency — which the demand must wait out anyway —
        # while any action resets to the tight cadence.
        idle_cap = max(self.poll_s, self.preempt_after_s, 0.05)
        wait = self.poll_s
        while not self._stopping.is_set():
            try:
                acted = self._step()
            except Exception as e:  # noqa: BLE001 — a dead engine must
                # not kill the preemptor silently; parked requests are
                # failed by stop()/engine shutdown, new work just waits
                log.debug("preemptor step failed: %s", e)
                acted = False
            if acted:
                wait = self.poll_s
            else:
                busy = bool(self._parked) or bool(self.engine._waiting)
                wait = self.poll_s if busy else min(wait * 2, idle_cap)
                self._stopping.wait(wait)

    def _step(self) -> bool:
        demand, demand_req = self._pending_best()
        if demand is not None and self._capacity_blocked(demand_req):
            victim = self._live_worst(demand)
            if victim is not None:
                return self._preempt(victim)
        return self._try_resume(demand)

    def _preempt(self, victim) -> bool:
        try:
            snap = self.engine.export_sequence(victim)
        except (RuntimeError, TimeoutError) as e:
            log.debug("preempt export failed: %s", e)
            try:
                self.engine.resume_sequence(victim)
            except (RuntimeError, TimeoutError):
                pass
            return False
        if snap is None:
            return False  # finished first — the slot is already free
        self.engine.release_sequence(victim)
        tier = getattr(victim, "priority", 1)
        tr = getattr(victim, "trace", None)
        if tr is not None:
            # parked time is its own phase (a stall CAUSE the trace
            # attributes): ends when the re-import activates the slot
            tr.begin("preempt.park", tier=tier).done()
            tr.phase("engine.preempted", tier=tier)
            tr.meta["stall"] = "preempted"
        with self._lock:
            self._parked.append((tier, self._clock(), victim, snap))
        self.preemptions_total += 1
        log.debug("preempted tier-%d sequence (%d tokens generated) "
                  "for higher-priority demand", tier, len(victim.tokens))
        return True

    def _try_resume(self, pending_tier: Optional[int]) -> bool:
        with self._lock:
            if not self._parked:
                return False
            # best tier first, then FIFO — the inverse of eviction order
            self._parked.sort(key=lambda p: (p[0], p[1]))
            candidates = list(self._parked)
        for entry in candidates:
            tier, _t, req, snap = entry
            if req.done.is_set() or req.cancelled.is_set():
                with self._lock:  # client gave up while parked
                    if entry in self._parked:
                        self._parked.remove(entry)
                if not req.done.is_set():
                    req.done.set()
                continue
            if pending_tier is not None and pending_tier <= tier:
                return False  # better demand still waiting: stay parked
            try:
                self.engine.import_sequence(snap, req=req)
            except RuntimeError:
                return False  # pool still full: retry next poll
            except TimeoutError:
                return False
            with self._lock:
                if entry in self._parked:
                    self._parked.remove(entry)
            tr = getattr(req, "trace", None)
            if tr is not None:
                tr.begin("preempt.unpark", tier=tier).done()
            self.resumes_total += 1
            return True
        return False

    def parked(self) -> int:
        with self._lock:
            return len(self._parked)

    def stats(self) -> dict:
        return {
            "qos_preemptions_total": self.preemptions_total,
            "qos_preempt_resumes_total": self.resumes_total,
            "qos_preempted_parked": self.parked(),
        }

    def stop(self, fail_parked: bool = True) -> None:
        self._stopping.set()
        self._thread.join(timeout=5)
        if not fail_parked:
            return
        with self._lock:
            parked, self._parked = self._parked, []
        for _tier, _t, req, _snap in parked:
            if not req.done.is_set():
                req.error = RuntimeError(
                    "preempted sequence abandoned at shutdown")
                req.done.set()
