"""Model lifecycle: the kserve.Model analog.

[upstream: kserve/kserve -> python/kserve/kserve/model.py]: a model has
``load() -> ready``, ``preprocess -> predict -> postprocess``, and is hosted
by a ModelServer speaking the V1/V2 inference protocols.  TPU-first
divergence: ``predict`` receives a *batch* (the server micro-batches
concurrent requests before dispatch — XLA-compiled callables want large
batches, and per-request dispatch would waste the MXU).
"""

from __future__ import annotations

import time
from typing import Any, Optional

Instances = list[Any]


class Model:
    """Base model. Subclass and override load() and predict_batch()."""

    def __init__(self, name: str, config: Optional[dict[str, Any]] = None):
        self.name = name
        self.config = dict(config or {})
        self.ready = False
        self.load_time_s: Optional[float] = None

    # -- lifecycle --------------------------------------------------------

    def load(self) -> None:
        """Load weights / compile; must set self.ready = True."""
        self.ready = True

    def start(self) -> None:
        t0 = time.perf_counter()
        self.load()
        self.load_time_s = time.perf_counter() - t0
        if not self.ready:
            raise RuntimeError(f"model {self.name}: load() did not set ready")

    def stop(self) -> None:
        self.ready = False

    # -- inference --------------------------------------------------------

    def preprocess(self, instances: Instances) -> Instances:
        return instances

    def predict_batch(self, instances: Instances) -> Instances:
        raise NotImplementedError

    def postprocess(self, predictions: Instances) -> Instances:
        return predictions

    def explain_batch(self, instances: Instances) -> Instances:
        """Per-instance explanations (the ``:explain`` verb); only
        explainer components implement this."""
        raise NotImplementedError(f"model {self.name} does not explain")

    def __call__(self, instances: Instances) -> Instances:
        return self.postprocess(self.predict_batch(self.preprocess(instances)))

    # -- metadata (V2 model metadata endpoint) ----------------------------

    def metadata(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "platform": "kubeflow-tpu-jax",
            "ready": self.ready,
            "load_time_s": self.load_time_s,
        }
