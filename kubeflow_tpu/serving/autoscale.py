"""Predictive cluster autoscaler (ISSUE 15): sense -> decide -> actuate.

Every fleet-scope actuator already exists — lossless replica drain via
``migrate_live_sequences`` (PR 7), elastic TP resize via ``GangResizer``
(PR 9), prefill/decode role pools (PR 7), session hibernation (PR 11) —
and PR 12 landed the sensor layer (``TraceSink.summary()`` per-class
queue-wait aggregates, plane shed counters, ``ClusterPrefixPoller``
prefix heat).  This module closes the loop: a short-horizon predictor
(EWMA + slope over a sliding window; the CONTRACT is the decision
interface, not the estimator) feeds one pure decision function that
emits at most one action per tick, and a per-actuator state machine
enforces hysteresis, cooldowns and bounded-retry backoff so a failing
actuator can never turn the loop into a resize storm.

Thread contract (the ``*Autoscaler``/``*Scaler``/``*Reaper`` analyzer
roots pin it): the decision loop is a WORKER thread — same shape as
``EnginePreemptor``.  Sensor reads are GIL ``list()``/dict copies or
the engines' public ``stats()``; every engine mutation goes through the
existing mailbox/drain APIs (``migrate_live_sequences``,
``hibernate_sequence``, ``GangResizer.resize``, the controller's
replica scaling) — never a direct pool write.  Actuators run on the
tick caller's thread (the controller's reconcile worker, or the
``start()`` thread), so a slow drain stalls this loop, never an engine
scheduler.

Decision priority (first match wins; everything below the matched rule
is NOT considered this tick — one action per tick is the anti-flap
floor):

1. ``wake``            — scaled to zero with demand pending
2. ``scale_up``        — EMERGENCY surge (ISSUE 16): more than
                         ``emergency_unhealthy_frac`` of the pool's
                         health circuits open at once — bounded surge
                         that may also bypass the placement cooldown,
                         at most once per ``emergency_window_s``
3. ``scale_up``        — shed rate / queue wait / free-block famine
                         (SLO pressure outranks the utilization bands)
4. ``scale_up``        — forecast utilization above the high band
5. ``resize_up``       — same deficit but replicas are at max: the
                         bottleneck is per-replica throughput, so the
                         TP degree grows instead (Tenplex: parallelism
                         degree is a runtime variable)
6. ``scale_to_zero``   — idle past the zero clock with a measured
                         cold-start budget that fits
7. ``scale_down``      — forecast AND current utilization below the
                         low band (both: a forecast dip alone must not
                         shed capacity)
8. ``resize_down``     — still below the low band at the replica floor
                         with a lower configured degree available
9. ``tier_rebalance``  — prefill/decode pressure imbalance beyond the
                         band (Podracer: chips are fungible across
                         roles)
10. ``none``           — inside the hysteresis band
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

log = logging.getLogger("kubeflow_tpu.serving.autoscale")

#: every action ``decide`` can emit
ACTIONS = ("none", "wake", "scale_up", "scale_down", "resize_up",
           "resize_down", "tier_rebalance", "scale_to_zero")

#: actuator channels — each owns a cooldown + retry budget.  Several
#: actions share a channel on purpose: wake and scale_up both place a
#: replica, so they share the placement cooldown.
ACTUATOR_OF = {
    "wake": "replica_up", "scale_up": "replica_up",
    "scale_down": "replica_down", "scale_to_zero": "zero",
    "resize_up": "resize", "resize_down": "resize",
    "tier_rebalance": "tier",
}

_POLICY_KEYS = frozenset({
    "target_concurrency", "window_s", "horizon_s", "high_band",
    "low_band", "shed_hot", "queue_wait_hot_s", "free_block_low",
    "scale_to_zero", "idle_zero_s", "cold_start_budget_s",
    "tp_degrees", "tier_band", "up_cooldown_s", "down_cooldown_s",
    "resize_cooldown_s", "tier_cooldown_s", "zero_cooldown_s",
    "max_retries", "backoff_s", "backoff_cap_s", "loop_s",
    "emergency_unhealthy_frac", "emergency_surge",
    "emergency_window_s", "thaw_concurrency",
})


def validate_autoscale(spec) -> dict:
    """Validate an ISvc ``autoscale:`` knob dict (the ONE validator —
    the controller wraps errors into its conf-freeze ``invalid engine
    knobs`` message, the same contract as ``validate_qos`` /
    ``validate_tracing``).  Returns the normalized dict."""
    if not isinstance(spec, dict):
        raise ValueError("autoscale must be a mapping of knobs")
    unknown = set(spec) - _POLICY_KEYS
    if unknown:
        raise ValueError(f"autoscale keys {sorted(unknown)} unknown")
    out = dict(spec)

    def _pos(key: str, *, zero_ok: bool = False) -> None:
        if key not in out:
            return
        try:
            v = float(out[key])
            ok = v >= 0 if zero_ok else v > 0
        except (TypeError, ValueError):
            ok = False
        if not ok or not math.isfinite(float(out[key])):
            raise ValueError(
                f"autoscale.{key} {out[key]!r} must be a "
                + ("non-negative" if zero_ok else "positive") + " number")

    for k in ("target_concurrency", "window_s", "idle_zero_s",
              "cold_start_budget_s", "up_cooldown_s", "down_cooldown_s",
              "resize_cooldown_s", "tier_cooldown_s", "zero_cooldown_s",
              "backoff_s", "backoff_cap_s", "loop_s"):
        _pos(k)
    for k in ("horizon_s", "shed_hot", "queue_wait_hot_s"):
        _pos(k, zero_ok=True)
    if "free_block_low" in out:
        try:
            ok = 0.0 <= float(out["free_block_low"]) < 1.0
        except (TypeError, ValueError):
            ok = False
        if not ok:
            raise ValueError(
                f"autoscale.free_block_low {out['free_block_low']!r} "
                "must be in [0, 1)")
    if "tier_band" in out:
        try:
            ok = float(out["tier_band"]) >= 0.0
        except (TypeError, ValueError):
            ok = False
        if not ok:
            raise ValueError(
                f"autoscale.tier_band {out['tier_band']!r} must be >= 0")
    hi = float(out.get("high_band", 1.25))
    lo = float(out.get("low_band", 0.5))
    if not (0.0 <= lo < hi):
        raise ValueError(
            f"autoscale bands must satisfy 0 <= low_band < high_band "
            f"(got low={lo}, high={hi}) — the gap IS the hysteresis")
    if "max_retries" in out and int(out["max_retries"]) < 1:
        raise ValueError(
            f"autoscale.max_retries {out['max_retries']!r} must be >= 1")
    degrees = out.get("tp_degrees")
    if degrees is not None:
        if (not isinstance(degrees, (list, tuple))
                or not all(isinstance(d, int) and d >= 1 for d in degrees)
                or list(degrees) != sorted(set(degrees))):
            raise ValueError(
                "autoscale.tp_degrees must be a strictly increasing "
                "list of ints >= 1")
    if "scale_to_zero" in out and not isinstance(out["scale_to_zero"], bool):
        raise ValueError("autoscale.scale_to_zero must be a bool")
    _pos("emergency_window_s")
    if "emergency_unhealthy_frac" in out:
        try:
            ok = 0.0 < float(out["emergency_unhealthy_frac"]) <= 1.0
        except (TypeError, ValueError):
            ok = False
        if not ok:
            raise ValueError(
                "autoscale.emergency_unhealthy_frac "
                f"{out['emergency_unhealthy_frac']!r} must be in (0, 1]")
    if "emergency_surge" in out:
        v = out["emergency_surge"]
        if isinstance(v, bool) or not isinstance(v, int) or v < 1:
            raise ValueError(
                f"autoscale.emergency_surge {v!r} must be an int >= 1")
    if "thaw_concurrency" in out:
        v = out["thaw_concurrency"]
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            raise ValueError(
                f"autoscale.thaw_concurrency {v!r} must be an int >= 0 "
                "(0 = uncapped)")
    return out


@dataclass(frozen=True)
class AutoscalePolicy:
    """Frozen knob set for one autoscaler instance (conf-freeze: built
    once per revision fingerprint, like the traffic plane)."""

    #: per-replica inflight the fleet is sized for; utilization =
    #: inflight / (replicas * target_concurrency)
    target_concurrency: float = 4.0
    #: sensor sliding window feeding the predictor
    window_s: float = 30.0
    #: forecast horizon — 0 disables the slope term (pure EWMA)
    horizon_s: float = 5.0
    #: hysteresis band on forecast utilization: above high -> grow,
    #: below low -> shrink, inside -> hold
    high_band: float = 1.25
    low_band: float = 0.5
    #: sheds/s (worst class) beyond which scale-up fires regardless of
    #: utilization — a shed IS an SLO miss already happening
    shed_hot: float = 0.0
    #: mean queue wait (worst class, seconds) beyond which scale-up fires
    queue_wait_hot_s: float = 1.0
    #: min free-block ratio across engines below which scale-up fires
    #: (KV famine strands admissions even at modest concurrency)
    free_block_low: float = 0.08
    scale_to_zero: bool = False
    #: idle seconds before scale-to-zero considers firing
    idle_zero_s: float = 60.0
    #: measured cold start must fit this budget for zero to be safe
    cold_start_budget_s: float = 30.0
    #: allowed TP degrees, strictly increasing; empty = resize actuator off
    tp_degrees: Tuple[int, ...] = ()
    #: relative prefill/decode pressure gap tolerated before rebalance
    tier_band: float = 0.5
    up_cooldown_s: float = 2.0
    down_cooldown_s: float = 10.0
    resize_cooldown_s: float = 30.0
    tier_cooldown_s: float = 30.0
    zero_cooldown_s: float = 60.0
    #: consecutive actuator failures tolerated before the channel parks
    max_retries: int = 3
    backoff_s: float = 1.0
    backoff_cap_s: float = 30.0
    #: threaded-mode tick interval
    loop_s: float = 1.0
    #: correlated-failure emergency mode (ISSUE 16): when more than
    #: this fraction of the router's backends have non-closed health
    #: circuits, ``decide`` fires a bounded surge scale-out that
    #: outranks the utilization bands, and ``tick`` may bypass the
    #: placement cooldown/backoff at most once per
    #: ``emergency_window_s``
    emergency_unhealthy_frac: float = 0.5
    #: replicas added per emergency surge decision (capped at max)
    emergency_surge: int = 1
    emergency_window_s: float = 30.0
    #: mass-recovery thaw cap: max concurrent ``thaw_sequence`` calls
    #: per deployment (0 = uncapped, the pre-PR behavior)
    thaw_concurrency: int = 0

    @classmethod
    def from_config(cls, spec: Optional[dict]) -> "AutoscalePolicy":
        if not spec:
            return cls()
        out = validate_autoscale(spec)
        kw: Dict[str, Any] = {}
        for k, v in out.items():
            if k == "tp_degrees":
                kw[k] = tuple(int(d) for d in v)
            elif k in ("max_retries", "emergency_surge",
                       "thaw_concurrency"):
                kw[k] = int(v)
            elif k == "scale_to_zero":
                kw[k] = bool(v)
            else:
                kw[k] = float(v)
        return cls(**kw)


class TrendPredictor:
    """EWMA level + least-squares slope over a sliding time window.

    Pure host arithmetic (table-tested): ``observe(t, v)`` retires
    samples older than ``window_s``, ``forecast(h)`` extrapolates
    ``level + slope * h``.  The estimator is deliberately boring — the
    decision interface is the contract, and a fancier model slots in
    behind the same three reads."""

    def __init__(self, window_s: float = 30.0, alpha: float = 0.3):
        self.window_s = float(window_s)
        self.alpha = float(alpha)
        self._samples: deque = deque()
        self._level: Optional[float] = None

    def observe(self, t: float, v: float) -> None:
        v = float(v)
        self._samples.append((float(t), v))
        while self._samples and t - self._samples[0][0] > self.window_s:
            self._samples.popleft()
        self._level = (v if self._level is None
                       else self.alpha * v + (1 - self.alpha) * self._level)

    @property
    def n(self) -> int:
        return len(self._samples)

    @property
    def level(self) -> float:
        return 0.0 if self._level is None else self._level

    @property
    def slope(self) -> float:
        """Least-squares d(value)/dt over the retained window; 0 until
        two samples span a non-zero interval."""
        if len(self._samples) < 2:
            return 0.0
        ts = [s[0] for s in self._samples]
        vs = [s[1] for s in self._samples]
        tm = sum(ts) / len(ts)
        vm = sum(vs) / len(vs)
        den = sum((t - tm) ** 2 for t in ts)
        if den <= 0.0:
            return 0.0
        return sum((t - tm) * (v - vm) for t, v in zip(ts, vs)) / den

    def forecast(self, horizon_s: float) -> float:
        return self.level + self.slope * float(horizon_s)


class ConcurrencyGate:
    """Bounded-concurrency context manager for the mass-recovery
    stampede paths (ISSUE 16): cold-start pre-warm and
    hibernated-session thaw both arrive in herds after a domain
    outage — the gate admits ``limit`` at a time and makes the rest
    WAIT (refusal would just re-herd the retries).  Plain bounded
    semaphore plus counters; safe to share across threads."""

    def __init__(self, limit: int = 1):
        self.limit = max(1, int(limit))
        self._sem = threading.BoundedSemaphore(self.limit)
        self._lock = threading.Lock()
        self.entries_total = 0
        self.waits_total = 0

    def __enter__(self) -> "ConcurrencyGate":
        if not self._sem.acquire(blocking=False):
            with self._lock:
                self.waits_total += 1
            self._sem.acquire()
        with self._lock:
            self.entries_total += 1
        return self

    def __exit__(self, *exc) -> None:
        self._sem.release()

    def stats(self) -> dict:
        return {"gate_limit": self.limit,
                "gate_entries_total": self.entries_total,
                "gate_waits_total": self.waits_total}


class ActuatorState:
    """Cooldown + bounded-retry backoff for ONE actuator channel.

    ``ready`` gates firing; ``note_failed`` backs off exponentially and
    PARKS the channel after ``max_retries`` consecutive failures — a
    parked channel never fires again until ``reset()`` (the loop resets
    it when the demanded action changes or the band clears, i.e. when
    the world moved on).  This is the no-flap contract the chaos sweep
    pins: a dead actuator costs at most ``max_retries`` attempts per
    demand episode."""

    def __init__(self, name: str, cooldown_s: float, *,
                 max_retries: int = 3, backoff_s: float = 1.0,
                 backoff_cap_s: float = 30.0):
        self.name = name
        self.cooldown_s = float(cooldown_s)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.last_fired = -math.inf
        self.failures = 0
        self.blocked_until = -math.inf
        self.parked = False

    def ready(self, now: float) -> bool:
        return (not self.parked
                and now >= self.blocked_until
                and now - self.last_fired >= self.cooldown_s)

    def note_fired(self, now: float) -> None:
        self.last_fired = now

    def note_ok(self) -> None:
        self.failures = 0
        self.blocked_until = -math.inf

    def note_failed(self, now: float) -> None:
        self.failures += 1
        if self.failures >= self.max_retries:
            self.parked = True
        else:
            self.blocked_until = now + min(
                self.backoff_cap_s,
                self.backoff_s * (2.0 ** (self.failures - 1)))

    def reset(self) -> None:
        self.failures = 0
        self.parked = False
        self.blocked_until = -math.inf


@dataclass(frozen=True)
class Decision:
    """One tick's verdict — at most one action, with the actuator
    payload it needs (target replica count / TP degree / prefill tier
    size)."""

    action: str
    reason: str = ""
    replicas: Optional[int] = None
    degree: Optional[int] = None
    prefill: Optional[int] = None

    @property
    def actuator(self) -> Optional[str]:
        return ACTUATOR_OF.get(self.action)


def _sig(sig: Mapping, key: str, default: float) -> float:
    v = sig.get(key, default)
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def decide(sig: Mapping, policy: AutoscalePolicy) -> Decision:
    """The pure decision function: one sensor snapshot -> exactly one
    :class:`Decision` (possibly ``none``).  No clocks, no side effects —
    the table-driven tests enumerate it row by row.

    Expected ``sig`` keys (missing keys take neutral defaults, so a
    partially-wired deployment degrades to the utilization bands):
    ``replicas``, ``min_replicas``, ``max_replicas``, ``util``,
    ``util_forecast``, ``shed_rate``, ``queue_wait_s``,
    ``free_block_ratio``, ``idle_s``, ``live``, ``pending``,
    ``cold_start_s``, ``degree``, ``prefill_pressure``,
    ``decode_pressure``, ``prefill_replicas``, ``decode_replicas``,
    ``unhealthy_frac``.
    """
    n = int(_sig(sig, "replicas", 0))
    lo_n = max(int(_sig(sig, "min_replicas", 0)), 0)
    hi_n = max(int(_sig(sig, "max_replicas", max(n, 1))), 1)
    floor = max(lo_n, 1) if not policy.scale_to_zero else lo_n
    util = _sig(sig, "util", 0.0)
    fc = _sig(sig, "util_forecast", util)
    pending = _sig(sig, "pending", 0.0)

    # 1. wake: scaled to zero with demand at the door
    if n == 0:
        if pending > 0 or util > 0:
            return Decision("wake", "demand while scaled to zero",
                            replicas=max(floor, 1))
        return Decision("none", "scaled to zero, no demand")

    # 2. emergency surge (ISSUE 16): a correlated failure — more than
    # emergency_unhealthy_frac of the pool's health circuits open at
    # once — outranks every band and even the SLO rules: the fleet is
    # not merely hot, it is GONE.  Surge is bounded (emergency_surge
    # replicas, never past max) and the reason prefix is the contract
    # ``tick`` keys its cooldown bypass off.
    bad = _sig(sig, "unhealthy_frac", 0.0)
    if bad > policy.emergency_unhealthy_frac and n < hi_n:
        return Decision(
            "scale_up",
            f"emergency: {bad:.0%} of backends unhealthy",
            replicas=min(hi_n,
                         n + max(int(policy.emergency_surge), 1)))

    # 3. SLO pressure outranks the utilization bands: a shed or a long
    # queue wait is a miss already happening, not a forecast
    shed = _sig(sig, "shed_rate", 0.0)
    qwait = _sig(sig, "queue_wait_s", 0.0)
    free = _sig(sig, "free_block_ratio", 1.0)
    if n < hi_n:
        if shed > policy.shed_hot:
            return Decision("scale_up", f"shed rate {shed:.3g}/s",
                            replicas=n + 1)
        if qwait > policy.queue_wait_hot_s:
            return Decision("scale_up", f"queue wait {qwait:.3g}s",
                            replicas=n + 1)
        if free < policy.free_block_low:
            return Decision("scale_up",
                            f"free-block ratio {free:.3g}",
                            replicas=n + 1)

    # 4/5. the high band: forecast says the fleet will run hot.  With
    # replica headroom, add concurrency; at max replicas the deficit is
    # per-replica throughput — grow the TP degree instead.
    if fc > policy.high_band:
        if n < hi_n:
            return Decision("scale_up",
                            f"forecast util {fc:.3g} > {policy.high_band}",
                            replicas=n + 1)
        degree = int(_sig(sig, "degree", 0))
        bigger = [d for d in policy.tp_degrees if d > degree]
        if degree and bigger:
            return Decision("resize_up",
                            f"at max replicas, forecast util {fc:.3g}",
                            degree=bigger[0])

    # 6. scale-to-zero: idle past the clock, nothing live, and the
    # measured cold start fits the budget (an unmeasured cold start
    # counts as 0 — the first zero is how the budget gets measured,
    # and the activator path bounds the damage)
    idle = _sig(sig, "idle_s", 0.0)
    live = _sig(sig, "live", 0.0)
    if (policy.scale_to_zero and lo_n == 0 and idle > policy.idle_zero_s
            and live <= 0
            and _sig(sig, "cold_start_s", 0.0)
            <= policy.cold_start_budget_s):
        return Decision("scale_to_zero", f"idle {idle:.3g}s", replicas=0)

    # 7/8. the low band: BOTH current and forecast utilization must sit
    # below it (a dip in the forecast alone must not shed capacity —
    # that asymmetry is deliberate: adding capacity early is cheap,
    # removing it early sheds SLO)
    # the last replica retires ONLY through scale_to_zero above — its
    # gates (nothing live, cold start fits the budget) are the whole
    # point; a band-driven step 1 -> 0 would skip hibernation
    if fc < policy.low_band and util < policy.low_band:
        if n > max(floor, 1):
            return Decision("scale_down",
                            f"util {util:.3g} below {policy.low_band}",
                            replicas=n - 1)
        degree = int(_sig(sig, "degree", 0))
        smaller = [d for d in policy.tp_degrees if 0 < d < degree]
        if degree and smaller:
            return Decision("resize_down",
                            f"at replica floor, util {util:.3g}",
                            degree=smaller[-1])

    # 9. tier rebalance: prefill vs decode pressure imbalance beyond the
    # band, with a spare engine on the fat side
    pp = _sig(sig, "prefill_pressure", 0.0)
    dp = _sig(sig, "decode_pressure", 0.0)
    pn = int(_sig(sig, "prefill_replicas", 0))
    dn = int(_sig(sig, "decode_replicas", 0))
    if pn >= 1 and dn >= 1:
        if pp > (1.0 + policy.tier_band) * max(dp, 1e-9) and dn > 1:
            return Decision("tier_rebalance",
                            f"prefill pressure {pp:.3g} vs decode "
                            f"{dp:.3g}", prefill=pn + 1)
        if dp > (1.0 + policy.tier_band) * max(pp, 1e-9) and pn > 1:
            return Decision("tier_rebalance",
                            f"decode pressure {dp:.3g} vs prefill "
                            f"{pp:.3g}", prefill=pn - 1)

    return Decision("none", "inside the hysteresis band")


class ClusterAutoscaler:
    """The sense -> decide -> actuate loop.

    ``sensors`` is a callable returning the signal mapping ``decide``
    consumes (raw values; this loop adds the predictor-derived
    ``util_forecast`` before deciding).  ``actuators`` maps channel
    names (``replica_up``/``replica_down``/``resize``/``tier``/``zero``)
    to callables taking the :class:`Decision`; a missing channel means
    the deployment has no such actuator and the decision is recorded
    but not fired.  ``failpoint`` is the chaos hook
    (``FaultPlan.autoscale_failpoint()``): called with the channel name
    right before the actuator runs, raising to simulate a failed
    resize / failed drain / unreachable replica.

    Worker-thread discipline: ``tick`` runs on the caller's thread (the
    controller's reconcile worker or the ``start()`` loop) and touches
    engines only through their public cross-thread APIs.
    """

    def __init__(self, policy: AutoscalePolicy, *,
                 sensors: Callable[[], Mapping],
                 actuators: Optional[Mapping[str, Callable]] = None,
                 failpoint: Optional[Callable[[str], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy
        self.sensors = sensors
        self.actuators: Dict[str, Callable] = dict(actuators or {})
        self.failpoint = failpoint
        self.clock = clock
        cooldown = {
            "replica_up": policy.up_cooldown_s,
            "replica_down": policy.down_cooldown_s,
            "resize": policy.resize_cooldown_s,
            "tier": policy.tier_cooldown_s,
            "zero": policy.zero_cooldown_s,
        }
        self.states: Dict[str, ActuatorState] = {
            name: ActuatorState(
                name, cd, max_retries=policy.max_retries,
                backoff_s=policy.backoff_s,
                backoff_cap_s=policy.backoff_cap_s)
            for name, cd in cooldown.items()
        }
        self._util = TrendPredictor(policy.window_s)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._last_demand: Optional[str] = None
        #: bounded decision history (action, ok) for flap inspection
        self.history: deque = deque(maxlen=256)
        self.decisions_total: Dict[str, int] = {a: 0 for a in ACTIONS}
        self.actuator_failures_total = 0
        self.actuator_skips_total = 0
        self.sensor_errors_total = 0
        self.ticks_total = 0
        #: emergency cooldown bypass (ISSUE 16): at most one per
        #: ``emergency_window_s`` — the clock that bounds it
        self._last_emergency_bypass = float("-inf")
        self.emergency_bypass_total = 0
        #: EWMA of measured cold starts (scale-up fire -> replica ready)
        self.cold_start_s = 0.0
        self._cold_n = 0
        #: the warm-path EWMA (ISSUE 17): cold starts whose warmup HIT
        #: the AOT artifact cache, tracked separately — one cache-cold
        #: build (first boot, version bump) must not poison the budget
        #: the scale-to-zero gate holds the steady state to
        self.cold_start_warm_s = 0.0
        self._cold_warm_n = 0

    # -- sensors ----------------------------------------------------------

    def note_cold_start(self, seconds: float, warm: bool = False) -> None:
        """Record one measured cold start (scale-up decision to replica
        Ready).  The EWMA is the budget ``decide`` holds scale-to-zero
        to — zero is only cheap if waking is.  ``warm=True`` tags a
        build whose program warmup hit the artifact cache: it ALSO
        feeds the warm-path EWMA, which the gate prefers once measured
        (every post-first boot takes the warm path, so that is the
        budget that predicts the next wake)."""
        with self._lock:
            self._cold_n += 1
            a = 0.3 if self._cold_n > 1 else 1.0
            self.cold_start_s = (a * float(seconds)
                                 + (1 - a) * self.cold_start_s)
            if warm:
                self._cold_warm_n += 1
                a = 0.3 if self._cold_warm_n > 1 else 1.0
                self.cold_start_warm_s = (
                    a * float(seconds)
                    + (1 - a) * self.cold_start_warm_s)

    # -- the loop ---------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Decision:
        """One sense -> decide -> actuate pass; returns the decision
        (``none`` with a reason when gated by cooldown/backoff/park)."""
        now = self.clock() if now is None else now
        self.ticks_total += 1
        try:
            sig = dict(self.sensors() or {})
        except Exception as e:  # noqa: BLE001 — a torn sensor read must
            # not kill the loop; the next tick re-reads
            self.sensor_errors_total += 1
            log.debug("autoscale sensor read failed: %s", e)
            return self._record(Decision("none", f"sensor error: {e}"),
                                ok=True)
        self._util.observe(now, _sig(sig, "util", 0.0))
        sig.setdefault("util_forecast",
                       self._util.forecast(self.policy.horizon_s))
        # the scale-to-zero gate budgets the NEXT wake: once a
        # warm-cache cold start has been measured, that is the path
        # every future wake takes — prefer it over the all-paths EWMA
        # (which one cache-cold first boot would otherwise poison)
        sig.setdefault("cold_start_s",
                       self.cold_start_warm_s if self._cold_warm_n > 0
                       else self.cold_start_s)
        dec = decide(sig, self.policy)

        # demand-change bookkeeping: when the demanded action changes
        # (including to none), the previous episode is over — parked
        # channels get their retry budget back.  THIS is what bounds a
        # failing actuator to max_retries attempts per demand episode
        # while still letting a later, different episode try again.
        demand = dec.action if dec.action != "none" else None
        if demand != self._last_demand:
            for st in self.states.values():
                if st.parked or st.failures:
                    st.reset()
            self._last_demand = demand

        if dec.action == "none":
            return self._record(dec, ok=True)
        chan = dec.actuator
        assert chan is not None
        state = self.states[chan]
        ready = state.ready(now)
        if (not ready and not state.parked
                and dec.reason.startswith("emergency")
                and now - self._last_emergency_bypass
                >= self.policy.emergency_window_s):
            # correlated-failure surge may jump the placement
            # cooldown/backoff — but never a PARKED channel (the
            # bounded-retry contract holds even in an emergency), and
            # at most once per emergency window so a flapping health
            # sensor cannot turn the bypass into unlimited fire
            self._last_emergency_bypass = now
            self.emergency_bypass_total += 1
            ready = True
        if not ready:
            self.actuator_skips_total += 1
            why = ("parked after bounded retries" if state.parked
                   else "backoff" if now < state.blocked_until
                   else "cooldown")
            return self._record(
                Decision("none", f"{dec.action} gated: {chan} {why}"),
                ok=True)
        fn = self.actuators.get(chan)
        if fn is None:
            self.actuator_skips_total += 1
            return self._record(
                Decision("none", f"{dec.action}: no {chan} actuator"),
                ok=True)
        state.note_fired(now)
        try:
            if self.failpoint is not None:
                self.failpoint(chan)
            fn(dec)
        except Exception as e:  # noqa: BLE001 — actuator failure is a
            # first-class outcome: back off, bounded retries, no flap
            state.note_failed(now)
            self.actuator_failures_total += 1
            log.warning("autoscale actuator %s failed (%d/%d): %s",
                        chan, state.failures, state.max_retries, e)
            return self._record(dec, ok=False)
        state.note_ok()
        return self._record(dec, ok=True)

    def _record(self, dec: Decision, *, ok: bool) -> Decision:
        self.decisions_total[dec.action] = (
            self.decisions_total.get(dec.action, 0) + 1)
        self.history.append((dec.action, ok))
        return dec

    # -- threaded mode ----------------------------------------------------

    def start(self, interval_s: Optional[float] = None) -> "ClusterAutoscaler":
        """Run the loop on a daemon worker thread (the bench/serving
        path; the controller instead calls ``tick`` from its 4 Hz
        reconcile worker)."""
        if self._thread is not None:
            return self
        interval = float(interval_s or self.policy.loop_s)
        self._stopping.clear()

        def _loop_autoscale() -> None:
            while not self._stopping.wait(interval):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the loop survives
                    log.exception("autoscale tick failed")

        self._thread = threading.Thread(
            target=_loop_autoscale, name="cluster-autoscaler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    # -- observability ----------------------------------------------------

    def stats(self) -> dict:
        out = {
            "autoscale_ticks_total": self.ticks_total,
            "autoscale_actuator_failures_total":
                self.actuator_failures_total,
            "autoscale_actuator_skips_total": self.actuator_skips_total,
            "autoscale_sensor_errors_total": self.sensor_errors_total,
            "autoscale_emergency_bypass_total":
                self.emergency_bypass_total,
            "autoscale_cold_start_s": round(self.cold_start_s, 4),
            "autoscale_cold_start_warm_s": round(
                self.cold_start_warm_s, 4),
            "decisions": dict(self.decisions_total),
        }
        out["autoscale_parked_actuators"] = sum(
            1 for st in self.states.values() if st.parked)
        return out

    def metrics_lines(self) -> List[str]:
        """Prometheus rows for the router/bench /metrics export —
        decisions carry the action as a LABEL (the class-as-label
        rule)."""
        from .traffic import prom_label

        s = self.stats()
        lines = [
            f"kft_autoscale_ticks_total {s['autoscale_ticks_total']}",
            "kft_autoscale_actuator_failures_total "
            f"{s['autoscale_actuator_failures_total']}",
            "kft_autoscale_parked_actuators "
            f"{s['autoscale_parked_actuators']}",
            f"kft_autoscale_cold_start_s {s['autoscale_cold_start_s']}",
            "kft_autoscale_cold_start_warm_s "
            f"{s['autoscale_cold_start_warm_s']}",
        ]
        for action in ACTIONS:
            lines.append(
                'kft_autoscale_decisions_total{action="'
                f'{prom_label(action)}"}} '
                f"{self.decisions_total.get(action, 0)}")
        return lines


class SessionReaper:
    """Idle-session reaper (ISSUE 15 satellite): a configurable idle
    clock that ``hibernate_sequence``s quiet sessions to the spill
    store, freeing their HBM blocks — hibernation stops being purely
    API/operator-driven.

    A session is QUIET when its token stream has made no progress for
    ``idle_s`` (engine-side accounting: ``Request.last_token_at``,
    stamped by the scheduler at every delivery) — in practice a held
    import parked between turns, or a sequence wedged behind an
    operator quiesce.  An actively-decoding sequence refreshes its
    stamp every chunk and is never reaped.  Reaped sessions thaw
    bit-identically on the next request (``thaw_sequence`` — the PR 11
    parity bar), and a failed spill resumes the sequence in place
    (copy-then-cutover at the storage tier), so the reaper can never
    lose a conversation.

    Worker-thread discipline (the ``*Reaper`` analyzer root): reads are
    the engine's public ``idle_sessions`` GIL-copy probe; the only
    mutation path is ``hibernate_sequence`` — the engine's own
    mailbox-backed API, run on THIS thread (device fetch + file I/O
    never land on a scheduler).
    """

    def __init__(self, engines: Callable[[], list], idle_s: float, *,
                 interval_s: float = 1.0,
                 clock: Callable[[], float] = time.perf_counter):
        if float(idle_s) <= 0:
            raise ValueError(f"reap_idle_s {idle_s!r} must be > 0")
        self.engines = engines
        self.idle_s = float(idle_s)
        self.interval_s = float(interval_s)
        self.clock = clock
        self.sessions_reaped_total = 0
        self.reap_failures_total = 0
        self._stopping = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def scan(self, now: Optional[float] = None) -> int:
        """One reap pass over every engine; returns sessions reaped."""
        reaped = 0
        for eng in list(self.engines() or []):
            probe = getattr(eng, "idle_sessions", None)
            if probe is None or getattr(eng, "spill_store", None) is None:
                continue
            for req in probe(self.idle_s, now=now):
                sid = getattr(req, "session_id", None)
                if not sid:
                    continue
                try:
                    if eng.hibernate_sequence(req, sid):
                        reaped += 1
                except Exception as e:  # noqa: BLE001 — a torn spill
                    # resumed the sequence in place; count and move on
                    self.reap_failures_total += 1
                    log.debug("session reap %s failed: %s", sid, e)
        self.sessions_reaped_total += reaped
        return reaped

    def start(self) -> "SessionReaper":
        if self._thread is not None:
            return self
        self._stopping.clear()

        def _loop_reap() -> None:
            while not self._stopping.wait(self.interval_s):
                try:
                    self.scan()
                except Exception:  # noqa: BLE001 — the clock survives
                    log.exception("session reap pass failed")

        self._thread = threading.Thread(
            target=_loop_reap, name="session-reaper", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def stats(self) -> dict:
        return {
            "sessions_reaped_total": self.sessions_reaped_total,
            "reap_failures_total": self.reap_failures_total,
        }
