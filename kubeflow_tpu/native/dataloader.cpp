// Native data-loader kernels for the host-side input pipeline.
//
// The reference's data path runs in native code too (PyTorch's C++
// DataLoader workers / TF's tf.data C++ runtime); the operator tier never
// sees it (SURVEY.md §2.5 DP row: "each rank loads its own shard").  These
// are the TPU rebuild's equivalents for the three host-side hot spots that
// sit between an mmap'd token corpus and jax.make_array_from_process_local_
// data — kept in C++ because they are pure memory-bandwidth loops that the
// GIL would otherwise serialize against the training step's dispatch
// thread:
//
//   kft_shuffle_indices   deterministic Fisher-Yates epoch shuffle
//                         (splitmix64 PRNG, seed -> identical order on
//                         every host, which is what keeps per-process
//                         shards disjoint without communication)
//   kft_pack_sequences    GPT-style document packing: concatenate docs in
//                         shuffle order, EOS-separated, sliced into fixed
//                         (seq_len+1)-token rows; multi-threaded over rows
//   kft_gather_batch      batch assembly: gather rows by index into a
//                         contiguous buffer (the memcpy loop feeding
//                         device_put)
//
// Built with plain g++ -O3 -shared (no deps); loaded via ctypes.  Every
// entry point has a NumPy fallback in train/native_data.py and a parity
// test, so the .so is an accelerator, never a requirement.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// splitmix64: tiny, high-quality, and trivially reproducible in NumPy for
// the fallback/parity tests.
static inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

void kft_shuffle_indices(uint64_t n, uint64_t seed, uint64_t* out) {
  for (uint64_t i = 0; i < n; ++i) out[i] = i;
  uint64_t state = seed;
  // Fisher-Yates; bounded rejection sampling keeps the swap index unbiased
  for (uint64_t i = n; i > 1; --i) {
    uint64_t bound = i;
    uint64_t limit = UINT64_MAX - (UINT64_MAX % bound);
    uint64_t r;
    do {
      r = splitmix64(state);
    } while (r >= limit);
    uint64_t j = r % bound;
    uint64_t tmp = out[i - 1];
    out[i - 1] = out[j];
    out[j] = tmp;
  }
}

// Pack documents (concatenated in `order`, EOS between docs) into rows
// [row0, row0 + n_seqs) of the epoch stream, each (seq_len + 1) tokens,
// writing to out[n_seqs][seq_len+1].  Semantics match the NumPy fallback
// exactly: build the virtual stream doc[order[0]] EOS doc[order[1]] EOS ...
// and cut consecutive rows; the stream is padded with EOS if it runs
// short.  row0 lets a host pack just its own window of the epoch without
// materializing the rest.  Returns the epoch's total row count.
uint64_t kft_pack_sequences(const int32_t* tokens,
                            const uint64_t* doc_offsets,  // n_docs + 1
                            uint64_t n_docs,
                            const uint64_t* order,
                            int32_t eos,
                            uint64_t seq_len,
                            uint64_t row0,
                            uint64_t n_seqs,
                            int32_t* out) {
  const uint64_t row = seq_len + 1;

  // prefix lengths of the shuffled stream so each thread can binary-search
  // its own starting document — no cross-thread state.
  std::vector<uint64_t> stream_prefix(n_docs + 1, 0);
  for (uint64_t d = 0; d < n_docs; ++d) {
    uint64_t len = doc_offsets[order[d] + 1] - doc_offsets[order[d]];
    stream_prefix[d + 1] = stream_prefix[d] + len + 1;  // +1 for EOS
  }
  const uint64_t stream_len = stream_prefix[n_docs];

  unsigned hw = std::thread::hardware_concurrency();
  uint64_t n_threads = hw ? (hw < 8 ? hw : 8) : 1;
  if (n_seqs < n_threads) n_threads = n_seqs ? n_seqs : 1;

  auto worker = [&](uint64_t row_begin, uint64_t row_end) {
    uint64_t pos = (row0 + row_begin) * row;  // stream position of the range
    // find the document containing `pos`
    uint64_t lo = 0, hi = n_docs;
    while (lo < hi) {
      uint64_t mid = (lo + hi) / 2;
      if (stream_prefix[mid + 1] <= pos) lo = mid + 1; else hi = mid;
    }
    uint64_t d = lo;
    uint64_t out_pos = row_begin * row;   // output is window-relative
    const uint64_t out_end = row_end * row;
    while (out_pos < out_end) {
      if (pos >= stream_len || d >= n_docs) {
        out[out_pos++] = eos;  // stream exhausted: EOS padding
        ++pos;
        continue;
      }
      uint64_t in_doc = pos - stream_prefix[d];
      uint64_t doc_len = doc_offsets[order[d] + 1] - doc_offsets[order[d]];
      if (in_doc < doc_len) {
        // contiguous run: copy min(doc remainder, out remainder)
        uint64_t n_copy = doc_len - in_doc;
        uint64_t out_left = out_end - out_pos;
        if (n_copy > out_left) n_copy = out_left;
        std::memcpy(out + out_pos,
                    tokens + doc_offsets[order[d]] + in_doc,
                    n_copy * sizeof(int32_t));
        out_pos += n_copy;
        pos += n_copy;
      } else {
        out[out_pos++] = eos;  // the separator slot after the doc
        ++pos;
        ++d;
      }
    }
  };

  if (n_threads <= 1) {
    worker(0, n_seqs);
  } else {
    std::vector<std::thread> threads;
    uint64_t chunk = (n_seqs + n_threads - 1) / n_threads;
    for (uint64_t t = 0; t < n_threads; ++t) {
      uint64_t b = t * chunk;
      uint64_t e = b + chunk < n_seqs ? b + chunk : n_seqs;
      if (b >= e) break;
      threads.emplace_back(worker, b, e);
    }
    for (auto& th : threads) th.join();
  }
  return (stream_len + row - 1) / row;  // epoch row count
}

void kft_gather_batch(const int32_t* data,
                      uint64_t row_len,
                      const uint64_t* idx,
                      uint64_t n,
                      int32_t* out) {
  for (uint64_t i = 0; i < n; ++i) {
    std::memcpy(out + i * row_len, data + idx[i] * row_len,
                row_len * sizeof(int32_t));
  }
}

}  // extern "C"
