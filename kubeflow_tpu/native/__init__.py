"""Native (C++) runtime components, built on demand with the system g++.

The reference's native tier is its data path (PyTorch C++ DataLoader
workers, tf.data's C++ runtime); the control plane itself is Go with no hot
loops (SURVEY.md §2 intro).  Mirroring that split: JAX/XLA owns device
compute, C++ owns the host-side memory loops feeding it, and everything
here is optional — a NumPy fallback backs every entry point.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional

log = logging.getLogger("kubeflow_tpu.native")

_SRC = os.path.join(os.path.dirname(__file__), "dataloader.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build(so_path: str) -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", so_path,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, OSError) as e:
        log.warning("native dataloader build failed (%s); using NumPy fallback", e)
        return False


def load_library() -> Optional[ctypes.CDLL]:
    """The compiled dataloader library, building it on first use; None when
    no toolchain is available (callers fall back to NumPy)."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        cache_dir = os.environ.get(
            "KFT_NATIVE_CACHE",
            os.path.join(tempfile.gettempdir(), "kft-native"))
        os.makedirs(cache_dir, exist_ok=True)
        so_path = os.path.join(cache_dir, "libkft_data.so")
        src_mtime = os.path.getmtime(_SRC)
        if not os.path.exists(so_path) or os.path.getmtime(so_path) < src_mtime:
            tmp = so_path + f".build-{os.getpid()}"
            if not _build(tmp):
                _build_failed = True
                return None
            # durability before publish: a crash after the replace must
            # not leave a live .so whose pages never hit disk (dlopen of
            # a torn library segfaults instead of failing cleanly)
            fd = os.open(tmp, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            os.replace(tmp, so_path)  # atomic publish for concurrent builders
        try:
            lib = ctypes.CDLL(so_path)
        except OSError as e:
            log.warning("native dataloader load failed (%s)", e)
            _build_failed = True
            return None
        u64 = ctypes.c_uint64
        p_u64 = ctypes.POINTER(u64)
        p_i32 = ctypes.POINTER(ctypes.c_int32)
        lib.kft_shuffle_indices.argtypes = [u64, u64, p_u64]
        lib.kft_shuffle_indices.restype = None
        lib.kft_pack_sequences.argtypes = [
            p_i32, p_u64, u64, p_u64, ctypes.c_int32, u64, u64, u64, p_i32]
        lib.kft_pack_sequences.restype = u64
        lib.kft_gather_batch.argtypes = [p_i32, u64, p_u64, u64, p_i32]
        lib.kft_gather_batch.restype = None
        _lib = lib
        return _lib
