"""AST lint framework: parse once, run rules, ratchet against a baseline.

Pure stdlib by design — the tier-1 ratchet test and the CLI must parse
the whole platform (~21k LoC) in well under a second, so nothing here
may import jax, numpy, or any platform module.

Key ratchet property: finding identity (:meth:`Finding.key`) is
LINE-NUMBER-FREE — ``path::rule::scope::message`` — so unrelated edits
that shift a frozen finding up or down the file do not resurrect it as
"new".  Two identical findings in one scope collapse to a count, and the
baseline stores counts: the ratchet fails when the count for any key
*grows*, shrinking is always allowed (that is the point).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

#: pragma grammar: ``# analysis: ok <rule>[, <rule>...][ — reason]`` on
#: the offending line or the line directly above it
_PRAGMA = re.compile(
    r"#\s*analysis:\s*ok\s+([a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*)")

#: the established swallowed-exception justification form (the exemplar
#: is hpo/controllers.py's db-retry sites): ``# noqa: BLE001`` is only a
#: justification when a REASON follows the dash — a bare noqa is exactly
#: the silent swallow the rule exists to surface
_NOQA_JUSTIFIED = re.compile(r"#\s*noqa:\s*BLE001\s*(?:—|--|-)\s*\S")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str      # repo-relative, posix separators
    line: int      # 1-based, for humans; NOT part of the ratchet key
    scope: str     # enclosing function/class qualname ('' = module level)
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.rule}::{self.scope}::{self.message}"

    def __str__(self) -> str:
        where = f"{self.path}:{self.line}"
        scope = f" [{self.scope}]" if self.scope else ""
        return f"{where}: {self.rule}{scope}: {self.message}"


class ParsedFile:
    """One module parsed once and shared by every rule."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=relpath)
        #: line -> set of rule names pragma'd ok on that line
        self.pragmas: dict[int, set[str]] = {}
        for i, ln in enumerate(self.lines, start=1):
            m = _PRAGMA.search(ln)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                self.pragmas.setdefault(i, set()).update(rules)
        # scope map: line -> innermost function/class qualname
        self._scopes: list[tuple[int, int, str]] = []
        self._index_scopes(self.tree, [])

    def _index_scopes(self, node: ast.AST, stack: list[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = ".".join(stack + [child.name])
                end = getattr(child, "end_lineno", child.lineno)
                self._scopes.append((child.lineno, end, qual))
                self._index_scopes(child, stack + [child.name])
            else:
                self._index_scopes(child, stack)

    def scope_at(self, line: int) -> str:
        """Innermost def/class qualname covering ``line``."""
        best, best_span = "", None
        for start, end, qual in self._scopes:
            if start <= line <= end:
                span = end - start
                if best_span is None or span <= best_span:
                    best, best_span = qual, span
        return best

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def allowed(self, line: int, rule: str) -> bool:
        """Pragma on the offending line or the line above silences the
        rule there (the noqa-above convention for long call lines)."""
        for ln in (line, line - 1):
            if rule in self.pragmas.get(ln, set()):
                return True
        return False

    def has_justified_noqa(self, line: int) -> bool:
        for ln in (line, line - 1):
            if _NOQA_JUSTIFIED.search(self.line_text(ln)):
                return True
        return False


@dataclass
class LintContext:
    """Everything the rule set sees: all parsed files, keyed by relpath."""

    root: str
    files: dict[str, ParsedFile] = field(default_factory=dict)

    def finding(self, pf: ParsedFile, rule: str, node: ast.AST,
                message: str) -> Optional[Finding]:
        """Finding at ``node`` unless a pragma silences it."""
        line = getattr(node, "lineno", 1)
        if pf.allowed(line, rule):
            return None
        return Finding(rule=rule, path=pf.relpath, line=line,
                       scope=pf.scope_at(line), message=message)


#: rule registry: name -> fn(ctx) -> iterable of findings.  Rules are
#: whole-context (lock-order needs the cross-module graph); per-file
#: rules just iterate ctx.files.
RuleFn = Callable[[LintContext], Iterable[Finding]]
_RULES: dict[str, RuleFn] = {}


def rule(name: str) -> Callable[[RuleFn], RuleFn]:
    def deco(fn: RuleFn) -> RuleFn:
        _RULES[name] = fn
        return fn
    return deco


def rule_names() -> list[str]:
    _ensure_rules_loaded()
    return sorted(_RULES)


def _ensure_rules_loaded() -> None:
    # rule modules self-register via @rule at import; imported lazily so
    # `from .astlint import Finding` never recurses
    from . import (  # noqa: F401
        rules_dispatch,
        rules_hygiene,
        rules_locks,
        rules_metrics,
        rules_protocol,
        rules_threads,
    )


#: directories under the repo root that hold platform code to lint;
#: tests/ is deliberately out (fixture snippets there are true positives
#: on purpose), artifacts/examples hold generated/demo code
LINT_DIRS = ("kubeflow_tpu", "scripts")


def discover(root: str) -> list[str]:
    out = []
    for d in LINT_DIRS:
        base = os.path.join(root, d)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [x for x in dirnames if x != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(out)


@dataclass
class LintReport:
    findings: list[Finding]

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.key] = out.get(f.key, 0) + 1
        return out

    def by_rule(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def parse_paths(root: str, paths: Iterable[str]) -> LintContext:
    ctx = LintContext(root=root)
    for p in paths:
        rel = os.path.relpath(p, root)
        with open(p, "r", encoding="utf-8") as fh:
            text = fh.read()
        try:
            ctx.files[rel.replace(os.sep, "/")] = ParsedFile(rel, text)
        except SyntaxError:
            # a file the platform cannot even parse is somebody else's
            # build break, not a lint finding
            continue
    return ctx


def run_lint(root: str, paths: Optional[Iterable[str]] = None,
             rules: Optional[Iterable[str]] = None) -> LintReport:
    """Parse ``paths`` (default: the platform dirs under ``root``) and
    run ``rules`` (default: all registered)."""
    _ensure_rules_loaded()
    ctx = parse_paths(root, paths if paths is not None else discover(root))
    wanted = list(rules) if rules is not None else sorted(_RULES)
    findings: list[Finding] = []
    for name in wanted:
        findings.extend(_RULES[name](ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return LintReport(findings)


# -- baseline ratchet ------------------------------------------------------

def baseline_path(root: str) -> str:
    return os.path.join(root, "kubeflow_tpu", "analysis", "baseline.json")


def load_baseline(path: str) -> dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def write_baseline(path: str, report: LintReport) -> dict:
    """Freeze the current findings as the new debt ceiling."""
    doc = {
        "comment": (
            "platform_lint ratchet baseline: frozen findings debt. "
            "New findings FAIL tier-1; shrink freely, grow never. "
            "Regenerate with `python -m kubeflow_tpu.analysis "
            "--update-baseline` only for reviewed, intentional debt."),
        "by_rule": report.by_rule(),
        "findings": dict(sorted(report.counts().items())),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return doc


def compare_to_baseline(report: LintReport,
                        baseline: dict[str, int]) -> list[Finding]:
    """Findings above the frozen debt: for each key, any occurrences
    beyond the baselined count (a brand-new key has baseline 0)."""
    counts: dict[str, int] = {}
    new: list[Finding] = []
    for f in report.findings:
        counts[f.key] = counts.get(f.key, 0) + 1
        if counts[f.key] > baseline.get(f.key, 0):
            new.append(f)
    return new
