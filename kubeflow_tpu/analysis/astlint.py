"""AST lint framework: parse once, run rules, ratchet against a baseline.

Pure stdlib by design — the tier-1 ratchet test and the CLI must parse
the whole platform (~21k LoC) in well under a second, so nothing here
may import jax, numpy, or any platform module.

Key ratchet property: finding identity (:meth:`Finding.key`) is
LINE-NUMBER-FREE — ``path::rule::scope::message`` — so unrelated edits
that shift a frozen finding up or down the file do not resurrect it as
"new".  Two identical findings in one scope collapse to a count, and the
baseline stores counts: the ratchet fails when the count for any key
*grows*, shrinking is always allowed (that is the point).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

#: pragma grammar: ``# analysis: ok <rule>[, <rule>...][ — reason]`` on
#: the offending line or the line directly above it
_PRAGMA = re.compile(
    r"#\s*analysis:\s*ok\s+([a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*)")

#: hot-path aliases for the single indexing recursion
_AST = ast.AST
_ClassDef = ast.ClassDef
_FunctionDef = ast.FunctionDef
_AsyncFunctionDef = ast.AsyncFunctionDef

#: node shapes the per-def body lists record (what the call-graph body
#: scan consumes); nested defs are recorded by their own branch
_BODY_TYPES = frozenset({ast.Call, ast.With, ast.AsyncWith, ast.Assign})

#: entering these marks the subtree lexically guarded: if/try/ternary
#: are the cache-miss idiom, a lambda body runs later (if ever)
_GUARD_TYPES = frozenset({ast.If, ast.Try, ast.IfExp, ast.Lambda})

#: the established swallowed-exception justification form (the exemplar
#: is hpo/controllers.py's db-retry sites): ``# noqa: BLE001`` is only a
#: justification when a REASON follows the dash — a bare noqa is exactly
#: the silent swallow the rule exists to surface
_NOQA_JUSTIFIED = re.compile(r"#\s*noqa:\s*BLE001\s*(?:—|--|-)\s*\S")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str      # repo-relative, posix separators
    line: int      # 1-based, for humans; NOT part of the ratchet key
    scope: str     # enclosing function/class qualname ('' = module level)
    message: str

    @property
    def key(self) -> str:
        return f"{self.path}::{self.rule}::{self.scope}::{self.message}"

    def __str__(self) -> str:
        where = f"{self.path}:{self.line}"
        scope = f" [{self.scope}]" if self.scope else ""
        return f"{where}: {self.rule}{scope}: {self.message}"


class ParsedFile:
    """One module parsed once and shared by every rule."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=relpath)
        #: id(node) -> ordered child nodes: the ONE tree traversal,
        #: done at parse time, that every later pass reuses.
        #: ``ast.iter_child_nodes`` costs a generator + getattr per
        #: field per visit; over ~250k nodes x several passes that IS
        #: the lint's wall time, so children are extracted once here
        #: (straight from ``__dict__``, which preserves field order)
        #: and every other walk is a dict lookup.  The parser's shared
        #: singletons (Load/Store/Add/... — 36% of all nodes, zero
        #: analytical value; recognizable by their empty ``__dict__``)
        #: are dropped entirely.  Leaves store no entry — read with
        #: ``.get``.
        self.children: dict[int, list[ast.AST]] = {}
        #: node type -> nodes of that type, pre-order.  Rules that scan
        #: for one shape (every With, every Call) index this instead of
        #: re-walking the tree.
        self.by_type: dict[type, list[ast.AST]] = {}
        #: id(def node) -> [(node, lexically_guarded)] for the def's
        #: OWN body: its Call/With/Assign statements and immediate
        #: nested defs, with nested-def SUBTREES attributed to the
        #: nested def and lambda bodies attributed (guarded) to the
        #: enclosing def.  ``guarded`` = under an ``if``/``try``/
        #: ternary/lambda — the lexical shape of the cache-miss idiom.
        #: This is the call-graph body scan, prepaid during indexing so
        #: the graph build never re-walks a body.
        self.body_items: dict[int, list[tuple[ast.AST, bool]]] = {}
        #: (node, qual, innermost_class, outermost_class, is_top_level)
        #: for every def — the shared function table the call graph and
        #: the lock/thread rules index from instead of re-recursing
        self.defs: list[tuple[ast.AST, str, str, str, bool]] = []
        #: (node, qual, innermost enclosing class) for every ClassDef
        self.classdefs: list[tuple[ast.ClassDef, str, str]] = []
        #: line -> set of rule names pragma'd ok on that line
        self.pragmas: dict[int, set[str]] = {}
        for i, ln in enumerate(self.lines, start=1):
            m = _PRAGMA.search(ln)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                self.pragmas.setdefault(i, set()).update(rules)
        # scope map: line -> innermost function/class qualname
        self._scopes: list[tuple[int, int, str]] = []
        self._index(self.tree, "", "", "", True, None, False)

    def _index(self, node: ast.AST, prefix: str, inner_cls: str,
               outer_cls: str, is_top: bool,
               body: Optional[list], guarded: bool) -> None:
        """The single indexing recursion: fills the children map, the
        by-type buckets, the scope spans, the def/class tables, and the
        per-def body-item lists in one pass."""
        kids: list[ast.AST] = []
        for v in node.__dict__.values():
            if type(v) is list:
                for x in v:
                    if isinstance(x, _AST) and x.__dict__:
                        kids.append(x)
            elif isinstance(v, _AST) and v.__dict__:
                kids.append(v)
        if not kids:
            return
        self.children[id(node)] = kids
        by_type = self.by_type
        for child in kids:
            t = type(child)
            b = by_type.get(t)
            if b is None:
                by_type[t] = b = []
            b.append(child)
            if t is _ClassDef:
                qual = prefix + child.name
                end = getattr(child, "end_lineno", child.lineno)
                self._scopes.append((child.lineno, end, qual))
                self.classdefs.append((child, qual, inner_cls))
                # class-level statements of a LOCAL class stay in the
                # enclosing def's body (they run when the def runs)
                self._index(child, qual + ".", child.name,
                            outer_cls or child.name, False, body, guarded)
            elif t is _FunctionDef or t is _AsyncFunctionDef:
                qual = prefix + child.name
                end = getattr(child, "end_lineno", child.lineno)
                self._scopes.append((child.lineno, end, qual))
                self.defs.append((child, qual, inner_cls, outer_cls,
                                  is_top))
                if body is not None:
                    body.append((child, guarded))
                new_body: list = []
                self.body_items[id(child)] = new_body
                self._index(child, qual + ".", inner_cls, outer_cls,
                            False, new_body, False)
            else:
                if body is not None and t in _BODY_TYPES:
                    body.append((child, guarded))
                self._index(child, prefix, inner_cls, outer_cls, is_top,
                            body, guarded or t in _GUARD_TYPES)

    def of_type(self, *types: type) -> list[ast.AST]:
        """Pre-indexed nodes of the given exact types, document order
        per type (concrete ast node classes have no subclasses, so the
        exact-type buckets are exhaustive)."""
        if len(types) == 1:
            return self.by_type.get(types[0], [])
        out: list[ast.AST] = []
        for t in types:
            out.extend(self.by_type.get(t, ()))
        return out

    def scope_at(self, line: int) -> str:
        """Innermost def/class qualname covering ``line``."""
        best, best_span = "", None
        for start, end, qual in self._scopes:
            if start <= line <= end:
                span = end - start
                if best_span is None or span <= best_span:
                    best, best_span = qual, span
        return best

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def allowed(self, line: int, rule: str) -> bool:
        """Pragma on the offending line or the line above silences the
        rule there (the noqa-above convention for long call lines)."""
        for ln in (line, line - 1):
            if rule in self.pragmas.get(ln, set()):
                return True
        return False

    def has_justified_noqa(self, line: int) -> bool:
        for ln in (line, line - 1):
            if _NOQA_JUSTIFIED.search(self.line_text(ln)):
                return True
        return False


@dataclass
class LintContext:
    """Everything the rule set sees: all parsed files, keyed by relpath."""

    root: str
    files: dict[str, ParsedFile] = field(default_factory=dict)

    def finding(self, pf: ParsedFile, rule: str, node: ast.AST,
                message: str) -> Optional[Finding]:
        """Finding at ``node`` unless a pragma silences it."""
        line = getattr(node, "lineno", 1)
        if pf.allowed(line, rule):
            return None
        return Finding(rule=rule, path=pf.relpath, line=line,
                       scope=pf.scope_at(line), message=message)


#: rule registry: name -> fn(ctx) -> iterable of findings.  Rules are
#: whole-context (lock-order needs the cross-module graph); per-file
#: rules just iterate ctx.files.
RuleFn = Callable[[LintContext], Iterable[Finding]]
_RULES: dict[str, RuleFn] = {}


def rule(name: str) -> Callable[[RuleFn], RuleFn]:
    def deco(fn: RuleFn) -> RuleFn:
        _RULES[name] = fn
        return fn
    return deco


def rule_names() -> list[str]:
    _ensure_rules_loaded()
    return sorted(_RULES)


def _ensure_rules_loaded() -> None:
    # rule modules self-register via @rule at import; imported lazily so
    # `from .astlint import Finding` never recurses
    from . import (  # noqa: F401
        rules_clock,
        rules_dispatch,
        rules_hygiene,
        rules_locks,
        rules_metrics,
        rules_persist,
        rules_protocol,
        rules_threads,
    )


#: directories under the repo root that hold platform code to lint;
#: tests/ is deliberately out (fixture snippets there are true positives
#: on purpose), artifacts/examples hold generated/demo code
LINT_DIRS = ("kubeflow_tpu", "scripts")


def discover(root: str) -> list[str]:
    out = []
    for d in LINT_DIRS:
        base = os.path.join(root, d)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [x for x in dirnames if x != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(out)


@dataclass
class LintReport:
    findings: list[Finding]

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.key] = out.get(f.key, 0) + 1
        return out

    def by_rule(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def parse_paths(root: str, paths: Iterable[str]) -> LintContext:
    ctx = LintContext(root=root)
    for p in paths:
        rel = os.path.relpath(p, root)
        with open(p, "r", encoding="utf-8") as fh:
            text = fh.read()
        try:
            ctx.files[rel.replace(os.sep, "/")] = ParsedFile(rel, text)
        except SyntaxError:
            # a file the platform cannot even parse is somebody else's
            # build break, not a lint finding
            continue
    return ctx


def run_lint(root: str, paths: Optional[Iterable[str]] = None,
             rules: Optional[Iterable[str]] = None) -> LintReport:
    """Parse ``paths`` (default: the platform dirs under ``root``) and
    run ``rules`` (default: all registered)."""
    _ensure_rules_loaded()
    ctx = parse_paths(root, paths if paths is not None else discover(root))
    wanted = list(rules) if rules is not None else sorted(_RULES)
    findings: list[Finding] = []
    for name in wanted:
        findings.extend(_RULES[name](ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return LintReport(findings)


# -- baseline ratchet ------------------------------------------------------

def baseline_path(root: str) -> str:
    return os.path.join(root, "kubeflow_tpu", "analysis", "baseline.json")


def load_baseline(path: str) -> dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def write_baseline(path: str, report: LintReport) -> dict:
    """Freeze the current findings as the new debt ceiling."""
    doc = {
        "comment": (
            "platform_lint ratchet baseline: frozen findings debt. "
            "New findings FAIL tier-1; shrink freely, grow never. "
            "Regenerate with `python -m kubeflow_tpu.analysis "
            "--update-baseline` only for reviewed, intentional debt."),
        "by_rule": report.by_rule(),
        "findings": dict(sorted(report.counts().items())),
    }
    # the analyzer obeys its own torn-write rule: tmp-path write ->
    # flush+fsync -> atomic replace, so a crash mid-update leaves the
    # previous baseline intact rather than a half-written ratchet
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return doc


def compare_to_baseline(report: LintReport,
                        baseline: dict[str, int]) -> list[Finding]:
    """Findings above the frozen debt: for each key, any occurrences
    beyond the baselined count (a brand-new key has baseline 0)."""
    counts: dict[str, int] = {}
    new: list[Finding] = []
    for f in report.findings:
        counts[f.key] = counts.get(f.key, 0) + 1
        if counts[f.key] > baseline.get(f.key, 0):
            new.append(f)
    return new
