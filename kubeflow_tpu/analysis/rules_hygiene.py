"""Hygiene rules: swallowed exceptions, pickle ingestion, thread daemons.

``swallowed-exception``: a blanket ``except Exception`` that neither
re-raises, logs, nor carries a justification is how the chaos harness's
bug class hides — the double restart-bump of PR 1 survived as long as
it did because failure paths went quiet.  The contract: every blanket
handler must (a) re-raise, (b) call a logger (``log.debug(...)``,
``log.exception(...)``, ``traceback.print_exc()``...), or (c) carry a
justification — either the pragma ``# analysis: ok swallowed-exception``
or the established ``# noqa: BLE001 — <reason>`` form (reason
REQUIRED; hpo/controllers.py's db-retry sites are the exemplar).

``unsafe-pickle``: ``pickle.load``/``loads`` is code execution on
attacker bytes.  The ONE legitimate ingestion point is the gang
channel's post-auth replay stream (``GangChannel._recv_frame`` —
handshake frames are length-capped JSON *by design* precisely so no
pre-auth pickle ever runs; see serving/gang.py).  Anything else fails.

``nondaemon-thread``: a helper thread without ``daemon=True`` (or a
``t.daemon = True`` assignment right after construction) keeps the
interpreter alive after main exits — the wedged-shutdown class chaos
runs turn into hung CI jobs.  Threads that must outlive main on
purpose carry the pragma.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .astlint import Finding, LintContext, rule
from .rules_dispatch import _dotted

# -- swallowed-exception ---------------------------------------------------

_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}
_LOG_FUNCS = {"print", "print_exc", "print_exception", "print_stack"}


def _is_blanket(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return bool({"Exception", "BaseException"} & set(names))


def _body_handles(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _LOG_METHODS:
                return True
            if isinstance(f, ast.Name) and f.id in _LOG_FUNCS:
                return True
            if isinstance(f, ast.Attribute) and f.attr in _LOG_FUNCS:
                return True
    return False


@rule("swallowed-exception")
def swallowed_exception(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.files.values():
        for node in pf.of_type(ast.ExceptHandler):
            if not _is_blanket(node):
                continue
            if _body_handles(node):
                continue
            if pf.has_justified_noqa(node.lineno):
                continue
            f = ctx.finding(
                pf, "swallowed-exception", node,
                "blanket `except Exception` without log, re-raise, or "
                "justification (`# noqa: BLE001 — <reason>` or "
                "`# analysis: ok swallowed-exception`)")
            if f:
                yield f


# -- unsafe-pickle ---------------------------------------------------------

#: the post-auth gang replay ingestion point: the ONLY scope allowed to
#: unpickle wire bytes (path, enclosing scope qualname)
PICKLE_ALLOWLIST = {
    ("kubeflow_tpu/serving/gang.py", "GangChannel._recv_frame"),
}


def _is_pickle_load(call: ast.Call) -> bool:
    d = _dotted(call.func)
    if d in ("pickle.load", "pickle.loads", "cPickle.load",
             "cPickle.loads", "pickle.Unpickler", "dill.load",
             "dill.loads"):
        return True
    return isinstance(call.func, ast.Name) and call.func.id == "Unpickler"


@rule("unsafe-pickle")
def unsafe_pickle(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.files.values():
        for node in pf.of_type(ast.Call):
            if not _is_pickle_load(node):
                continue
            scope = pf.scope_at(node.lineno)
            if (pf.relpath, scope) in PICKLE_ALLOWLIST:
                continue
            f = ctx.finding(
                pf, "unsafe-pickle", node,
                "pickle ingestion outside the post-auth gang replay "
                "allowlist (pickle.loads on wire bytes is arbitrary "
                "code execution)")
            if f:
                yield f


# -- nondaemon-thread ------------------------------------------------------

def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return True
    return isinstance(f, ast.Name) and f.id == "Thread"


def _daemon_kwarg_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return (isinstance(kw.value, ast.Constant)
                    and kw.value.value is True)
    return False


@rule("nondaemon-thread")
def nondaemon_thread(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.files.values():
        for node in pf.of_type(ast.Call):
            if not _is_thread_ctor(node):
                continue
            if _daemon_kwarg_true(node):
                continue
            # `t.daemon = True` immediately after construction counts
            end = getattr(node, "end_lineno", node.lineno)
            if any(".daemon = True" in pf.line_text(ln)
                   for ln in range(node.lineno, end + 4)):
                continue
            f = ctx.finding(
                pf, "nondaemon-thread", node,
                "threading.Thread without daemon=True (wedges "
                "interpreter shutdown; pragma if it must outlive main)")
            if f:
                yield f
