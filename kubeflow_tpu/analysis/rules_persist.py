"""``torn-write``: the crash-safety commit protocol, made mechanical.

PRs 11 and 21 grew hand-rolled persistence across eight modules
(`controlplane/wal.py`, `serving/storage.py`, `serving/programs.py`,
...) that all promise the same thing: a crash at ANY instruction leaves
either the old state or the new state on disk, never a torn hybrid.
The protocol behind that promise is always the same three steps —

    write to a tmp path  ->  flush + ``os.fsync``  ->  ``os.replace``

with a directory fsync where a manifest/rename is the commit point
(the rename is durable only once the directory entry is).  Until now
the discipline was enforced by review and chaos seeds; this rule makes
it a ratchet.  Three orderings are findings, each at the exact call:

- **bare final write** — ``open(final_path, "w"/"a"/"x")`` in a
  persistence module with no tmp staging: a crash mid-write leaves a
  torn file AT THE LIVE NAME.  Tmp-path writes (anything staged under
  a name that says so) are the protocol's first step and stay quiet.
  Append-mode logs that are DESIGNED to be torn-tail-repaired (the
  WAL) declare themselves with a pragma — that's the contract being
  stated, not the rule being dodged.
- **rename without fsync** — ``os.replace``/``os.rename`` with no
  fsync anywhere earlier in the function (direct ``os.fsync`` or a
  call whose effect set carries ``fsync`` — the ``_fsync_file``/
  ``_fsync_dir`` helpers and the WAL's ``_fsync_locked`` count via the
  call graph): the name commits while the payload may still be in the
  page cache, which is precisely the torn-write window.
- **fsync after replace** — a FILE fsync issued after the function's
  last rename: the name is already published before the data is
  durable, so the ordering protects nothing.  Directory fsyncs are
  exempt — ``fsync(dir)`` AFTER the rename is the correct final step
  (it makes the new directory entry itself durable).

Scope: modules that visibly participate in the commit protocol (any
lexical ``os.fsync``/``os.replace``/``os.rename``) plus the named
persistence core — so a random ``open(path, "w")`` in a bench script
is not a finding, but the same line in ``storage.py`` is.  Analysis is
per-function and lexical (event order by source position) with the
call graph supplying fsync effects; dynamic paths degrade to quiet,
like every under-approximation in this package.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .astlint import Finding, LintContext, rule
from .callgraph import _dotted, get_graph

#: the persistence core is ALWAYS in scope, even if a refactor removed
#: every lexical fsync (which would itself be the regression to catch)
PERSIST_PATHS = (
    "kubeflow_tpu/controlplane/wal.py",
    "kubeflow_tpu/serving/storage.py",
    "kubeflow_tpu/serving/programs.py",
)

#: substrings that mark a path expression as STAGED (protocol step 1):
#: tmp/temp dirs, tempfile helpers, .part/.new spill conventions
_STAGED_MARKERS = ("tmp", "temp", "stag", "part", "new")

_RENAMES = ("os.replace", "os.rename")


def _write_mode(call: ast.Call) -> Optional[str]:
    """The mode string of a creating/truncating ``open``, else None."""
    f = call.func
    if not (isinstance(f, ast.Name) and f.id == "open"):
        return None
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and mode.startswith(("w", "a", "x")):
        return mode
    return None


def _path_source(call: ast.Call, assigns: dict[str, str]) -> str:
    """Best-effort text of the open()'s path argument, with one level
    of local-variable resolution so ``tmp = path + '.tmp';
    open(tmp, 'w')`` reads as staged."""
    if not call.args:
        return ""
    arg = call.args[0]
    src = ast.unparse(arg)
    if isinstance(arg, ast.Name) and arg.id in assigns:
        src = f"{src} = {assigns[arg.id]}"
    return src


def _is_staged(path_src: str) -> bool:
    low = path_src.lower()
    return any(m in low for m in _STAGED_MARKERS)


def _in_scope(pf) -> bool:
    if pf.relpath in PERSIST_PATHS:
        return True
    for node in pf.of_type(ast.Call):
        if _dotted(node.func) in ("os.fsync", "os.replace", "os.rename"):
            return True
    return False


def _fsync_kind(call: ast.Call, graph) -> Optional[str]:
    """'file' / 'dir' if this call fsyncs (directly or via a callee
    with the fsync effect), else None.  Ambiguous fd args count as
    'dir' — the exemption direction, never a false positive."""
    d = _dotted(call.func)
    if d in ("os.fsync", "fsync"):
        arg = call.args[0] if call.args else None
        if (isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Attribute)
                and arg.func.attr == "fileno"):
            return "file"
        return "dir"
    for callee in graph.resolve_call(call):
        if "fsync" in graph.effects(callee):
            name = callee.rsplit(".", 1)[-1].lower()
            return "dir" if "dir" in name else "file"
    return None


@rule("torn-write")
def torn_write(ctx: LintContext) -> Iterable[Finding]:
    graph = get_graph(ctx)
    by_rel: dict[str, list] = {}
    for fq, fi in sorted(graph.funcs.items()):
        by_rel.setdefault(fi.relpath, []).append(fi)
    for rel, pf in sorted(ctx.files.items()):
        if not _in_scope(pf):
            continue
        for fi in by_rel.get(rel, ()):
            # one lexical pass over the OWN body: opens, fsyncs, renames
            pos = lambda n: (n.lineno, n.col_offset)  # noqa: E731
            assigns: dict[str, str] = {}
            for node in ast.walk(fi.node):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    assigns.setdefault(node.targets[0].id,
                                       ast.unparse(node.value))
            opens: list[tuple[tuple, ast.Call, str]] = []
            fsyncs: list[tuple[tuple, str]] = []
            renames: list[tuple[tuple, ast.Call]] = []
            for call in fi.calls:
                mode = _write_mode(call)
                if mode is not None:
                    opens.append((pos(call), call, mode))
                kind = _fsync_kind(call, graph)
                if kind is not None:
                    fsyncs.append((pos(call), kind))
                if _dotted(call.func) in _RENAMES:
                    renames.append((pos(call), call))

            for p, call, mode in opens:
                path_src = _path_source(call, assigns)
                if _is_staged(path_src):
                    continue
                f = ctx.finding(
                    pf, "torn-write", call,
                    f"crash-visible write `open({path_src or '...'}, "
                    f"{mode!r})` outside the tmp->fsync->`os.replace` "
                    "commit protocol — a crash mid-write tears the "
                    "live file")
                if f:
                    yield f

            for p, call in renames:
                if any(fp < p for fp, _k in fsyncs):
                    continue
                target = ast.unparse(call.args[0]) if call.args else "..."
                f = ctx.finding(
                    pf, "torn-write", call,
                    f"`{_dotted(call.func)}` of `{target}` publishes "
                    "without a preceding fsync — the name commits while "
                    "the payload may still be in the page cache")
                if f:
                    yield f

            if renames:
                last_rename, anchor = max(renames)
                for fp, kind in fsyncs:
                    if kind == "file" and fp > last_rename:
                        f = ctx.finding(
                            pf, "torn-write", anchor,
                            "file fsync ordered AFTER the rename commit "
                            "— the name publishes before the data is "
                            "durable; fsync the payload first (dir "
                            "fsync is what belongs after)")
                        if f:
                            yield f
                        break
