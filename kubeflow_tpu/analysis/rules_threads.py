"""``thread-affinity``: scheduler-owned state mutates on the scheduler.

PRs 6-9 grew one load-bearing concurrency contract: an engine's
scheduler state — the slot table, the waiting list, the paged-KV
allocator and its block tables, the pool buffers, the ``_migrating``
freeze map — is owned by the scheduler thread, and every other thread
(HTTP handlers, migration workers, resize orchestration, supervisors)
mutates it ONLY by posting an op to the migration mailbox that the
scheduler services between dispatches.  Both recent review rounds spent
their budget re-finding hand-rolled violations of that contract (the
PR 7 ABANDONED-OP races, the PR 9 export-set race): the bug class is
*lexically visible*, so this rule makes it mechanical.

The rule builds a per-file THREAD-ROLE graph:

- **scheduler** — the ``*Engine`` scheduler roots (``_loop``/
  ``_admit``/``_process``/...; the same list the dispatch rule walks)
  and everything reachable from them.  The mailbox seam is invisible to
  the call graph on purpose: ``export_sequence`` only *posts* to the
  queue, ``_mig_export`` is reachable only from ``_loop`` — so
  mailbox-routed mutation classifies as scheduler-side without any
  allowlist.
- **external** — every other entry a different thread can run:
  ``threading.Thread(target=...)`` spawn targets, HTTP handler methods
  (``do_GET``/``do_POST``/...), the gang ``follow()``/``_accept_loop``
  replay entries, and the engine's PUBLIC cross-thread API (``submit``,
  ``export_sequence``, ... — anything a server thread calls).

Then it flags, inside ``*Engine`` classes, every write to a
scheduler-owned attribute (assignment, subscript store, or a mutating
method call like ``.append``/``.pop``/``.release``) in a method
reachable from an external role.  A method reachable from BOTH roles is
flagged too — that shared reachability IS the race.  Lifecycle methods
(``__init__``, ``warmup``, ``stop``, ``close``) are out: they run
before the scheduler exists or after it joined, and static analysis
cannot see phases.

A second check catches the same contract violated from OUTSIDE the
engine: ``other.engine._slots[...] = ...``-style foreign writes to
owned attributes from any serving-layer code that is not an engine
scheduler.  The gang ``follow()`` replay executor is the one carved-out
owner: its engine's scheduler never starts (followers never submit), so
the replay loop owns the pool buffers by design.

Intentional cross-thread writes carry the standard pragma::

    self._waiting.clear()  # analysis: ok thread-affinity — post-join

Runtime truth (which thread really ran it) is the LockAudit/chaos
harness's job; this rule is the static floor.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .astlint import Finding, LintContext, ParsedFile, rule
from .callgraph import get_graph
from .rules_dispatch import ROOT_METHODS, walk_skip_defs

#: files whose classes carry the serving thread contract
THREAD_SCOPE_PREFIXES = ("kubeflow_tpu/serving/",)

#: scheduler-owned attribute names (the serving-plane state the mailbox
#: seam exists to protect).  Matching is by NAME — over-approximate on
#: purpose: a non-engine class reusing one of these names for
#: cross-thread state is exactly the confusion worth flagging.
SCHEDULER_OWNED = frozenset({
    # slot table + admission state
    "_slots", "_waiting", "_active", "_positions", "_remaining",
    "_prefilling", "_slot_content", "_slot_plen", "_slot_seg",
    # paged block economy
    "_alloc", "_slot_blocks",
    # pool device buffers (donated across dispatches — an aliased write
    # from another thread corrupts an in-flight dispatch)
    "_pool_cache", "_pool_logits", "_seg_cache",
    # shared-prefix segments
    "_seg_content", "_seg_refs", "_seg_used",
    # migration freeze map
    "_migrating",
})

#: method calls that mutate their receiver (list/dict/set verbs plus
#: the BlockAllocator's economy verbs)
_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "sort", "reverse", "update", "setdefault", "add", "discard",
    "alloc", "ref", "release", "register",
})

#: HTTP handler entry points (ThreadingHTTPServer runs each on its own
#: worker thread)
_HANDLER_METHODS = frozenset({
    "do_GET", "do_POST", "do_PUT", "do_DELETE", "do_PATCH", "do_HEAD",
})

#: lifecycle methods that run outside the concurrent phase: __init__
#: builds the object before any thread exists, warmup runs before
#: traffic, stop/close mutate only after setting _stop and joining the
#: scheduler.  Static analysis cannot see phases, so these are excluded
#: by name — a write here that really does race carries the runtime
#: auditors' burden, not this rule's.
_LIFECYCLE = frozenset({
    "__init__", "warmup", "stop", "close", "shutdown", "start",
})


class _RoleGraph:
    """Per-file function index + call graph with INNERMOST-class
    attribution (the cross-module graph resolves more call shapes, but
    this rule's role model is deliberately file-local — the mailbox
    seam argument only holds within one engine module).  Indexes come
    from the parse-time def table; per-function call lists come from
    the shared :class:`~.callgraph.CallGraph` (one body scan total)."""

    def __init__(self, pf: ParsedFile, graph):
        self.pf = pf
        self.graph = graph
        self.mod = None  # set below; the file's module name in the graph
        #: qualname -> def node
        self.funcs: dict[str, ast.AST] = {}
        #: qualname -> innermost enclosing class name ('' = module)
        self.owner: dict[str, str] = {}
        #: innermost class name -> method name -> qualname
        self.by_class: dict[str, dict[str, str]] = {}
        #: bare module-level function name -> qualname
        self.module_funcs: dict[str, str] = {}
        #: class name -> ClassDef node
        self.classes: dict[str, ast.ClassDef] = {}
        for node, _qual, _inner in pf.classdefs:
            self.classes[node.name] = node
        for node, qual, cls, _outer, is_top in pf.defs:
            self.funcs[qual] = node
            self.owner[qual] = cls
            if cls:
                self.by_class.setdefault(cls, {}).setdefault(
                    node.name, qual)
            if is_top:
                self.module_funcs[node.name] = qual
        for mod, rel in graph.modules.items():
            if rel == pf.relpath:
                self.mod = mod
                break
        self._callees_cache: dict[str, set[str]] = {}

    def callees(self, qual: str) -> set[str]:
        """File-local callees of ``qual``'s whole lexical subtree (its
        own body plus nested defs — a closure handed to a thread runs
        that thread's code), resolved with this rule's deliberately
        narrow shapes: bare module functions and ``self.m()``."""
        cached = self._callees_cache.get(qual)
        if cached is not None:
            return cached
        out: set[str] = set()
        self._callees_cache[qual] = out  # placed first: cycle-safe
        fi = self.graph.funcs.get(f"{self.mod}::{qual}")
        if fi is not None:
            cls = self.owner.get(qual, "")
            for node in fi.calls:
                f = node.func
                if isinstance(f, ast.Name):
                    if f.id in self.module_funcs:
                        out.add(self.module_funcs[f.id])
                elif (isinstance(f, ast.Attribute)
                      and isinstance(f.value, ast.Name)
                      and f.value.id == "self" and cls):
                    m = self.by_class.get(cls, {}).get(f.attr)
                    if m:
                        out.add(m)
            for callee, cnode, _g in fi.edges:
                if cnode is None:  # nested def: fold its subtree in
                    out |= self.callees(callee.split("::", 1)[1])
        return out

    def reachable(self, roots: Iterable[str]) -> set[str]:
        seen: set[str] = set()
        todo = [r for r in roots if r in self.funcs]
        while todo:
            q = todo.pop()
            if q in seen:
                continue
            seen.add(q)
            todo.extend(self.callees(q) - seen)
        return seen

    def thread_targets(self) -> list[str]:
        """Qualnames passed as ``threading.Thread(target=...)`` —
        entries another thread runs."""
        out: list[str] = []
        for node in self.pf.of_type(ast.Call):
            f = node.func
            is_thread = (isinstance(f, ast.Attribute) and f.attr == "Thread"
                         ) or (isinstance(f, ast.Name) and f.id == "Thread")
            if not is_thread:
                continue
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None and node.args:
                continue  # positional Thread(group, target) is unused here
            q = self._resolve_ref(target, node)
            if q:
                out.append(q)
        return out

    def _resolve_ref(self, expr: Optional[ast.AST],
                     at: ast.AST) -> Optional[str]:
        """Resolve a first-class function reference (``self._loop``, a
        bare name, or ``obj._method`` by unique method name)."""
        if expr is None:
            return None
        if isinstance(expr, ast.Name):
            return self.module_funcs.get(expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                cls = self._class_at(at)
                return self.by_class.get(cls, {}).get(expr.attr)
            # obj._method: unique method name anywhere in the file
            cands = [q for c in self.by_class.values()
                     for n, q in c.items() if n == expr.attr]
            if len(cands) == 1:
                return cands[0]
        return None

    def _class_at(self, node: ast.AST) -> str:
        scope = self.pf.scope_at(getattr(node, "lineno", 1))
        # innermost CLASS on the qualname path
        parts = scope.split(".") if scope else []
        for i in range(len(parts), 0, -1):
            cand = parts[i - 1]
            if cand in self.classes:
                return cand
        return ""


def _owned_base_attr(expr: ast.AST) -> Optional[str]:
    """The scheduler-owned attribute at the base of a ``self.<attr>``
    target chain (``self._slots``, ``self._slot_blocks[i]``,
    ``self._alloc.cow_copies_total``), or None."""
    node = expr
    owned = None
    while True:
        if isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Attribute):
            if (isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in SCHEDULER_OWNED):
                owned = node.attr
            node = node.value
        else:
            return owned


def _foreign_owned_attr(expr: ast.AST) -> Optional[str]:
    """Owned attribute written through a NON-self object
    (``engine._slots``, ``self.engine._waiting``)."""
    node = expr
    while isinstance(node, ast.Subscript):
        node = node.value
    if not (isinstance(node, ast.Attribute)
            and node.attr in SCHEDULER_OWNED):
        return None
    base = node.value
    if isinstance(base, ast.Name) and base.id == "self":
        return None  # self-writes are the first check's business
    return node.attr


def _iter_owned_writes(fn: ast.AST, children: dict,
                       foreign: bool = False):
    """(node, attr) for every owned-state write lexically in ``fn``'s
    own body (nested defs run on whichever thread calls them — the
    closure handed to the mailbox is the seam working as intended, so
    they are not this method's writes)."""
    pick = _foreign_owned_attr if foreign else _owned_base_attr
    for node in walk_skip_defs(fn, children):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                attr = pick(t)
                if attr:
                    yield node, attr
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                attr = pick(f.value)
                if attr:
                    yield node, attr


@rule("thread-affinity")
def thread_affinity(ctx: LintContext) -> Iterable[Finding]:
    cg = get_graph(ctx)
    for rel, pf in sorted(ctx.files.items()):
        if not rel.startswith(THREAD_SCOPE_PREFIXES):
            continue
        graph = _RoleGraph(pf, cg)
        spawned = set(graph.thread_targets())

        # -- check 1: engine methods, classified by role ------------------
        # Autoscaling/reaper ORCHESTRATION classes (ISSUE 15:
        # ``*Autoscaler``/``*Scaler``/``*Reaper``) ride the same walk
        # with an empty scheduler role: they have no scheduler roots,
        # so EVERY method classifies as an external entry — these
        # classes run on their own worker thread (or the reconcile
        # worker) and may touch engines only through public
        # cross-thread APIs, never by writing owned state directly.
        for cls in sorted(graph.classes):
            if not cls.endswith(("Engine", "Autoscaler", "Scaler",
                                 "Reaper")):
                continue
            methods = graph.by_class.get(cls, {})
            sched_set = graph.reachable(
                methods[m] for m in ROOT_METHODS if m in methods)
            entries: dict[str, str] = {}  # qualname -> entry method name
            for name, qual in sorted(methods.items()):
                if name in _LIFECYCLE or name in ROOT_METHODS:
                    continue
                # NOTE: being scheduler-reachable does NOT exempt an
                # entry — a public method the scheduler also calls runs
                # on two threads, and that shared reachability IS the
                # race this rule exists to flag
                if (not name.startswith("_")          # public cross-thread API
                        or qual in spawned            # worker thread body
                        or name in _HANDLER_METHODS
                        or name == "_accept_loop"):
                    entries[qual] = name
            if not entries:
                continue
            reach_from: dict[str, str] = {}  # method -> first entry reaching it
            for qual, name in entries.items():
                for m in graph.reachable([qual]):
                    reach_from.setdefault(m, name)
            for qual in sorted(reach_from):
                name = qual.rsplit(".", 1)[-1]
                if name in _LIFECYCLE:
                    continue
                fn = graph.funcs[qual]
                role = reach_from[qual]
                shared = qual in sched_set
                for node, attr in _iter_owned_writes(fn, pf.children):
                    f = ctx.finding(
                        pf, "thread-affinity", node,
                        f"write to scheduler-owned `{attr}` from "
                        f"non-scheduler entry `{role}`"
                        + (" (method is ALSO scheduler-reachable — "
                           "shared reachability is the race)"
                           if shared else "")
                        + " — route it through the scheduler mailbox")
                    if f:
                        yield f

        # -- check 2: foreign writes into an engine's owned state ---------
        # the follow() replay executor (and its helpers) owns its
        # engine's pool buffers by design: the follower engine's
        # scheduler never starts, so the replay loop IS that engine's
        # owning thread
        replay = graph.reachable(
            [q for n, q in graph.module_funcs.items()
             if n == "follow" or n.startswith("_follower")])
        for qual in sorted(graph.funcs):
            if qual in replay:
                continue
            fn = graph.funcs[qual]
            for node, attr in _iter_owned_writes(
                    fn, pf.children, foreign=True):
                f = ctx.finding(
                    pf, "thread-affinity", node,
                    f"foreign write to scheduler-owned `{attr}` of "
                    "another object's engine — only the engine's own "
                    "scheduler (or the gang replay executor) may "
                    "mutate it; use the engine's mailbox API")
                if f:
                    yield f
