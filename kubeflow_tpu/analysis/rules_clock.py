"""``wall-clock-in-policy``: no ambient time, no process rng, on any
policy path the sim twin replays.

The fleet twin (ISSUE 20) runs the REAL policy objects — router pick +
circuits + retry budget, the QoS door, ``decide``/``tick`` — on a
virtual clock and a seeded rng.  That only stays true while every one
of those code paths takes time from its ``clock=``/``now=`` seam and
randomness from its ``rng=`` seam: one ``time.monotonic()`` snuck into
a cooldown check and the twin silently diverges from production (same
seed, different bytes), which is exactly the re-modeling drift the
twin exists to rule out.

Scope is explicit: every file under ``kubeflow_tpu/sim/`` (the twin
must be 100% virtual by construction) plus the named policy surfaces
in serving/ that grew seams this PR (:data:`POLICY_SCOPES`).  The
check is transitive over the PR 18 call graph: a scoped function that
*reaches* a helper reading the wall clock is as broken as one that
reads it directly, so the finding lands at the terminal site, wherever
it lives.  The walk applies the same lifecycle cut as the dispatch
rules — ``__init__``/``start``/``stop`` run once outside the replayed
steady state.

The one excused shape is the injectable-default seam itself::

    def activate(self, now=None):
        self._t0 = time.time() if now is None else now

A wall-clock call lexically under an ``<x> is None`` conditional is
the fallback arm of a ``now=`` parameter — the caller CAN inject
virtual time, which is all the twin needs.  Everything else wants the
seam, or an ``# analysis: ok wall-clock-in-policy`` pragma with a
reason.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .astlint import Finding, LintContext, rule
from .callgraph import LIFECYCLE_METHODS, _dotted, get_graph

#: the twin package: everything in it is policy scope
SIM_PREFIX = "kubeflow_tpu/sim/"

#: (relpath, qualname prefixes) — the serving policy surfaces with
#: ``clock=``/``rng=`` seams.  Deliberately NOT whole files: the HTTP
#: handler, reconcile loop and gang probes in controller.py live on
#: real wall time (they serve real clients), only the pure pick/
#: circuit/outage policy the twin drives is held to the seam contract.
POLICY_SCOPES: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("kubeflow_tpu/serving/traffic.py", (
        "TokenBucket", "PrefixAffinity", "SessionAffinity",
        "KvBlockRegistry", "BackendHealth", "RetryBudget",
        "jittered_retry_after", "smooth_wrr_pick", "live_candidates",
        "door_decision", "_ClassState", "TrafficPlane",
        "ClusterPrefixPoller", "blocks_needed", "best_pending",
        "choose_victim", "EnginePreemptor")),
    ("kubeflow_tpu/serving/autoscale.py", (
        "AutoscalePolicy", "TrendPredictor", "ConcurrencyGate",
        "ActuatorState", "decide", "ClusterAutoscaler",
        "SessionReaper")),
    ("kubeflow_tpu/serving/controller.py", (
        "Router._pick", "Router._note", "Router._backend_down",
        "Router._backend_up", "Router._check_domain_outage",
        "Router.domain_of", "Router.set_domains",
        "Router.set_backends", "Router.backends")),
)

#: ambient-time reads (and sleeps — a policy that sleeps real seconds
#: cannot replay in virtual ones)
_WALL_CLOCK = frozenset({
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "time.sleep",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
})

#: module-level ``random.*`` draws mutate interpreter-global state —
#: unseedable from a scenario.  ``random.Random(seed)`` (constructing
#: the seam) is exactly what the twin wants, so only the drawing
#: functions are listed.
_PROCESS_RNG = frozenset(f"random.{f}" for f in (
    "random", "uniform", "gauss", "normalvariate", "lognormvariate",
    "expovariate", "betavariate", "triangular", "vonmisesvariate",
    "paretovariate", "weibullvariate", "choice", "choices", "sample",
    "shuffle", "randint", "randrange", "getrandbits", "randbytes",
    "seed",
))


def _scoped(relpath: str, qual: str) -> bool:
    if relpath.startswith(SIM_PREFIX):
        return True
    for rel, prefixes in POLICY_SCOPES:
        if relpath != rel:
            continue
        for p in prefixes:
            if qual == p or qual.startswith(p + "."):
                return True
    return False


def _violation(call: ast.Call) -> Optional[str]:
    d = _dotted(call.func)
    if d in _WALL_CLOCK:
        return f"wall-clock `{d}()`"
    if d in _PROCESS_RNG:
        return f"process rng `{d}()`"
    return None


def _fallback_excused(pf, def_node: ast.AST) -> set[int]:
    """ids of Call nodes inside an ``<x> is None`` conditional of this
    def — the injectable-default idiom (``time.time() if now is None
    else now``) IS the seam, so its fallback arm is excused."""
    excused: set[int] = set()
    end = getattr(def_node, "end_lineno", def_node.lineno)
    for node in pf.of_type(ast.IfExp, ast.If):
        if not (def_node.lineno <= node.lineno <= end):
            continue
        test = node.test
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.ops[0], (ast.Is, ast.IsNot))
                and any(isinstance(c, ast.Constant) and c.value is None
                        for c in test.comparators)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                excused.add(id(sub))
    return excused


def policy_reachable(graph, roots: Iterable[str]) -> set[str]:
    """Reachability from the policy roots with the lifecycle cut
    (the rules_dispatch phase contract): construction and teardown run
    once, outside the replayed steady state, so the walk never
    descends INTO ``__init__``/``start``/``stop``/... — a root that IS
    one still gets scanned directly."""
    seen: set[str] = set()
    todo = [r for r in roots if r in graph.funcs]
    while todo:
        fq = todo.pop()
        if fq in seen:
            continue
        seen.add(fq)
        for callee, _node, _g in graph.funcs[fq].edges:
            if callee in seen:
                continue
            bare = callee.split("::", 1)[1].rsplit(".", 1)[-1]
            if bare in LIFECYCLE_METHODS:
                continue
            todo.append(callee)
    return seen


@rule("wall-clock-in-policy")
def wall_clock_in_policy(ctx: LintContext) -> Iterable[Finding]:
    graph = get_graph(ctx)
    roots = [fq for fq, fi in sorted(graph.funcs.items())
             if _scoped(fi.relpath, fq.split("::", 1)[1])]
    for fq in sorted(policy_reachable(graph, roots)):
        fi = graph.funcs[fq]
        pf = ctx.files.get(fi.relpath)
        if pf is None:
            continue
        excused = _fallback_excused(pf, fi.node)
        for call in fi.calls:
            if id(call) in excused:
                continue
            label = _violation(call)
            if label is None:
                continue
            f = ctx.finding(
                pf, "wall-clock-in-policy", call,
                f"{label} on a virtual-clock policy path — take time "
                "from the `clock=`/`now=` seam and randomness from "
                "the `rng=` seam so the sim twin replays it "
                "deterministically")
            if f:
                yield f
