"""Cross-module call graph + effect propagation: the analyzer's spine.

PR 10's flagship rule walked an INTRA-file call graph — a scheduler
helper one module away could ``.item()``, ``sendall()``, or fsync
without tier-1 noticing, which on TPU silently drains the device queue
the whole dispatch-ahead design exists to keep full (PAPERS.md:
"Exploring the limits of Concurrency in ML Training on Google TPUs").
This module builds ONE graph over every file in the
:class:`~kubeflow_tpu.analysis.astlint.LintContext` and infers
per-function **effect sets** bottom-up, so rules ask "what does calling
this reach?" instead of re-walking ASTs:

- **Edges** resolve ``from .x import y`` / ``import a.b as c`` symbol
  and module aliases, bare ``name(...)`` calls (nested defs, module
  functions, imported functions, class constructors -> ``__init__``),
  ``self._helper()`` through the cross-module MRO (base classes
  resolved through imports), ``self.X(...)`` getter aliases
  (``self.X = nested_fn``), and one level of attribute typing:
  ``self.store = KvSpillStore(...)`` in any method makes
  ``self.store.write()`` resolve to ``KvSpillStore.write``
  (conflicting assignments degrade the attribute to untyped).
  Anything dynamic — ``getattr(o, n)()``, callables passed as
  arguments, dict-of-fns dispatch — degrades to NO edge, never a
  crash: the graph under-approximates by design and the rules say so.
- **Effects** (:data:`EFFECTS`) are inferred per function from its own
  body and propagated callee->caller with a cycle-safe monotone
  fixpoint (recursion and mutual recursion converge because effect
  sets only grow and are bounded).  Each (function, effect) keeps one
  witness site — the terminal call the effect came from — so findings
  can say *where* the blocking call actually lives.
- **Nested defs** get a pseudo-edge from their enclosing function:
  reachability treats a closure built by a reachable function as
  reachable (the scheduler hands closures to dispatch paths), which
  preserves the old full-subtree walk's coverage.  The one exception
  is ``jit-unguarded`` (below), which nested edges do NOT carry — a
  nested def builds its program lazily when *called*.
- **jit-construct / jit-unguarded**: program construction
  (``jax.jit`` / ``mesh_jit`` / ``make_*_program``) is an effect;
  ``jit-unguarded`` additionally requires the construction NOT be
  under an ``if``/``try`` (the cache-guard idiom) and not in a
  memoizing (``@lru_cache``-style) function, and it propagates only
  through call sites that are themselves unguarded — calling a cached
  getter in a loop is fine, calling an unconditional builder is the
  recompile treadmill.

Consumers: ``host-sync-in-dispatch`` and ``jit-in-loop``
(rules_dispatch) root the same walk they always did but now cross
modules; ``lock-blocking-call`` (rules_locks) joins the lock model to
the effect sets; ``torn-write`` (rules_persist) uses the ``fsync``
effect to credit ``_fsync_dir``-style helpers.  The graph is built
once per lint run (memoized on the context) — it is also the perf
story: every rule that used to re-walk the whole AST now iterates
pre-indexed node lists, which is what keeps whole-platform parse+lint
under the 2 s tier-1 wall-time bar.

Pure stdlib, like everything in this package.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .astlint import LintContext, ParsedFile

#: the effect vocabulary.  ``host-sync`` covers device materialization
#: (``.item()``/``device_get``/np-materialize/...), ``socket`` the
#: blocking socket verbs, ``fsync`` the blocking file-commit op (plain
#: buffered writes are ``file-write``), ``lock`` any lock/Condition
#: acquisition, and the jit pair is documented in the module docstring.
EFFECTS = (
    "host-sync", "socket", "sleep", "fsync", "file-write",
    "urlopen", "thread-join", "lock", "jit-construct", "jit-unguarded",
)

#: effects that mean "the caller blocks": what lock-blocking-call flags
BLOCKING_EFFECTS = frozenset(
    {"host-sync", "socket", "sleep", "fsync", "urlopen", "thread-join"})

#: scheduler entry points: methods of any ``*Engine`` class from which
#: the dispatch-path reachability walk starts (rules_dispatch roots
#: them through the MRO; rules_threads classifies them as the
#: scheduler role)
ROOT_METHODS = ("_loop", "_loop_inner", "_admit", "_process", "step",
                "_dispatch")

#: lifecycle entries that run OUTSIDE the concurrent/steady-state phase
#: (the same contract rules_threads encodes): __init__ builds the
#: object before any thread exists, warmup runs before traffic,
#: stop/close after the scheduler joined.  Dispatch-reachability walks
#: do not traverse INTO these (a root that IS one still gets scanned),
#: and program construction inside __init__/warmup is object-lifecycle
#: compilation, not a per-iteration treadmill.
LIFECYCLE_METHODS = frozenset({
    "__init__", "warmup", "stop", "close", "shutdown", "start",
})

_MAKE_PROGRAM = re.compile(r"^make_\w*_program$")

#: lexical lock-name markers (rules_locks keeps its own copy for lock
#: *identity*; this one only decides whether a with-item / .acquire()
#: receiver is lock-ish enough to count as the ``lock`` effect)
_LOCKISH = ("lock", "gate", "cond", "mutex", "cv", "sem")

_WRITE_MODES = ("w", "a", "x")


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_DEF_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def walk_skip_defs(node: ast.AST,
                   children: Optional[dict] = None) -> Iterable[ast.AST]:
    """ast.walk that does NOT descend into nested function/lambda bodies
    — a def inside the scanned region runs later (if ever), not here.
    Pass ``ParsedFile.children`` to reuse the parse-time child map
    instead of re-deriving children per visit (the fast path every
    in-context caller uses)."""
    if children is None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _DEF_NODES):
                continue
            yield child
            yield from walk_skip_defs(child)
        return
    stack = [c for c in reversed(children.get(id(node), ()))
             if not isinstance(c, _DEF_NODES)]
    while stack:
        n = stack.pop()
        yield n
        kids = children.get(id(n))
        if kids:
            for i in range(len(kids) - 1, -1, -1):
                c = kids[i]
                if not isinstance(c, _DEF_NODES):
                    stack.append(c)


# -- host-materialization matchers (shared with rules_dispatch) ------------

def _is_item(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "item" and not call.args)


def _is_tolist(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "tolist" and not call.args)


def _is_device_get(call: ast.Call) -> bool:
    d = _dotted(call.func)
    return d in ("jax.device_get", "device_get")


def _is_block_until_ready(call: ast.Call) -> bool:
    if isinstance(call.func, ast.Attribute) and (
            call.func.attr == "block_until_ready"):
        return True
    return _dotted(call.func) == "jax.block_until_ready"


def _is_np_materialize(call: ast.Call) -> bool:
    d = _dotted(call.func)
    if d not in _NP_MATERIALIZE:
        return False
    if not call.args:
        return False
    # materializing an obvious host literal is not a device fetch
    return not isinstance(call.args[0], _HOST_LITERALS)


_REDUCERS = {"max", "min", "sum", "mean", "any", "all", "argmax", "argmin"}

#: np.asarray/np.array spellings + the literal arg shapes that make one
#: a host materialization rather than a device fetch (shared between
#: the matcher below and the flattened _BodyScan fast path)
_NP_MATERIALIZE = ("np.asarray", "np.array", "numpy.asarray",
                   "numpy.array", "onp.asarray", "onp.array")
_HOST_LITERALS = (ast.List, ast.ListComp, ast.Tuple, ast.Constant)


def _is_scalarized_reduction(call: ast.Call) -> bool:
    """float(x.max()) / int(a[m].sum()): forces the reduced value to a
    Python scalar — a sync when x is a device array."""
    if not (isinstance(call.func, ast.Name)
            and call.func.id in ("float", "int", "bool")
            and len(call.args) == 1):
        return False
    a = call.args[0]
    return (isinstance(a, ast.Call) and isinstance(a.func, ast.Attribute)
            and a.func.attr in _REDUCERS)


#: (label, matcher) pairs for the ``host-sync`` effect — the labels are
#: the exact strings host-sync-in-dispatch has always reported, so the
#: cross-module rework resurrects no pragma'd finding under a new name
HOST_SYNC_MATCHERS = (
    ("`.item()`", _is_item),
    ("`.tolist()`", _is_tolist),
    ("`jax.device_get`", _is_device_get),
    ("`block_until_ready`", _is_block_until_ready),
    ("numpy materialization (`np.asarray`/`np.array`)", _is_np_materialize),
    ("scalarized reduction (`int`/`float` of `.max()`-like)",
     _is_scalarized_reduction),
)

_BLOCKING_SOCKET_ATTRS = {"sendall", "recv", "recv_into", "accept"}


def is_blocking_socket(call: ast.Call) -> bool:
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in _BLOCKING_SOCKET_ATTRS):
        return True
    return _dotted(call.func) in ("socket.create_connection",
                                  "create_connection")


def is_program_construction(call: ast.Call) -> bool:
    f = call.func
    d = _dotted(f)
    if d in ("jax.jit", "jax.pmap"):
        return True
    name = None
    if isinstance(f, ast.Name):
        name = f.id
    elif isinstance(f, ast.Attribute):
        name = f.attr
    if name is None:
        return False
    return name == "mesh_jit" or bool(_MAKE_PROGRAM.match(name))


def _is_sleep(call: ast.Call) -> bool:
    return _dotted(call.func) in ("time.sleep", "sleep")


def _is_fsync(call: ast.Call) -> bool:
    return _dotted(call.func) in ("os.fsync", "fsync")


def _is_urlopen(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name) and f.id == "urlopen":
        return True
    return isinstance(f, ast.Attribute) and f.attr == "urlopen"


def _is_thread_join(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "join"
            and "thread" in (_dotted(f.value) or "").lower())


def _is_file_write_open(call: ast.Call) -> bool:
    """``open(path, "w"/"a"/"x"...)`` — a creating/truncating write."""
    f = call.func
    name = f.id if isinstance(f, ast.Name) else None
    if name != "open":
        return False
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and mode.startswith(_WRITE_MODES)


def _lockish_name(expr: ast.AST) -> Optional[str]:
    d = _dotted(expr)
    if d is None:
        return None
    last = d.rsplit(".", 1)[-1].lower()
    return d if any(k in last for k in _LOCKISH) else None


# -- the graph -------------------------------------------------------------

@dataclass
class FuncInfo:
    """One function/method: identity, own-body calls, outgoing edges."""

    fqual: str                       # "pkg.mod::Cls.meth"
    mod: str
    relpath: str
    cls: str                         # innermost owning class name, '' = none
    node: ast.AST
    #: every Call in the OWN body (nested defs excluded, lambdas
    #: included — a lambda built here is this function's code)
    calls: list[ast.Call] = field(default_factory=list)
    #: (Call, guarded) as collected — consumed by the resolve phase
    raw: list[tuple[ast.Call, bool]] = field(default_factory=list)
    #: (callee fqual, call node | None, guarded) — node None = nested-def
    #: pseudo-edge
    edges: list[tuple[str, Optional[ast.Call], bool]] = (
        field(default_factory=list))
    intrinsic: set[str] = field(default_factory=set)


@dataclass
class ClassInfo:
    mod: str
    name: str
    node: ast.ClassDef
    methods: dict[str, str] = field(default_factory=dict)   # name -> fqual
    bases: list[tuple[str, str]] = field(default_factory=list)
    #: self.<attr> -> (mod, cls) from single-typed ``self.x = Cls(...)``
    attr_types: dict[str, Optional[tuple[str, str]]] = (
        field(default_factory=dict))
    #: self.<attr> -> fqual from ``self.x = <function>`` getter installs
    fn_aliases: dict[str, str] = field(default_factory=dict)


def _module_name(relpath: str) -> str:
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else (
        relpath.split("/"))
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class CallGraph:
    """The whole-context call graph.  Build once via :func:`get_graph`."""

    def __init__(self, ctx: LintContext):
        self.ctx = ctx
        #: module name -> relpath (only modules in the context resolve)
        self.modules: dict[str, str] = {}
        #: module name -> top-level name -> ("func"|"class", local qual)
        self.toplevel: dict[str, dict[str, tuple[str, str]]] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self.classes: dict[tuple[str, str], ClassInfo] = {}
        #: module -> local alias -> ("module", modname) |
        #: ("symbol", modname, name)
        self.imports: dict[str, dict[str, tuple]] = {}
        #: id(Call node) -> tuple of resolved callee fquals
        self._by_site: dict[int, tuple[str, ...]] = {}
        self._effects: dict[str, frozenset] = {}
        #: (fqual, effect) -> (site fqual, human label) witness
        self._origin: dict[tuple[str, str], tuple[str, str]] = {}

        #: (owning FuncInfo, attr, value node) for every single-target
        #: ``self.X = ...`` — collected by the body scan, consumed by
        #: the attr-typing pass
        self._self_assigns: list[tuple[FuncInfo, str, ast.AST]] = []

        for rel, pf in sorted(ctx.files.items()):
            self._index_file(rel, pf)
        self._resolve_imports()
        # two-phase body scan: collect (calls + effects + self-assigns)
        # BEFORE attr typing, resolve edges after — one pass over every
        # body total, no re-walks
        scans = [_BodyScan(self, fi) for fi in self.funcs.values()]
        for s in scans:
            s.collect()
        self._resolve_bases_and_attrs()
        for s in scans:
            s.resolve()
        self._propagate()

    # -- pass 1: per-file symbol indexing ---------------------------------

    def _index_file(self, rel: str, pf: ParsedFile) -> None:
        mod = _module_name(rel)
        self.modules[mod] = rel
        top: dict[str, tuple[str, str]] = {}
        self.toplevel[mod] = top
        self.imports[mod] = {}
        self._collect_imports(mod, pf)
        # the per-file def/class tables were indexed once at parse time
        # (ParsedFile._index) — reuse them instead of re-recursing
        for node, qual, _inner in pf.classdefs:
            ci = self.classes.setdefault(
                (mod, node.name),
                ClassInfo(mod=mod, name=node.name, node=node))
            ci.node = node
            if "." not in qual:
                top.setdefault(node.name, ("class", node.name))
        for node, qual, cls, _outer, is_top in pf.defs:
            fq = f"{mod}::{qual}"
            self.funcs[fq] = FuncInfo(
                fqual=fq, mod=mod, relpath=rel, cls=cls, node=node)
            if is_top:
                top.setdefault(node.name, ("func", qual))
            if cls:
                self.classes.setdefault(
                    (mod, cls),
                    ClassInfo(mod=mod, name=cls, node=None)
                ).methods.setdefault(node.name, fq)

    def _collect_imports(self, mod: str, pf: ParsedFile) -> None:
        imps = self.imports[mod]
        rel = self.modules[mod]
        # the package for relative imports: the dir the file lives in
        pkg_parts = rel.split("/")[:-1]
        for node in pf.of_type(ast.Import):
            for a in node.names:
                alias = a.asname or a.name.split(".")[0]
                target = a.name if a.asname else a.name.split(".")[0]
                imps[alias] = ("module", target)
        for node in pf.of_type(ast.ImportFrom):
            if node.level:
                base = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                base_mod = ".".join(base)
            else:
                base_mod = ""
            src = ".".join(x for x in (base_mod, node.module or "") if x)
            for a in node.names:
                if a.name == "*":
                    continue
                alias = a.asname or a.name
                imps[alias] = ("from", src, a.name)

    # -- pass 2: resolve imports, bases, attribute types ------------------

    def _resolve_imports(self) -> None:
        """Normalize 'from' entries into symbol or module refs."""
        for mod, imps in self.imports.items():
            for alias, entry in list(imps.items()):
                if entry[0] != "from":
                    continue
                _, src, name = entry
                if src in self.toplevel and name in self.toplevel[src]:
                    kind, qual = self.toplevel[src][name]
                    imps[alias] = ("symbol", kind, src, qual)
                elif f"{src}.{name}" in self.modules:
                    imps[alias] = ("module", f"{src}.{name}")
                else:
                    del imps[alias]  # stdlib / out-of-context: no edge

    def _resolve_classref(self, mod: str,
                          expr: ast.AST) -> Optional[tuple[str, str]]:
        """(mod, cls) for a Name/Attribute class reference, else None."""
        if isinstance(expr, ast.Name):
            if (mod, expr.id) in self.classes:
                return (mod, expr.id)
            imp = self.imports.get(mod, {}).get(expr.id)
            if imp and imp[0] == "symbol" and imp[1] == "class":
                return (imp[2], imp[3])
            return None
        if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                          ast.Name):
            imp = self.imports.get(mod, {}).get(expr.value.id)
            if imp and imp[0] == "module" and (
                    imp[1], expr.attr) in self.classes:
                return (imp[1], expr.attr)
        return None

    def _resolve_bases_and_attrs(self) -> None:
        for (mod, name), ci in self.classes.items():
            if ci.node is not None:
                for b in ci.node.bases:
                    ref = self._resolve_classref(mod, b)
                    if ref:
                        ci.bases.append(ref)
        # attribute typing + getter aliases: self.X = Cls(...) /
        # self.X = fn — from the assigns the body scan collected
        for fi, attr, v in self._self_assigns:
            ci = self.classes.get((fi.mod, fi.cls))
            if ci is None:
                continue
            if isinstance(v, ast.Call):
                ref = self._resolve_classref(fi.mod, v.func)
                if ref is None:
                    continue
                prev = ci.attr_types.get(attr, ref)
                # conflicting types degrade to untyped (None)
                ci.attr_types[attr] = ref if prev == ref else None
            elif isinstance(v, ast.Name):
                fq = self._resolve_funcref(fi.mod, fi, v.id)
                if fq:
                    ci.fn_aliases.setdefault(attr, fq)

    def _resolve_funcref(self, mod: str, fi: FuncInfo,
                         name: str) -> Optional[str]:
        """A bare function NAME visible from inside ``fi``: nested def in
        an enclosing scope chain, module function, or imported symbol."""
        qual = fi.fqual.split("::", 1)[1]
        parts = qual.split(".")
        for i in range(len(parts), 0, -1):
            cand = f"{mod}::{'.'.join(parts[:i])}.{name}"
            if cand in self.funcs:
                return cand
        top = self.toplevel.get(mod, {})
        if name in top and top[name][0] == "func":
            return f"{mod}::{top[name][1]}"
        imp = self.imports.get(mod, {}).get(name)
        if imp and imp[0] == "symbol" and imp[1] == "func":
            return f"{imp[2]}::{imp[3]}"
        return None

    # -- queries -----------------------------------------------------------

    def method(self, mod: str, cls: str, name: str,
               _seen: Optional[set] = None) -> Optional[str]:
        """MRO-resolved method fqual: own class first, then bases DFS
        (cross-module bases resolve through imports)."""
        ci = self.classes.get((mod, cls))
        if ci is None:
            return None
        if name in ci.methods:
            return ci.methods[name]
        seen = _seen if _seen is not None else set()
        if (mod, cls) in seen:
            return None
        seen.add((mod, cls))
        for bmod, bcls in ci.bases:
            got = self.method(bmod, bcls, name, seen)
            if got:
                return got
        return None

    def resolve_call(self, call: ast.Call) -> tuple[str, ...]:
        """Callee fquals resolved for this exact Call node ('' none)."""
        return self._by_site.get(id(call), ())

    def effects(self, fqual: str) -> frozenset:
        return self._effects.get(fqual, frozenset())

    def effect_site(self, fqual: str,
                    effect: str) -> Optional[tuple[str, str]]:
        """(site fqual, label) witness for ``effect`` on ``fqual``."""
        return self._origin.get((fqual, effect))

    def reachable(self, roots: Iterable[str]) -> set[str]:
        seen: set[str] = set()
        todo = [r for r in roots if r in self.funcs]
        while todo:
            q = todo.pop()
            if q in seen:
                continue
            seen.add(q)
            for callee, _node, _g in self.funcs[q].edges:
                if callee not in seen:
                    todo.append(callee)
        return seen

    def func_file(self, fqual: str) -> str:
        return self.funcs[fqual].relpath

    # -- pass 4: effect fixpoint ------------------------------------------

    def _propagate(self) -> None:
        """Cycle-safe monotone fixpoint: effects(f) = intrinsic(f) ∪
        ⋃ effects(callees).  ``jit-unguarded`` flows only through
        UNGUARDED real call edges (a guarded call site is the cache
        idiom; a nested def constructs lazily)."""
        eff: dict[str, set[str]] = {
            fq: set(fi.intrinsic) for fq, fi in self.funcs.items()}
        callers: dict[str, list[tuple[str, bool, bool]]] = {}
        for fq, fi in self.funcs.items():
            for callee, node, guarded in fi.edges:
                callers.setdefault(callee, []).append(
                    (fq, guarded, node is None))
        todo = list(self.funcs)
        in_todo = set(todo)
        while todo:
            fq = todo.pop()
            in_todo.discard(fq)
            for caller, guarded, nested in callers.get(fq, ()):
                flow = set(eff[fq])
                if guarded or nested:
                    flow.discard("jit-unguarded")
                # __init__/warmup are jit-unguarded SINKS: whatever
                # their callees construct is object-lifecycle
                # compilation (see _BodyScan), so the treadmill effect
                # stops there instead of flowing to constructors' users
                if caller.split("::", 1)[1].rsplit(
                        ".", 1)[-1] in ("__init__", "warmup"):
                    flow.discard("jit-unguarded")
                add = flow - eff[caller]
                if not add:
                    continue
                eff[caller] |= add
                for e in add:
                    self._origin.setdefault(
                        (caller, e),
                        self._origin.get((fq, e), (fq, e)))
                if caller not in in_todo:
                    todo.append(caller)
                    in_todo.add(caller)
        self._effects = {fq: frozenset(s) for fq, s in eff.items()}


class _BodyScan:
    """One function's own-body pass: call edges + intrinsic effects,
    with guard tracking for the jit cache idiom.  The traversal itself
    happened at parse time (ParsedFile.body_items carries each def's
    own-body nodes with their guard flags); this class only interprets
    those items."""

    def __init__(self, graph: CallGraph, fi: FuncInfo):
        self.g = graph
        self.fi = fi
        self.pf = graph.ctx.files.get(fi.relpath)
        self.memoized = any(
            "cache" in (_dotted(d if not isinstance(d, ast.Call) else d.func)
                        or "").lower()
            for d in getattr(fi.node, "decorator_list", []))

    def collect(self) -> None:
        """Phase 1: own-body calls, intrinsic effects, self-assigns —
        read off the parse-time body table (ParsedFile.body_items), so
        no body is ever traversed twice."""
        fi, g = self.fi, self.g
        items = (self.pf.body_items.get(id(fi.node), ())
                 if self.pf is not None else ())
        for node, guarded in items:
            t = type(node)
            if t is ast.Call:
                fi.calls.append(node)
                fi.raw.append((node, guarded))
                self._scan_call(node, guarded)
            elif t is ast.Assign:
                tgt = node.targets[0]
                if (len(node.targets) == 1
                        and isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    g._self_assigns.append((fi, tgt.attr, node.value))
            elif t is ast.FunctionDef or t is ast.AsyncFunctionDef:
                nested = f"{fi.fqual}.{node.name}"
                if nested in g.funcs:
                    fi.edges.append((nested, None, guarded))
            else:  # With / AsyncWith
                for item in node.items:
                    if _lockish_name(item.context_expr):
                        self._effect("lock", node,
                                     _dotted(item.context_expr) or "lock")

    def resolve(self) -> None:
        """Phase 2 (after attr typing): raw calls -> resolved edges."""
        fi, g = self.fi, self.g
        for call, guarded in fi.raw:
            callees = self._resolve(call)
            if callees:
                g._by_site[id(call)] = callees
                for c in callees:
                    fi.edges.append((c, call, guarded))

    def _effect(self, effect: str, node: ast.AST, label: str) -> None:
        self.fi.intrinsic.add(effect)
        self.g._origin.setdefault((self.fi.fqual, effect),
                                  (self.fi.fqual, label))

    def _scan_call(self, call: ast.Call, guarded: bool) -> None:
        """Intrinsic effects of one call site.  This is the matcher set
        of HOST_SYNC_MATCHERS + the blocking/jit predicates, flattened
        to compute ``_dotted`` ONCE per site — the predicates each
        re-derive it, and at ~40k call sites that shows up in the
        whole-platform wall time.  Labels and match order are the
        frozen originals (finding identity depends on them)."""
        fi = self.fi
        f = call.func
        ftype = type(f)
        attr = f.attr if ftype is ast.Attribute else None
        name = f.id if ftype is ast.Name else None
        d = _dotted(f) if (attr is not None or name is not None) else None
        # a site already DECLARED as host math / a deliberate fetch
        # boundary (`# analysis: ok host-sync-in-dispatch — ...`) is
        # not a device sync: the declaration suppresses the effect for
        # every transitive consumer (lock-blocking-call etc.), not just
        # the direct rule
        if not (self.pf is not None
                and self.pf.allowed(call.lineno, "host-sync-in-dispatch")):
            label = None
            if attr == "item" and not call.args:
                label = "`.item()`"
            elif attr == "tolist" and not call.args:
                label = "`.tolist()`"
            elif d in ("jax.device_get", "device_get"):
                label = "`jax.device_get`"
            elif attr == "block_until_ready" or d == "jax.block_until_ready":
                label = "`block_until_ready`"
            elif (d in _NP_MATERIALIZE and call.args
                  and not isinstance(call.args[0], _HOST_LITERALS)):
                label = ("numpy materialization "
                         "(`np.asarray`/`np.array`)")
            elif name in ("float", "int", "bool") and len(call.args) == 1:
                a = call.args[0]
                if (isinstance(a, ast.Call)
                        and isinstance(a.func, ast.Attribute)
                        and a.func.attr in _REDUCERS):
                    label = ("scalarized reduction "
                             "(`int`/`float` of `.max()`-like)")
            if label is not None:
                self._effect("host-sync", call, label)
        if attr in _BLOCKING_SOCKET_ATTRS or d in (
                "socket.create_connection", "create_connection"):
            self._effect("socket", call,
                         (d or f".{attr}") if attr is not None else "socket")
        if d in ("time.sleep", "sleep"):
            self._effect("sleep", call, "`time.sleep`")
        if d in ("os.fsync", "fsync"):
            self._effect("fsync", call, "`os.fsync`")
        if name == "open" and _is_file_write_open(call):
            self._effect("file-write", call, "`open(.., 'w')`")
        if name == "urlopen" or attr == "urlopen":
            self._effect("urlopen", call, "`urlopen`")
        if attr == "join" and "thread" in (_dotted(f.value) or "").lower():
            self._effect("thread-join", call, "thread `.join`")
        if attr == "acquire" and _lockish_name(f.value):
            self._effect("lock", call, _dotted(f.value) or "lock")
        nm = name if name is not None else attr
        if d in ("jax.jit", "jax.pmap") or (
                nm is not None and (nm == "mesh_jit" or (
                    nm.startswith("make_") and _MAKE_PROGRAM.match(nm)))):
            self._effect("jit-construct", call, "program construction")
            bare = fi.fqual.split("::", 1)[1].rsplit(".", 1)[-1]
            if (not guarded and not self.memoized
                    and bare not in ("__init__", "warmup")):
                # __init__/warmup construction is object-lifecycle
                # compilation (N objects = N programs, by design);
                # jit-unguarded flags only re-construction treadmills
                self._effect("jit-unguarded", call, "program construction")

    def _resolve(self, call: ast.Call) -> tuple[str, ...]:
        fi, g = self.fi, self.g
        f = call.func
        if isinstance(f, ast.Name):
            fq = g._resolve_funcref(fi.mod, fi, f.id)
            if fq:
                return (fq,)
            ref = g._resolve_classref(fi.mod, f)
            if ref:
                init = g.method(ref[0], ref[1], "__init__")
                return (init,) if init else ()
            return ()
        if not isinstance(f, ast.Attribute):
            return ()
        base = f.value
        # self.m(...) -> MRO; self.X(...) -> getter alias
        if isinstance(base, ast.Name):
            if base.id == "self" and fi.cls:
                m = g.method(fi.mod, fi.cls, f.attr)
                out = (m,) if m else ()
                ci = g.classes.get((fi.mod, fi.cls))
                if ci:
                    a = ci.fn_aliases.get(f.attr)
                    if a and a not in out:
                        out = out + (a,)
                return out
            imp = g.imports.get(fi.mod, {}).get(base.id)
            if imp and imp[0] == "module":
                top = g.toplevel.get(imp[1], {})
                if f.attr in top:
                    kind, qual = top[f.attr]
                    if kind == "func":
                        return (f"{imp[1]}::{qual}",)
                    init = g.method(imp[1], qual, "__init__")
                    return (init,) if init else ()
            return ()
        # self.<attr>.m(...) through the attr-type map
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and fi.cls):
            ci = g.classes.get((fi.mod, fi.cls))
            ref = ci.attr_types.get(base.attr) if ci else None
            if ref:
                m = g.method(ref[0], ref[1], f.attr)
                if m:
                    return (m,)
        return ()


def get_graph(ctx: LintContext) -> CallGraph:
    """The context's call graph, built once and memoized on ``ctx``."""
    g = getattr(ctx, "_callgraph", None)
    if g is None:
        g = CallGraph(ctx)
        ctx._callgraph = g
    return g
