"""``metrics-contract``: engine ``stats()`` keys must be exportable.

Every serving-layer ``stats()`` dict is auto-exported by the model
server's /metrics walk (``kft_engine_<key>`` gauges) and scraped by the
router probes, the recovery/serving benches and — next — the
autoscaler.  That gives stats keys a CONTRACT the type system cannot
see:

- every key must render to a valid Prometheus metric name once the
  exporter splices it into ``kft_engine_<key>`` — one hyphenated or
  dotted key poisons the whole scrape (the PR 8 round-9 regression
  class, which moved per-tenant CLASS names out of metric names for
  exactly this reason);
- a key ending in ``_total`` claims OpenMetrics counter semantics:
  monotonically non-decreasing.  Scrapes rate() counters; a "counter"
  that goes down (a gauge misnamed ``_total``, a counter rebuilt from a
  live walk) silently corrupts every rate over it.

The static half here enforces the NAME rule at lint time: string keys
in dict literals / subscript assignments / ``setdefault`` calls inside
any serving-layer ``stats()`` function body must match
``[a-zA-Z_][a-zA-Z0-9_]*``.  The monotonicity half is value-dependent,
so it lives in :mod:`.runtime` (:func:`audit_stats_pair`) and is pinned
by the engine test suites across an audit pair.  Pragma:
``# analysis: ok metrics-contract — reason``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from .astlint import Finding, LintContext, ParsedFile, rule

#: a key is spliced into ``kft_engine_<key>`` — the key itself must be
#: a valid metric-name SUFFIX (letters, digits, underscores; the prefix
#: supplies the leading letter)
_NAME = re.compile(r"^[a-zA-Z0-9_]+$")

SCOPE_PREFIXES = ("kubeflow_tpu/serving/",)


def _stats_functions(pf: ParsedFile):
    for node in pf.of_type(ast.FunctionDef):
        if node.name == "stats":
            yield node


def _string_keys(fn: ast.FunctionDef):
    """(key, node) for every string key this stats() body builds:
    dict-literal keys, ``out["k"] = ...`` subscript writes, and
    ``.setdefault("k", ...)`` calls."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    yield k.value, k
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)):
                    yield tgt.slice.value, tgt
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "setdefault" and node.args
              and isinstance(node.args[0], ast.Constant)
              and isinstance(node.args[0].value, str)):
            yield node.args[0].value, node


@rule("metrics-contract")
def metrics_contract(ctx: LintContext) -> Iterable[Finding]:
    for rel, pf in sorted(ctx.files.items()):
        if not rel.startswith(SCOPE_PREFIXES):
            continue
        for fn in _stats_functions(pf):
            seen: set[str] = set()
            for key, node in _string_keys(fn):
                if key in seen:
                    continue
                seen.add(key)
                if _NAME.match(key):
                    continue
                f = ctx.finding(
                    pf, "metrics-contract", node,
                    f"stats() key `{key}` does not render to a valid "
                    "Prometheus name (kft_engine_<key>): use "
                    "[a-zA-Z0-9_] only — one bad key poisons the whole "
                    "/metrics scrape")
                if f is not None:
                    yield f
