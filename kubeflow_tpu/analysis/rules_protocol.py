"""Protocol-completeness rules: op tables and chaos fault pairing.

``op-table``: the gang control stream is a leader-publish /
follower-replay protocol — rank 0 publishes ``("<op>", ...)`` tuples
(serving/gang.py, serving/resize.py) and every follower dispatches on
``op == "<op>"`` arms in :func:`serving.gang.follow`.  The protocol's
failure mode is DRIFT: a new dispatch variant publishes an op nobody
replays (followers hit the ``unknown gang op`` raise mid-stream and the
gang goes fatal under live traffic — exactly what the kill-mid-resize
chaos sweep exists to provoke), or an arm survives its last publisher
and rots unexercised.  Both directions are a set difference over
string literals, so this rule computes them at lint time: every
published op must have a replay arm, every arm must have a publisher.
The union is taken across the whole serving layer — resize publishes
``resize``/``resize_abort``/``resize_commit`` that gang.py replays, and
that cross-file pairing is the point.

``fault-pairing``: the chaos plan has the same shape one layer up —
builder methods append ``Fault(FaultKind.X, ...)`` (the failpoint
factories) and actuators consume them by checking ``f.kind ==
FaultKind.X`` (``due_*`` polls, ``pod_script``, ``socket_wrapper``,
``apply_cluster_faults``...).  A kind produced but never consumed is a
fault that can never fire (the chaos test asserts nothing); a kind
consumed but never produced is a dead actuator arm; a declared member
with neither is dead vocabulary.

Both rules anchor findings at the drifting site (the publish with no
arm, the arm with no publish) and carry line-free ratchet keys like
every other rule.  Pragmas silence intentional asymmetry::

    ch.publish(("debug_dump", blob))  # analysis: ok op-table — leader-only
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional

from .astlint import Finding, LintContext, ParsedFile, rule

#: the serving layer IS the protocol scope: publishes in gang.py +
#: resize.py, arms in gang.py's follow() — scanned as ONE table so the
#: cross-file resize/replay pairing holds
OP_SCOPE_PREFIXES = ("kubeflow_tpu/serving/",)

CHAOS_SCOPE_PREFIXES = ("kubeflow_tpu/chaos/",)


def _scope_files(ctx: LintContext,
                 prefixes: tuple[str, ...]) -> list[ParsedFile]:
    """Every scope file, whether or not the lint selected it.

    Both rules here pair sites ACROSS files (resize.py publishes what
    gang.py replays), so a path-scoped run — ``platform_lint.py
    kubeflow_tpu/serving/resize.py``, the advertised pre-commit fast
    path — must still build the table from the WHOLE scope or every
    cross-file pairing reports as drift.  Files the selection left out
    are parsed from disk for table construction only; findings are
    anchored exclusively in ``ctx.files`` (see the callers)."""
    out = [pf for rel, pf in sorted(ctx.files.items())
           if rel.startswith(prefixes)]
    seen = set(ctx.files)
    for prefix in prefixes:
        base = os.path.join(ctx.root, *prefix.rstrip("/").split("/"))
        if not os.path.isdir(base):
            continue
        for fn in sorted(os.listdir(base)):
            if not fn.endswith(".py"):
                continue
            rel = f"{prefix.rstrip('/')}/{fn}"
            if rel in seen:
                continue
            try:
                with open(os.path.join(base, fn), encoding="utf-8") as fh:
                    out.append(ParsedFile(rel, fh.read()))
            except (OSError, SyntaxError):
                continue  # unreadable/broken scope file: table best-effort
    return out


#: the follower dispatch variable name in follow()'s replay loop
_OP_VARS = frozenset({"op"})


def _published_ops(pf: ParsedFile):
    """(op, Call node) for every ``<x>.publish(("<op>", ...))``."""
    for node in pf.of_type(ast.Call):
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "publish"
                and node.args):
            continue
        arg = node.args[0]
        if (isinstance(arg, ast.Tuple) and arg.elts
                and isinstance(arg.elts[0], ast.Constant)
                and isinstance(arg.elts[0].value, str)):
            yield arg.elts[0].value, node


def _replay_scopes(pf: ParsedFile) -> list[ast.AST]:
    """Function bodies that LOOK like a replay dispatch loop: they bind
    ``op = <msg>[0]`` (the follower convention).  Restricting arm
    collection to these scopes keeps unrelated locals named ``op`` (the
    inference-graph condition parser's operator strings) out of the
    table."""
    marks = []
    for stmt in pf.of_type(ast.Assign):
        if (len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id in _OP_VARS
                and isinstance(stmt.value, ast.Subscript)
                and isinstance(stmt.value.slice, ast.Constant)
                and stmt.value.slice.value == 0):
            marks.append(stmt.lineno)
    out, seen = [], set()
    for node, _qual, _inner, _outer, _top in pf.defs:
        end = getattr(node, "end_lineno", node.lineno)
        if id(node) not in seen and any(
                node.lineno <= ln <= end for ln in marks):
            seen.add(id(node))
            out.append(node)
    return out


def _handled_ops(pf: ParsedFile):
    """(op, Compare node) for every ``op == "<op>"`` dispatch arm (and
    ``op in ("a", "b")`` multi-arm membership) inside a replay scope."""
    for fn in _replay_scopes(pf):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left] + list(node.comparators)
            names = [s for s in sides
                     if isinstance(s, ast.Name) and s.id in _OP_VARS]
            if not names:
                continue
            for s in sides:
                if isinstance(s, ast.Constant) and isinstance(s.value, str):
                    yield s.value, node
                elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                    for e in s.elts:
                        if (isinstance(e, ast.Constant)
                                and isinstance(e.value, str)):
                            yield e.value, node


def _site_finding(ctx: LintContext, rule_name: str,
                  sites: list[tuple[ParsedFile, ast.AST]],
                  message: str) -> Optional[Finding]:
    """One finding per completeness violation, anchored at the first
    site inside the lint selection.  A pragma on ANY of the entry's
    sites suppresses it — the table entry is the unit of intent, and
    the declaring comment may legitimately sit on a site other than the
    one sorted first (two files publishing the same leader-only op)."""
    for pf, node in sites:
        if pf.allowed(getattr(node, "lineno", 1), rule_name):
            return None
    for pf, node in sites:
        if pf.relpath in ctx.files:
            return ctx.finding(pf, rule_name, node, message)
    return None  # drift anchored outside the selected paths: the full
    # lint (and tier-1) reports it at its own site


@rule("op-table")
def op_table(ctx: LintContext) -> Iterable[Finding]:
    published: dict[str, list[tuple[ParsedFile, ast.AST]]] = {}
    handled: dict[str, list[tuple[ParsedFile, ast.AST]]] = {}
    any_arms = False
    for pf in _scope_files(ctx, OP_SCOPE_PREFIXES):
        for op, node in _published_ops(pf):
            published.setdefault(op, []).append((pf, node))
        for op, node in _handled_ops(pf):
            any_arms = True
            handled.setdefault(op, []).append((pf, node))
    if not any_arms and not published:
        return
    for op in sorted(set(published) - set(handled)):
        f = _site_finding(
            ctx, "op-table", published[op],
            f"gang op `{op}` is published but has no follower replay "
            "arm — followers will die on `unknown gang op` mid-stream")
        if f:
            yield f
    for op in sorted(set(handled) - set(published)):
        f = _site_finding(
            ctx, "op-table", handled[op],
            f"dead replay arm: gang op `{op}` is handled but nothing "
            "publishes it")
        if f:
            yield f


def _faultkind_attr(node: ast.AST) -> Optional[str]:
    """'X' for a ``FaultKind.X`` attribute reference."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "FaultKind"):
        return node.attr
    return None


@rule("fault-pairing")
def fault_pairing(ctx: LintContext) -> Iterable[Finding]:
    declared: dict[str, list[tuple[ParsedFile, ast.AST]]] = {}
    produced: dict[str, list[tuple[ParsedFile, ast.AST]]] = {}
    consumed: dict[str, list[tuple[ParsedFile, ast.AST]]] = {}
    for pf in _scope_files(ctx, CHAOS_SCOPE_PREFIXES):
        # enum members: assignments inside ``class FaultKind``
        for node in pf.of_type(ast.ClassDef):
            if node.name == "FaultKind":
                for stmt in node.body:
                    if (isinstance(stmt, ast.Assign)
                            and isinstance(stmt.targets[0], ast.Name)):
                        declared.setdefault(
                            stmt.targets[0].id, []).append((pf, stmt))
        # producers: Fault(FaultKind.X, ...) — the failpoint factories
        for node in pf.of_type(ast.Call):
            if (isinstance(node.func, ast.Name)
                    and node.func.id == "Fault"):
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    k = _faultkind_attr(arg)
                    if k:
                        produced.setdefault(k, []).append((pf, node))
        # consumers: comparisons / membership tests on FaultKind.X
        for node in pf.of_type(ast.Compare):
            sides = [node.left] + list(node.comparators)
            for s in sides:
                k = _faultkind_attr(s)
                if k:
                    consumed.setdefault(k, []).append((pf, node))
                elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                    for e in s.elts:
                        k = _faultkind_attr(e)
                        if k:
                            consumed.setdefault(k, []).append((pf, node))
    if not declared and not produced:
        return
    for kind in sorted(set(produced) - set(consumed)):
        f = _site_finding(
            ctx, "fault-pairing", produced[kind],
            f"FaultKind.{kind} is produced by a failpoint factory but "
            "no actuator consumes it — the fault can never fire")
        if f:
            yield f
    for kind in sorted(set(consumed) - set(produced)):
        f = _site_finding(
            ctx, "fault-pairing", consumed[kind],
            f"dead actuator arm: FaultKind.{kind} is consumed but no "
            "builder produces it")
        if f:
            yield f
    for kind in sorted(set(declared) - set(produced) - set(consumed)):
        f = _site_finding(
            ctx, "fault-pairing", declared[kind],
            f"FaultKind.{kind} is declared but neither produced nor "
            "consumed — dead chaos vocabulary")
        if f:
            yield f
