"""Platform analyzer: AST lint rules + runtime dispatch/lock auditors.

The chaos harness (PR 1) found real concurrency bugs at runtime and the
stall-free batching work (PR 2) made dispatch hygiene the serving hot
path's whole perf story — both bug classes are *mechanically detectable
before runtime*.  "Exploring the limits of Concurrency in ML Training on
Google TPUs" (PAPERS.md) is blunt about why: TPU throughput lives or
dies on keeping the device queue full — no accidental host syncs, no
recompiles — and Podracer makes the same argument for the control loop.
This package enforces those invariants as code:

- :mod:`.astlint` — rule framework: parse every platform module once,
  run the rule set, compare against the ratchet baseline
  (``baseline.json``): existing findings are frozen debt, NEW findings
  fail tier-1 (``tests/test_analysis.py``).
- :mod:`.callgraph` — the cross-module call-graph + effect-propagation
  engine (ISSUE 18): ``from .x import y`` / ``self._helper()`` / MRO /
  attribute-typed edges across every parsed file, per-function effect
  sets (host-sync, socket, sleep, fsync, lock, unguarded jit
  construction) at a cycle-safe fixpoint, reachability queries for
  rules.  Dynamic calls degrade to no-edge — under-approximate, never
  crash.
- :mod:`.rules_dispatch` — ``host-sync-in-dispatch`` (a ``.item()`` /
  ``device_get`` / ``np.asarray`` reachable from the engine's dispatch
  loop stalls the device queue — transitively, in whatever module the
  helper lives) and ``jit-in-loop`` (program construction inside a
  loop body — or reached unguarded from one — is a recompile
  treadmill).
- :mod:`.rules_locks` — ``lock-order``: the global ``with <lock>:``
  nesting graph across serving/controlplane/hpo/net; cycles are
  deadlocks waiting for a chaos schedule, and blocking calls (sleep,
  socket ops, jax fetches) under a lock are convoy generators.
  ``lock-blocking-call`` completes the direct-site check transitively:
  blocking effects *reachable* through call edges while the lock is
  held, flagged with the terminal site named.
- :mod:`.rules_persist` — ``torn-write``: the crash-safety commit
  protocol (tmp write -> flush+fsync -> ``os.replace``, dir-fsync at
  manifest commit points) as a ratchet over the persistence modules;
  bare final-name writes, rename-without-fsync, and
  fsync-after-rename orderings are findings.
- :mod:`.rules_hygiene` — ``swallowed-exception`` (every ``except
  Exception`` must log, re-raise, or carry a justification),
  ``unsafe-pickle`` (pickle ingestion outside the post-auth gang replay
  path), ``nondaemon-thread`` (a non-daemon helper thread wedges
  interpreter shutdown).
- :mod:`.rules_threads` — ``thread-affinity``: per-file thread-role
  graph (scheduler roots vs spawned threads, HTTP handlers, gang
  replay loops, the public cross-thread API); writes to
  scheduler-owned engine state from a non-scheduler role fail unless
  routed through the migration mailbox — the PR 7/PR 9 review-round
  bug class, made mechanical.
- :mod:`.rules_clock` — ``wall-clock-in-policy`` (ISSUE 20): no
  ambient ``time.*`` read/sleep and no process-global ``random.*``
  draw in the sim twin or on any serving policy path it replays
  (router pick/circuits, QoS door, autoscaler ``decide``/``tick``),
  transitively over the call graph — the virtual-clock/seeded-rng
  seams are a contract, and one ``time.monotonic()`` snuck into a
  cooldown silently un-replays the twin.  The injectable-default
  fallback (``time.time() if now is None else now``) is recognized as
  a seam, not a violation.
- :mod:`.rules_protocol` — ``op-table`` (every published gang op needs
  a ``follow()`` replay arm and vice versa, cross-file across
  gang.py/resize.py) and ``fault-pairing`` (chaos ``FaultKind``
  factories vs their ``due_*``/actuator consumers).
- :mod:`.runtime` — the *runtime* half: :func:`recompile_guard` counts
  jit cache misses after warmup (``jit_recompiles_total`` engine gauge,
  asserted 0 in steady-state decode), :class:`LockAudit` records
  real acquisition order under chaos to catch inversions static nesting
  cannot see, and :class:`BlockLedger` shadow-refcounts the paged-KV
  block economy (conservation per op, zero-leaked-blocks audits at
  quiesce/retire/migration/resize boundaries, the
  ``kv_blocks_leaked_total`` /metrics gauge).
- :mod:`.selftest` — built-in true-positive/near-miss fixtures per
  rule; ``--self-test`` runs them with no pytest in the loop.

Intentional violations carry an inline pragma on the offending line (or
the line above)::

    x = jax.device_get(toks)  # analysis: ok host-sync-in-dispatch

For ``swallowed-exception`` the established justification comment form
``# noqa: BLE001 — <reason>`` (reason REQUIRED, em- or double-dash) is
honored too — hpo/controllers.py's db-retry sites are the exemplar.

Run it: ``python -m kubeflow_tpu.analysis`` (or
``scripts/platform_lint.py``); ``--update-baseline`` re-freezes debt
after an intentional change; ``--json`` emits machine-readable
findings with timing; ``--changed`` scopes the report (not the parse)
to your git diff; ``--rule`` accepts rule names or group aliases
(``threads``, ``protocol``, ``locks``, ``dispatch``, ``hygiene``,
``persist``); ``--self-test``
validates the rules against their own fixtures.  Exit codes: 0 = clean
(or self-test green), 1 = NEW findings above the ratchet baseline (or
a failed fixture), 2 = usage error.
This module deliberately imports no jax — the lint half is pure stdlib
so the CLI and the tier-1 ratchet test stay fast.
"""

from .astlint import (  # noqa: F401
    Finding,
    LintReport,
    baseline_path,
    compare_to_baseline,
    load_baseline,
    run_lint,
    write_baseline,
)
