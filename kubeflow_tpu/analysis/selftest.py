"""Built-in rule fixtures: the lint binary validates itself.

``python -m kubeflow_tpu.analysis --self-test`` (or
``scripts/platform_lint.py --self-test``) runs every fixture below
through the real rule engine in a temp tree — one TRUE POSITIVE (the
rule must fire, with the expected substring in the message) and one
NEAR MISS (the rule must stay silent) per rule — with no pytest in the
loop, so tier-1 CI can smoke the analyzer with nothing but the
interpreter.  The pytest suite (tests/test_analysis.py) runs richer
variants of the same fixtures; this module is the dependency-free
floor.

The op-table true positive is the SEEDED DRIFT the acceptance bar
names: a published gang op whose ``follow()`` arm was deleted — the
exact protocol rot the rule exists to catch.  The
``host-sync-cross-module`` pair (ISSUE 18) is the call-graph engine's
acceptance case: the blocking helper lives in a DIFFERENT file than
the ``*Engine`` root that reaches it, which the old intra-file walk
could never see.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass

from .astlint import run_lint


@dataclass(frozen=True)
class Fixture:
    rule: str
    name: str          # "<rule>/<true-positive|near-miss>"
    rel: str           # path inside the temp tree (rules scope by path)
    code: str
    expect: int        # minimum findings (0 = must be clean)
    needle: str = ""   # substring every finding message must contain
    #: additional (rel, code) files linted TOGETHER with the main one —
    #: the cross-module fixtures need an effect to live in a different
    #: file than the root that reaches it
    extra: tuple[tuple[str, str], ...] = ()


FIXTURES: tuple[Fixture, ...] = (
    Fixture(
        "host-sync-in-dispatch", "host-sync/true-positive",
        "kubeflow_tpu/serving/_st_dispatch.py",
        """
import jax

class FooEngine:
    def _loop(self):
        return jax.device_get(self.buf)
""",
        1, "host sync"),
    Fixture(
        "host-sync-in-dispatch", "host-sync/near-miss",
        "kubeflow_tpu/serving/_st_dispatch.py",
        """
import jax

class FooEngine:
    def _loop(self):
        return 1

    def debug_dump(self):
        return jax.device_get(self.buf)
""",
        0),
    Fixture(
        "jit-in-loop", "jit-in-loop/true-positive",
        "kubeflow_tpu/serving/_st_jit.py",
        """
import jax

def bad(fns):
    out = []
    for f in fns:
        out.append(jax.jit(f))
    return out
""",
        1, "recompile treadmill"),
    Fixture(
        "jit-in-loop", "jit-in-loop/near-miss",
        "kubeflow_tpu/serving/_st_jit.py",
        """
import jax

def good(fns, cache):
    def getter(k):
        if k not in cache:
            cache[k] = jax.jit(fns[k])
        return cache[k]
    out = []
    for k in range(4):
        out.append(getter(k)(k))
    return out
""",
        0),
    Fixture(
        "lock-order", "lock-order/true-positive",
        "kubeflow_tpu/serving/_st_locks.py",
        """
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()

def one():
    with a_lock:
        with b_lock:
            pass

def two():
    with b_lock:
        with a_lock:
            pass
""",
        1, "cycle"),
    Fixture(
        "lock-order", "lock-order/near-miss",
        "kubeflow_tpu/serving/_st_locks.py",
        """
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()

def one():
    with a_lock:
        with b_lock:
            pass

def two():
    with a_lock:
        with b_lock:
            pass
""",
        0),
    Fixture(
        "swallowed-exception", "swallowed/true-positive",
        "kubeflow_tpu/serving/_st_swallow.py",
        """
def f():
    try:
        risky()
    except Exception:
        pass
""",
        1, "blanket"),
    Fixture(
        "swallowed-exception", "swallowed/near-miss",
        "kubeflow_tpu/serving/_st_swallow.py",
        """
def f():
    try:
        risky()
    except Exception as e:
        raise RuntimeError("wrapped") from e
""",
        0),
    Fixture(
        "unsafe-pickle", "pickle/true-positive",
        "kubeflow_tpu/serving/_st_pickle.py",
        """
import pickle

def recv(sock):
    return pickle.loads(sock.recv(4096))
""",
        1, "arbitrary code execution"),
    Fixture(
        "unsafe-pickle", "pickle/near-miss",
        "kubeflow_tpu/serving/_st_pickle.py",
        """
import pickle

def send(obj):
    return pickle.dumps(obj)
""",
        0),
    Fixture(
        "nondaemon-thread", "nondaemon/true-positive",
        "kubeflow_tpu/serving/_st_thread.py",
        """
import threading

def start(work):
    threading.Thread(target=work).start()
""",
        1, "daemon"),
    Fixture(
        "nondaemon-thread", "nondaemon/near-miss",
        "kubeflow_tpu/serving/_st_thread.py",
        """
import threading

def start(work):
    threading.Thread(target=work, daemon=True).start()
""",
        0),
    Fixture(
        "thread-affinity", "thread-affinity/true-positive",
        "kubeflow_tpu/serving/_st_affinity.py",
        """
import threading

class FooEngine:
    def _loop(self):
        self._admit()

    def _admit(self):
        self._waiting.sort()

    def submit(self, req):
        self._waiting.append(req)
""",
        1, "scheduler-owned"),
    Fixture(
        "thread-affinity", "thread-affinity/near-miss",
        "kubeflow_tpu/serving/_st_affinity.py",
        """
import queue

class FooEngine:
    def _loop(self):
        self._service()

    def _service(self):
        kind, a = self._migrate_q.get_nowait()
        self._waiting.append(a)          # scheduler thread: fine

    def submit(self, req):
        self._migrate_q.put(("admit", req))   # the mailbox seam
""",
        0),
    Fixture(
        # ISSUE 15 rooting: autoscaler/reaper orchestration classes are
        # dispatch-path roots — a device fetch inside a sensor read
        # stalls every tick
        "host-sync-in-dispatch", "host-sync-autoscaler/true-positive",
        "kubeflow_tpu/serving/_st_dispatch_scaler.py",
        """
import jax

class FleetAutoscaler:
    def sense(self):
        return jax.device_get(self.buf)
""",
        1, "host sync"),
    Fixture(
        # same body, unrooted class name: planners that never touch the
        # tick path stay out of scope
        "host-sync-in-dispatch", "host-sync-autoscaler/near-miss",
        "kubeflow_tpu/serving/_st_dispatch_scaler.py",
        """
import jax

class AutoscalePlanner:
    def sense(self):
        return jax.device_get(self.buf)
""",
        0),
    Fixture(
        # ISSUE 17 rooting: AOT artifact classes are dispatch-path
        # roots — a device fetch inside cache bookkeeping puts host
        # work back on the dispatch path every program consult
        "host-sync-in-dispatch", "host-sync-artifact-cache/true-positive",
        "kubeflow_tpu/serving/_st_dispatch_artifacts.py",
        """
import jax

class ProgramArtifactCache:
    def fingerprint(self, buf):
        return jax.device_get(buf)
""",
        1, "host sync"),
    Fixture(
        # suffix match roots *ArtifactCache, not names that merely
        # contain it: an index over the cache dir is host bookkeeping
        # that never runs on the dispatch path
        "host-sync-in-dispatch", "host-sync-artifact-cache/near-miss",
        "kubeflow_tpu/serving/_st_dispatch_artifacts.py",
        """
import jax

class ArtifactCacheIndex:
    def fingerprint(self, buf):
        return jax.device_get(buf)
""",
        0),
    Fixture(
        # ISSUE 15 rooting: every orchestration-class method is an
        # external entry — writing scheduler-owned state from the
        # decision loop is the race the contract forbids
        "thread-affinity", "thread-affinity-autoscaler/true-positive",
        "kubeflow_tpu/serving/_st_affinity_scaler.py",
        """
class FleetAutoscaler:
    def tick(self):
        self._slots.pop()
""",
        1, "scheduler-owned"),
    Fixture(
        # the blessed shape: GIL-copy reads + the engine's public
        # cross-thread API for writes
        "thread-affinity", "thread-affinity-autoscaler/near-miss",
        "kubeflow_tpu/serving/_st_affinity_scaler.py",
        """
class FleetAutoscaler:
    def tick(self):
        live = len(list(self.engine.slots_view()))
        if live == 0:
            self.engine.submit(None)
""",
        0),
    Fixture(
        # the acceptance bar's seeded drift: op "beta" is published but
        # its follow() arm was deleted
        "op-table", "op-table/true-positive",
        "kubeflow_tpu/serving/_st_ops.py",
        """
def leader(ch, toks):
    ch.publish(("alpha", toks))
    ch.publish(("beta", toks))

def follow(channel):
    while True:
        msg = channel.next()
        op = msg[0]
        if op == "alpha":
            continue
        raise RuntimeError(f"unknown gang op {op!r}")
""",
        1, "`beta`"),
    Fixture(
        "op-table", "op-table/near-miss",
        "kubeflow_tpu/serving/_st_ops.py",
        """
def leader(ch, toks):
    ch.publish(("alpha", toks))

def follow(channel):
    while True:
        msg = channel.next()
        op = msg[0]
        if op == "alpha":
            continue
""",
        0),
    Fixture(
        "metrics-contract", "metrics-contract/true-positive",
        "kubeflow_tpu/serving/_st_metrics.py",
        """
class FooEngine:
    def stats(self):
        return {"tokens_emitted": 1, "kv-blocks.free": 2}
""",
        1, "kv-blocks.free"),
    Fixture(
        "metrics-contract", "metrics-contract/near-miss",
        "kubeflow_tpu/serving/_st_metrics.py",
        """
class FooEngine:
    def stats(self):
        out = {"tokens_emitted": 1}
        out["kv_blocks_free"] = 2
        out.setdefault("queue_depth", 0)
        return out
""",
        0),
    Fixture(
        "fault-pairing", "fault-pairing/true-positive",
        "kubeflow_tpu/chaos/_st_faults.py",
        """
class FaultKind:
    CRASH = "crash"
    GHOST = "ghost"

class Fault:
    def __init__(self, kind, at=0.0):
        self.kind = kind

class Plan:
    def crash(self):
        self.faults.append(Fault(FaultKind.CRASH))

    def ghost(self):
        self.faults.append(Fault(FaultKind.GHOST))

    def due(self):
        return [f for f in self.faults if f.kind == FaultKind.CRASH]
""",
        1, "GHOST"),
    Fixture(
        "fault-pairing", "fault-pairing/near-miss",
        "kubeflow_tpu/chaos/_st_faults.py",
        """
class FaultKind:
    CRASH = "crash"

class Fault:
    def __init__(self, kind, at=0.0):
        self.kind = kind

class Plan:
    def crash(self):
        self.faults.append(Fault(FaultKind.CRASH))

    def due(self):
        return [f for f in self.faults if f.kind == FaultKind.CRASH]
""",
        0),
    Fixture(
        # ISSUE 16 drift shape: a correlated-failure fault whose
        # actuator poll was deleted — the plan builds domain outages
        # nothing ever fires
        "fault-pairing", "fault-pairing-outage/true-positive",
        "kubeflow_tpu/chaos/_st_faults_outage.py",
        """
class FaultKind:
    CRASH = "crash"
    DOMAIN_OUTAGE = "domain_outage"

class Fault:
    def __init__(self, kind, at=0.0, node=None):
        self.kind = kind
        self.node = node

class Plan:
    def crash(self):
        self.faults.append(Fault(FaultKind.CRASH))

    def domain_outage(self, name):
        self.faults.append(Fault(FaultKind.DOMAIN_OUTAGE, node=name))

    def due(self):
        return [f for f in self.faults if f.kind == FaultKind.CRASH]
""",
        1, "DOMAIN_OUTAGE"),
    Fixture(
        # the paired shape this PR ships: producer builder + a
        # due_domain_outages-style consumer comparison
        "fault-pairing", "fault-pairing-outage/near-miss",
        "kubeflow_tpu/chaos/_st_faults_outage.py",
        """
class FaultKind:
    DOMAIN_OUTAGE = "domain_outage"

class Fault:
    def __init__(self, kind, at=0.0, node=None):
        self.kind = kind
        self.node = node

class Plan:
    def domain_outage(self, name):
        self.faults.append(Fault(FaultKind.DOMAIN_OUTAGE, node=name))

    def due_domain_outages(self):
        return [f.node for f in self.faults
                if f.kind == FaultKind.DOMAIN_OUTAGE and not f.fired]
""",
        0),
    Fixture(
        # ISSUE 16 rooting: the emergency surge path runs on the
        # autoscaler tick — writing scheduler-owned engine state from
        # it is the race the contract forbids, emergency or not
        "thread-affinity", "thread-affinity-emergency/true-positive",
        "kubeflow_tpu/serving/_st_affinity_emergency.py",
        """
class SurgeAutoscaler:
    def emergency_tick(self):
        self._waiting.clear()
""",
        1, "scheduler-owned"),
    Fixture(
        # BackendHealth is NOT a dispatch root (no Engine/Autoscaler/
        # Scaler/Reaper suffix): its lock-guarded circuit dict is its
        # own to mutate from any request thread
        "thread-affinity", "thread-affinity-emergency/near-miss",
        "kubeflow_tpu/serving/_st_affinity_emergency.py",
        """
class BackendHealth:
    def note_failure(self, backend):
        self._waiting.append(backend)
""",
        0),
    Fixture(
        # ISSUE 18: the persistence core (PERSIST_PATHS) is always in
        # scope — a bare open(final, "w") tears the live file on crash
        "torn-write", "torn-write/true-positive",
        "kubeflow_tpu/serving/storage.py",
        """
import json

def save_index(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)
""",
        1, "commit protocol"),
    Fixture(
        # staged write, but the name commits before the payload is
        # durable — the exact page-cache window the protocol closes
        "torn-write", "torn-write-rename/true-positive",
        "kubeflow_tpu/serving/_st_persist.py",
        """
import json
import os

def save_index(path, obj):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)
""",
        1, "preceding fsync"),
    Fixture(
        # the full protocol: tmp write -> flush+fsync -> atomic replace
        "torn-write", "torn-write/near-miss",
        "kubeflow_tpu/serving/_st_persist.py",
        """
import json
import os

def save_index(path, obj):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
""",
        0),
    Fixture(
        # ISSUE 18: blocking work REACHED through a call edge while a
        # lock is held — invisible to lock-order's direct-site check
        "lock-blocking-call", "lock-blocking/true-positive",
        "kubeflow_tpu/serving/_st_lockblock.py",
        """
import os

class BatchWriter:
    def flush_batch(self):
        with self._lock:
            self._flush()

    def _flush(self):
        self._f.flush()
        os.fsync(self._f.fileno())
""",
        1, "while holding"),
    Fixture(
        # the fix shape: drain under the lock, block outside it
        "lock-blocking-call", "lock-blocking/near-miss",
        "kubeflow_tpu/serving/_st_lockblock.py",
        """
import os

class BatchWriter:
    def flush_batch(self):
        with self._lock:
            batch = self._drain()
        self._write(batch)

    def _drain(self):
        out = list(self._pending)
        self._pending.clear()
        return out

    def _write(self, batch):
        self._f.write(b"".join(batch))
        os.fsync(self._f.fileno())
""",
        0),
    Fixture(
        # ISSUE 18's acceptance case: the helper lives one module away
        # from the *Engine root that reaches it — the old intra-file
        # walk was blind to exactly this
        "host-sync-in-dispatch", "host-sync-cross-module/true-positive",
        "kubeflow_tpu/serving/_st_xmod_a.py",
        """
from ._st_xmod_b import fetch_stats

class FooEngine:
    def _loop(self):
        return fetch_stats(self.buf)
""",
        1, "host sync",
        extra=(("kubeflow_tpu/serving/_st_xmod_b.py", """
import jax

def fetch_stats(buf):
    return jax.device_get(buf)
"""),)),
    Fixture(
        # same helper, reached only from a non-root method: reachability
        # (not mere import) is what puts an effect on the dispatch path
        "host-sync-in-dispatch", "host-sync-cross-module/near-miss",
        "kubeflow_tpu/serving/_st_xmod_a.py",
        """
from ._st_xmod_b import fetch_stats

class FooEngine:
    def _loop(self):
        return 1

    def debug_dump(self):
        return fetch_stats(self.buf)
""",
        0, "",
        extra=(("kubeflow_tpu/serving/_st_xmod_b.py", """
import jax

def fetch_stats(buf):
    return jax.device_get(buf)
"""),)),
    Fixture(
        # ISSUE 20: an ambient clock read inside the sim twin — the
        # exact drift the virtual-clock contract forbids
        "wall-clock-in-policy", "wall-clock/true-positive",
        "kubeflow_tpu/sim/_st_twin.py",
        """
import time

def cooldown_over(last, cooldown_s):
    return time.monotonic() - last >= cooldown_s
""",
        1, "virtual-clock policy path"),
    Fixture(
        # transitive, cross-module: the policy function itself is
        # clean, but a helper one module away draws the process rng
        "wall-clock-in-policy", "wall-clock-transitive/true-positive",
        "kubeflow_tpu/sim/_st_twin.py",
        """
from ..serving._st_jitter import spread_hint

def retry_delay(base):
    return spread_hint(base)
""",
        1, "process rng",
        extra=(("kubeflow_tpu/serving/_st_jitter.py", """
import random

def spread_hint(base):
    return base * (1.0 + random.random())
"""),)),
    Fixture(
        # the seam shapes: clock/rng taken from injected callables, and
        # the injectable-default fallback (`if now is None`) — all of
        # them are exactly what the twin threads through, none fire
        "wall-clock-in-policy", "wall-clock/near-miss",
        "kubeflow_tpu/sim/_st_twin.py",
        """
import random
import time


class Bucket:
    def __init__(self, clock=time.monotonic, rng=None):
        self._clock = clock
        self._rng = rng if rng is not None else random.Random(0)

    def take(self):
        now = self._clock()
        return now + self._rng.random()


def activate(plan, now=None):
    plan.t0 = time.time() if now is None else now
""",
        0),
)


def run_selftest(rules=None, out=print) -> int:
    """Run the fixtures (optionally a rule subset); 0 = all green.

    Each fixture lints ALONE in a fresh temp tree, so table-style rules
    (op-table, fault-pairing) see exactly the fixture's protocol."""
    wanted = set(rules) if rules else None
    ran = failed = 0
    for fx in FIXTURES:
        if wanted is not None and fx.rule not in wanted:
            continue
        ran += 1
        with tempfile.TemporaryDirectory(prefix="platform-lint-st-") as td:
            targets = []
            for rel, code in ((fx.rel, fx.code), *fx.extra):
                target = os.path.join(td, rel)
                os.makedirs(os.path.dirname(target), exist_ok=True)
                with open(target, "w", encoding="utf-8") as fh:
                    fh.write(code)
                targets.append(target)
            report = run_lint(td, paths=targets, rules=[fx.rule])
        n = len(report.findings)
        ok = (n == 0) if fx.expect == 0 else (
            n >= fx.expect
            and all(fx.needle in f.message for f in report.findings))
        if ok:
            out(f"  ok   {fx.name}")
        else:
            failed += 1
            out(f"  FAIL {fx.name}: expected "
                f"{'clean' if fx.expect == 0 else f'>={fx.expect} findings'}"
                f" with {fx.needle!r}, got {n}:")
            for f in report.findings:
                out(f"       {f}")
    out(f"self-test: {ran - failed}/{ran} fixtures green")
    return 1 if failed else 0
