"""Runtime auditors: the dynamic half of the platform analyzer.

Static rules (:mod:`.astlint`) catch what is *lexically* visible; these
two catch what only manifests live:

- :class:`RecompileGuard` / :func:`recompile_guard` — wraps an engine's
  jitted programs and counts jit-cache growth past each program's first
  compile.  The first compile per program is warmup (expected, paid
  once); ANY growth after that is a recompile — a shape/dtype/weak-type
  leak in the host scheduler that stalls every live request for a full
  trace+compile.  The engine exports the shared counter as its
  ``jit_recompiles_total`` stat (auto-surfaced as a /metrics gauge),
  and tier-1 asserts it stays 0 across a chunked-prefill + decode
  steady-state run.

- :class:`LockAudit` — wraps/instruments ``threading`` locks and records
  the REAL per-thread acquisition order, including orders that only
  happen under fault injection (the chaos harness's schedules).  The
  static ``lock-order`` rule sees lexical nesting; this sees the
  interleavings chaos actually produced.  ``inversions()`` returns the
  (A, B) pairs observed in both orders — each one is a deadlock that
  needs nothing more than worse timing.

No jax import at module load: the lint CLI shares this package and must
stay stdlib-fast.  ``RecompileGuard`` only touches jax objects it is
handed.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, Optional


class RecompileCounter:
    """Shared recompile tally (one per engine; thread-safe via the GIL
    for the two scalars it carries).

    ``armed`` gates counting: the engine's warmup deliberately compiles
    a LADDER of shapes per program (group sizes, attend rungs) — growth
    during that phase is the paid-once warm set, not a recompile.  The
    engine arms the counter when warmup finishes (or at first traffic
    when warmup was skipped); from then on, cache growth is a
    mid-serving stall and counts."""

    __slots__ = ("count", "armed")

    def __init__(self) -> None:
        self.count = 0
        self.armed = False


class RecompileGuard:
    """Count jit cache misses past a program's first compile.

    Wraps any callable produced by ``jax.jit`` or
    ``serving.sharded.mesh_jit`` (which exposes its inner jitted fn as
    ``_jitted``).  After each call the underlying trace-cache size is
    read (``_cache_size``, present on jax's PjitFunction); the first
    observed size is the warm set, growth beyond it increments the
    shared counter.  Programs without a readable cache (AOT-compiled
    executables, plain functions) pass through uncounted rather than
    guessing.
    """

    def __init__(self, program: Callable, counter: RecompileCounter):
        self._program = program
        self._inner = getattr(program, "_jitted", program)
        self._counter = counter
        self._warm: Optional[int] = None

    def _cache_size(self) -> Optional[int]:
        probe = getattr(self._inner, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:  # noqa: BLE001 — jax internals shifted: the
            # guard degrades to uncounted, never breaks dispatch
            return None

    def __call__(self, *args, **kwargs):
        out = self._program(*args, **kwargs)
        size = self._cache_size()
        if size is not None:
            if self._warm is None:
                # first compile of this program = its warm entry, never
                # a recompile (programs may be built lazily post-warmup:
                # a new attend rung's first compile is a cache miss by
                # design, re-tracing an EXISTING entry is the bug)
                self._warm = size
            elif size > self._warm:
                if self._counter.armed:
                    self._counter.count += size - self._warm
                self._warm = size
        return out

    def lower(self, *args, **kwargs):
        """AOT lowering passthrough (scripts/aot_7b_serving.py path)."""
        return self._program.lower(*args, **kwargs)

    @property
    def cache_entries(self) -> Optional[int]:
        """Current trace-cache size of the wrapped program (None when
        unreadable) — per-guard introspection for tests/debugging; the
        shared counter aggregates recompiles across guards."""
        return self._cache_size()


def recompile_guard(program: Callable,
                    counter: RecompileCounter) -> RecompileGuard:
    """Wrap ``program`` so cache growth past its first compile counts
    into ``counter`` (idempotent: re-wrapping a guard is a no-op)."""
    if isinstance(program, RecompileGuard):
        return program
    return RecompileGuard(program, counter)


class _AuditedLock:
    """Context-manager/acquire-release proxy recording into a LockAudit."""

    def __init__(self, audit: "LockAudit", lock: Any, name: str):
        self._audit = audit
        self._lock = lock
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._audit._acquired(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        self._audit._released(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __getattr__(self, name: str):
        # passthrough for wrapped-lock extras (RLock internals etc.);
        # only acquire/release order is audited
        return getattr(self._lock, name)


class LockAudit:
    """Record real lock-acquisition order across threads.

    Usage (chaos tests)::

        audit = LockAudit()
        audit.instrument(channel, "_lock")      # wrap an object's lock
        gate = audit.wrap(threading.Lock(), "gate")   # or wrap directly
        ... run the faulted scenario ...
        assert audit.inversions() == []

    Every acquisition while other audited locks are held by the SAME
    thread records ordered edges ``held -> acquired``.  An *inversion*
    is a pair observed in both orders — the textbook two-lock deadlock,
    needing only two threads to hit the two sites concurrently.  The
    recorder itself takes one private lock only to mutate the edge map
    (never while a wrapped lock is being waited on), so it cannot
    introduce the orderings it reports.
    """

    def __init__(self) -> None:
        self._tls = threading.local()
        self._mu = threading.Lock()
        #: (outer, inner) -> occurrences observed
        self._edges: dict[tuple[str, str], int] = {}
        #: names ever acquired (for reporting)
        self._seen: set[str] = set()

    def wrap(self, lock: Any, name: str) -> _AuditedLock:
        return _AuditedLock(self, lock, name)

    def instrument(self, obj: Any, attr: str,
                   name: Optional[str] = None) -> _AuditedLock:
        """Replace ``obj.attr`` with an audited proxy in place."""
        wrapped = self.wrap(getattr(obj, attr),
                            name or f"{type(obj).__name__}.{attr}")
        setattr(obj, attr, wrapped)
        return wrapped

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _acquired(self, name: str) -> None:
        held = self._held()
        with self._mu:
            self._seen.add(name)
            for outer in held:
                if outer != name:
                    key = (outer, name)
                    self._edges[key] = self._edges.get(key, 0) + 1
        held.append(name)

    def _released(self, name: str) -> None:
        held = self._held()
        # remove the most recent occurrence (locks release LIFO in with-
        # blocks, but hand-rolled release orders must not corrupt state)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def edges(self) -> dict[tuple[str, str], int]:
        with self._mu:
            return dict(self._edges)

    def inversions(self) -> list[tuple[str, str]]:
        """(A, B) pairs acquired in BOTH orders, A < B; empty = clean."""
        with self._mu:
            out = sorted(
                (a, b) for (a, b) in self._edges
                if a < b and (b, a) in self._edges)
        return out

    def report(self) -> dict:
        """JSON-ready summary (chaos harness artifacts)."""
        inversions = self.inversions()  # takes _mu itself: compute FIRST
        with self._mu:
            return {
                "locks": sorted(self._seen),
                "edges": {f"{a} -> {b}": n
                          for (a, b), n in sorted(self._edges.items())},
                "inversions": [f"{a} <-> {b}" for a, b in inversions],
            }


def audit_many(audit: LockAudit,
               targets: Iterable[tuple[Any, str]]) -> None:
    """Instrument a batch of (obj, attr) lock sites in one call."""
    for obj, attr in targets:
        audit.instrument(obj, attr)
