"""Runtime auditors: the dynamic half of the platform analyzer.

Static rules (:mod:`.astlint`) catch what is *lexically* visible; these
two catch what only manifests live:

- :class:`RecompileGuard` / :func:`recompile_guard` — wraps an engine's
  jitted programs and counts jit-cache growth past each program's first
  compile.  The first compile per program is warmup (expected, paid
  once); ANY growth after that is a recompile — a shape/dtype/weak-type
  leak in the host scheduler that stalls every live request for a full
  trace+compile.  The engine exports the shared counter as its
  ``jit_recompiles_total`` stat (auto-surfaced as a /metrics gauge),
  and tier-1 asserts it stays 0 across a chunked-prefill + decode
  steady-state run.

- :class:`LockAudit` — wraps/instruments ``threading`` locks and records
  the REAL per-thread acquisition order, including orders that only
  happen under fault injection (the chaos harness's schedules).  The
  static ``lock-order`` rule sees lexical nesting; this sees the
  interleavings chaos actually produced.  ``inversions()`` returns the
  (A, B) pairs observed in both orders — each one is a deadlock that
  needs nothing more than worse timing.

- :class:`BlockLedger` — wraps a paged-KV :class:`BlockAllocator`'s
  ``alloc``/``ref``/``release`` economy verbs and keeps SHADOW
  refcounts plus per-block ownership (which sequence, which call site).
  Every wrapped op cross-checks the allocator's real refcounts
  (conservation: a drifted count is a double-free or a bypassing write
  the moment it happens, not a mystery at teardown), and
  ``audit_quiesced`` asserts the zero-leaked-blocks invariant at the
  boundaries every recent PR hand-rolled per test — slot retirement,
  migration cutover/abort, elastic resize, full engine idle.  The
  engine exports the shared tally as its ``kv_blocks_leaked_total``
  stat (auto-surfaced as a /metrics gauge) and audits automatically
  when its pool goes fully idle.

No jax import at module load: the lint CLI shares this package and must
stay stdlib-fast.  ``RecompileGuard`` only touches jax objects it is
handed.
"""

from __future__ import annotations

import re
import sys
import threading
from typing import Any, Callable, Iterable, Optional


class RecompileCounter:
    """Shared recompile tally (one per engine; thread-safe via the GIL
    for the two scalars it carries).

    ``armed`` gates counting: the engine's warmup deliberately compiles
    a LADDER of shapes per program (group sizes, attend rungs) — growth
    during that phase is the paid-once warm set, not a recompile.  The
    engine arms the counter when warmup finishes (or at first traffic
    when warmup was skipped); from then on, cache growth is a
    mid-serving stall and counts."""

    __slots__ = ("count", "armed")

    def __init__(self) -> None:
        self.count = 0
        self.armed = False


class RecompileGuard:
    """Count jit cache misses past a program's first compile.

    Wraps any callable produced by ``jax.jit`` or
    ``serving.sharded.mesh_jit`` (which exposes its inner jitted fn as
    ``_jitted``).  After each call the underlying trace-cache size is
    read (``_cache_size``, present on jax's PjitFunction); the first
    observed size is the warm set, growth beyond it increments the
    shared counter.  Programs without a readable cache (AOT-compiled
    executables, plain functions) pass through uncounted rather than
    guessing.
    """

    def __init__(self, program: Callable, counter: RecompileCounter):
        self._program = program
        self._inner = getattr(program, "_jitted", program)
        self._counter = counter
        self._warm: Optional[int] = None

    def _cache_size(self) -> Optional[int]:
        probe = getattr(self._inner, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:  # noqa: BLE001 — jax internals shifted: the
            # guard degrades to uncounted, never breaks dispatch
            return None

    def __call__(self, *args, **kwargs):
        out = self._program(*args, **kwargs)
        size = self._cache_size()
        if size is not None:
            if self._warm is None:
                # first compile of this program = its warm entry, never
                # a recompile (programs may be built lazily post-warmup:
                # a new attend rung's first compile is a cache miss by
                # design, re-tracing an EXISTING entry is the bug)
                self._warm = size
            elif size > self._warm:
                if self._counter.armed:
                    self._counter.count += size - self._warm
                self._warm = size
        return out

    def lower(self, *args, **kwargs):
        """AOT lowering passthrough (scripts/aot_7b_serving.py path)."""
        return self._program.lower(*args, **kwargs)

    @property
    def cache_entries(self) -> Optional[int]:
        """Current trace-cache size of the wrapped program (None when
        unreadable) — per-guard introspection for tests/debugging; the
        shared counter aggregates recompiles across guards."""
        return self._cache_size()


def recompile_guard(program: Callable,
                    counter: RecompileCounter) -> RecompileGuard:
    """Wrap ``program`` so cache growth past its first compile counts
    into ``counter`` (idempotent: re-wrapping a guard is a no-op)."""
    if isinstance(program, RecompileGuard):
        return program
    return RecompileGuard(program, counter)


class _AuditedLock:
    """Context-manager/acquire-release proxy recording into a LockAudit."""

    def __init__(self, audit: "LockAudit", lock: Any, name: str):
        self._audit = audit
        self._lock = lock
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._audit._acquired(self.name)
        return got

    def release(self) -> None:
        self._lock.release()
        self._audit._released(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __getattr__(self, name: str):
        # passthrough for wrapped-lock extras (RLock internals etc.);
        # only acquire/release order is audited
        return getattr(self._lock, name)


class LockAudit:
    """Record real lock-acquisition order across threads.

    Usage (chaos tests)::

        audit = LockAudit()
        audit.instrument(channel, "_lock")      # wrap an object's lock
        gate = audit.wrap(threading.Lock(), "gate")   # or wrap directly
        ... run the faulted scenario ...
        assert audit.inversions() == []

    Every acquisition while other audited locks are held by the SAME
    thread records ordered edges ``held -> acquired``.  An *inversion*
    is a pair observed in both orders — the textbook two-lock deadlock,
    needing only two threads to hit the two sites concurrently.  The
    recorder itself takes one private lock only to mutate the edge map
    (never while a wrapped lock is being waited on), so it cannot
    introduce the orderings it reports.
    """

    def __init__(self) -> None:
        self._tls = threading.local()
        self._mu = threading.Lock()
        #: (outer, inner) -> occurrences observed
        self._edges: dict[tuple[str, str], int] = {}
        #: names ever acquired (for reporting)
        self._seen: set[str] = set()

    def wrap(self, lock: Any, name: str) -> _AuditedLock:
        return _AuditedLock(self, lock, name)

    def instrument(self, obj: Any, attr: str,
                   name: Optional[str] = None) -> _AuditedLock:
        """Replace ``obj.attr`` with an audited proxy in place."""
        wrapped = self.wrap(getattr(obj, attr),
                            name or f"{type(obj).__name__}.{attr}")
        setattr(obj, attr, wrapped)
        return wrapped

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _acquired(self, name: str) -> None:
        held = self._held()
        with self._mu:
            self._seen.add(name)
            for outer in held:
                if outer != name:
                    key = (outer, name)
                    self._edges[key] = self._edges.get(key, 0) + 1
        held.append(name)

    def _released(self, name: str) -> None:
        held = self._held()
        # remove the most recent occurrence (locks release LIFO in with-
        # blocks, but hand-rolled release orders must not corrupt state)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def edges(self) -> dict[tuple[str, str], int]:
        with self._mu:
            return dict(self._edges)

    def inversions(self) -> list[tuple[str, str]]:
        """(A, B) pairs acquired in BOTH orders, A < B; empty = clean."""
        with self._mu:
            out = sorted(
                (a, b) for (a, b) in self._edges
                if a < b and (b, a) in self._edges)
        return out

    def report(self) -> dict:
        """JSON-ready summary (chaos harness artifacts)."""
        inversions = self.inversions()  # takes _mu itself: compute FIRST
        with self._mu:
            return {
                "locks": sorted(self._seen),
                "edges": {f"{a} -> {b}": n
                          for (a, b), n in sorted(self._edges.items())},
                "inversions": [f"{a} <-> {b}" for a, b in inversions],
            }


def audit_many(audit: LockAudit,
               targets: Iterable[tuple[Any, str]]) -> None:
    """Instrument a batch of (obj, attr) lock sites in one call."""
    for obj, attr in targets:
        audit.instrument(obj, attr)


class _Books:
    """One allocator's shadow state inside a BlockLedger."""

    __slots__ = ("alloc", "name", "rc", "owners", "origins", "reported")

    def __init__(self, alloc: Any, name: str):
        self.alloc = alloc
        self.name = name
        #: block -> shadow refcount (tracked while > 0)
        self.rc: dict[int, int] = {}
        #: block -> sequence/owner label (engine annotations)
        self.owners: dict[int, str] = {}
        #: block -> call-site label captured at alloc time
        self.origins: dict[int, str] = {}
        #: blocks already counted into leaked_total (a still-leaked
        #: block re-audited at the next boundary must not re-count)
        self.reported: set[int] = set()


class BlockLedger:
    """Runtime audit of the paged-KV block economy (the dynamic half of
    the zero-leaked-blocks contract).

    Usage (migration/resize parity suites)::

        ledger = BlockLedger()
        src.attach_block_ledger(ledger)     # wraps src._alloc in place
        dst.attach_block_ledger(ledger)     # one ledger, both economies
        ... run the scenario ...
        assert ledger.conservation_errors == []
        assert src.stats()["kv_blocks_leaked_total"] == 0

    One ledger may attach to SEVERAL allocators (source + destination of
    a migration, old + new degree of a resize); books are per-allocator,
    the ``leaked_total`` tally is shared — "zero leaked blocks on both
    allocators" is one assert.

    What each wrapped verb checks, synchronously on the calling
    (scheduler) thread:

    - ``alloc``  — every granted block was free and now has refcount 1;
      the grant is recorded with its caller (``origin``) so a leak
      report names the allocation site, not just the block id.
    - ``ref``    — shadow count increments with the allocator's; a
      resurrection (ref on a free registered block) opens a new entry.
    - ``release``— shadow count decrements; a release of a block the
      ledger never saw allocated is recorded as a conservation error
      (the allocator's own over-release raise still fires first when
      the REAL count goes negative).

    After every verb the touched blocks' shadow counts are compared to
    the allocator's real ``_refs`` — any drift means some code path
    mutated the economy around the wrapped verbs, and is recorded into
    :attr:`conservation_errors` at the op that exposed it.

    ``audit_quiesced(alloc, held)`` is the boundary check: every block
    still referenced must be in ``held`` (the blocks live sequences
    legitimately hold); the rest are LEAKS — counted once each into
    ``leaked_total`` and returned with owner + origin attribution.  The
    engine calls it automatically when its pool goes fully idle and on
    the ``audit`` mailbox op; tests call it at retire/cutover/resize
    boundaries.
    """

    def __init__(self) -> None:
        # RLock: the verb hooks take it around book mutation and may
        # record an error (which takes it again) mid-check; audits on
        # other threads (a test auditing a stopped engine) then iterate
        # the same books safely
        self._mu = threading.RLock()
        self._books: dict[int, _Books] = {}
        self.leaked_total = 0
        self.ops_total = 0
        #: conservation violations observed (bounded; each is one
        #: human-readable line) — tests assert this stays empty
        self.conservation_errors: list[str] = []
        self._max_errors = 64

    # -- attachment --------------------------------------------------------

    def attach(self, alloc: Any, name: str = "") -> Any:
        """Wrap ``alloc``'s economy verbs in place (idempotent).  Blocks
        already allocated open the books with their current refcounts
        (origin ``pre-attach``).

        Attach at a QUIESCENT boundary — before the engine starts, or
        while its scheduler is idle (the engine's
        ``attach_block_ledger`` callers all do).  The snapshot and the
        wrapper installation happen under the ledger lock, so
        concurrent ``attach`` calls are safe; but an economy op racing
        the installation on ANOTHER thread could slip between snapshot
        and wrap unobserved and surface later as a spurious
        conservation error — quiescence is the caller's contract."""
        with self._mu:
            if id(alloc) in self._books:
                return alloc
            books = _Books(alloc, name or f"alloc@{len(self._books)}")
            for b in range(alloc.num_blocks):
                n = int(alloc._refs[b])
                if n > 0:
                    books.rc[b] = n
                    books.origins[b] = "pre-attach"

            orig_alloc, orig_ref = alloc.alloc, alloc.ref
            orig_release = alloc.release

            def alloc_wrapped(n: int):
                out = orig_alloc(n)
                if out is not None:
                    self._on_alloc(books, out)
                return out

            def ref_wrapped(blocks):
                blocks = list(blocks)
                orig_ref(blocks)
                self._on_ref(books, blocks)

            def release_wrapped(blocks):
                blocks = list(blocks)
                orig_release(blocks)  # over-release raises HERE first
                self._on_release(books, blocks)

            alloc.alloc = alloc_wrapped
            alloc.ref = ref_wrapped
            alloc.release = release_wrapped
            self._books[id(alloc)] = books
        return alloc

    def _book(self, alloc: Any) -> Optional[_Books]:
        with self._mu:
            return self._books.get(id(alloc))

    # -- verb hooks --------------------------------------------------------

    def _error(self, books: _Books, msg: str) -> None:
        with self._mu:
            if len(self.conservation_errors) < self._max_errors:
                self.conservation_errors.append(f"[{books.name}] {msg}")

    def _check(self, books: _Books, blocks: Iterable[int]) -> None:
        """Shadow-vs-real refcount comparison for the touched blocks."""
        for b in blocks:
            real = int(books.alloc._refs[b])
            shadow = books.rc.get(b, 0)
            if real != shadow:
                self._error(
                    books,
                    f"block {b}: shadow refcount {shadow} != allocator "
                    f"{real} — a code path mutates the economy around "
                    "the wrapped verbs")
                # resync so one drift reports once, not at every op
                if real > 0:
                    books.rc[b] = real
                else:
                    books.rc.pop(b, None)

    def _origin(self) -> str:
        # the wrapped verb's caller: _origin <- _on_alloc <- wrapper <- site
        f = sys._getframe(3)
        return f.f_code.co_name

    def _on_alloc(self, books: _Books, blocks: list) -> None:
        origin = self._origin()
        with self._mu:
            self.ops_total += 1
            for b in blocks:
                b = int(b)
                if books.rc.get(b, 0) != 0:
                    self._error(
                        books, f"block {b} granted by alloc while shadow "
                        f"refcount is {books.rc[b]} (owner "
                        f"{books.owners.get(b, '?')}) — double grant")
                books.rc[b] = 1
                books.origins[b] = origin
                books.owners.pop(b, None)
                books.reported.discard(b)
            self._check(books, map(int, blocks))

    def _on_ref(self, books: _Books, blocks: list) -> None:
        origin = self._origin()
        with self._mu:
            self.ops_total += 1
            for b in blocks:
                b = int(b)
                if b not in books.rc:
                    # resurrection out of the free list (prefix hit on a
                    # retired conversation's registered blocks)
                    books.origins[b] = origin
                    books.reported.discard(b)
                books.rc[b] = books.rc.get(b, 0) + 1
            self._check(books, map(int, blocks))

    def _on_release(self, books: _Books, blocks: list) -> None:
        with self._mu:
            self.ops_total += 1
            for b in blocks:
                b = int(b)
                if b not in books.rc:
                    self._error(
                        books, f"block {b} released but the ledger never "
                        "saw it allocated — unbalanced release")
                    continue
                books.rc[b] -= 1
                if books.rc[b] <= 0:
                    books.rc.pop(b, None)
                    books.owners.pop(b, None)
                    books.reported.discard(b)
            self._check(books, map(int, blocks))

    # -- annotations -------------------------------------------------------

    def annotate(self, alloc: Any, blocks: Iterable[int],
                 owner: str) -> None:
        """Tag ``blocks`` with the owning sequence (the engine calls
        this at admission/import so leak reports name the sequence)."""
        books = self._book(alloc)
        if books is None:
            return
        with self._mu:
            for b in blocks:
                books.owners[int(b)] = owner

    # -- audits ------------------------------------------------------------

    def live(self, alloc: Any) -> dict[int, int]:
        """Shadow refcounts currently > 0 for ``alloc``."""
        books = self._book(alloc)
        if books is None:
            return {}
        with self._mu:
            return dict(books.rc)

    def verify(self, alloc: Any) -> list[str]:
        """Full-sweep conservation check: every block's shadow count vs
        the allocator's, plus free-list consistency.  Returns NEW error
        lines (also appended to :attr:`conservation_errors`)."""
        books = self._book(alloc)
        if books is None:
            return []
        with self._mu:
            before = len(self.conservation_errors)
            self._check(books, range(alloc.num_blocks))
            for b in range(alloc.num_blocks):
                free = b in alloc._free
                refd = int(alloc._refs[b]) > 0
                if free and refd:
                    self._error(books,
                                f"block {b} is on the free list with "
                                f"refcount {int(alloc._refs[b])}")
                elif not free and not refd:
                    self._error(books,
                                f"block {b} has refcount 0 but is not "
                                "on the free list — unreachable forever")
            return self.conservation_errors[before:]

    def audit_quiesced(self, alloc: Any,
                       held: Iterable[int] = ()) -> list[dict]:
        """The boundary check: blocks still referenced but NOT in
        ``held`` are leaks.  Each leak counts once into
        ``leaked_total`` (re-audits of a still-leaked block are free)
        and is returned with its owner/origin attribution."""
        books = self._book(alloc)
        if books is None:
            return []
        held_set = {int(b) for b in held}
        leaks: list[dict] = []
        with self._mu:
            for b, n in sorted(books.rc.items()):
                if n <= 0 or b in held_set:
                    continue
                leaks.append({
                    "block": b, "refcount": n, "books": books.name,
                    "owner": books.owners.get(b, ""),
                    "origin": books.origins.get(b, ""),
                })
                if b not in books.reported:
                    books.reported.add(b)
                    self.leaked_total += 1
        return leaks

    # -- host tier (ISSUE 12) ----------------------------------------------

    def attach_host_pool(self, pool: Any, name: str = "host") -> Any:
        """Extend the shadow count to a ``HostBlockPool`` (the host-RAM
        KV tier): ``put`` and the LRU eviction are wrapped so the
        pool's ``blocks_held``/``bytes_held`` gauges are conservation-
        checked against the actual entry map after every op — a tier
        transition that loses or double-counts blocks surfaces at the
        op that exposed it, exactly like the HBM books.  Idempotent."""
        with self._mu:
            key = ("host", id(pool))
            if key in self._books:
                return pool
            books = _Books(pool, name)
            self._books[key] = books  # type: ignore[index]

            orig_put, orig_evict = pool.put, pool._evict_oldest

            def put_wrapped(tokens, blocks, nbytes=None):
                out = orig_put(tokens, blocks, nbytes)
                self.ops_total += 1
                with pool._lock:
                    # put has RETURNED: the eviction loop converged,
                    # so the capacity bound may be enforced here
                    self._check_host(books, pool, check_capacity=True)
                return out

            def evict_wrapped():
                # runs inside put/put_wrapped with pool._lock HELD
                # (the only eviction site) — check without re-locking,
                # and WITHOUT the capacity bound: mid-loop the pool is
                # legitimately still over capacity (put keeps evicting
                # until it converges)
                orig_evict()
                self._check_host(books, pool)

            pool.put = put_wrapped
            pool._evict_oldest = evict_wrapped
        return pool

    def _check_host(self, books: _Books, pool: Any,
                    check_capacity: bool = False) -> None:
        # under pool._lock on put paths; eviction only runs inside put.
        # Recount is O(entries) — the pool is bounded by capacity.
        actual = sum(len(e["blocks"]) for e in pool._seqs.values())
        if actual != pool.blocks_held:
            self._error(
                books, f"host tier holds {actual} blocks but the gauge "
                f"says {pool.blocks_held} — a spill/evict path mutates "
                "the tier around the wrapped verbs")
            pool.blocks_held = actual  # resync: one drift reports once
        if check_capacity and pool.blocks_held > pool.capacity_blocks:
            self._error(
                books, f"host tier over capacity: {pool.blocks_held} > "
                f"{pool.capacity_blocks} — eviction did not converge")

    def audit_host(self, pool: Any) -> list[str]:
        """Boundary check for the host tier: re-run the conservation
        count and return NEW error lines (empty = gauges honest,
        occupancy within capacity).

        LOCK ORDER: pool._lock BEFORE self._mu — the wrapped put/evict
        verbs hold pool._lock when their checks reach ``_error`` (which
        takes ``_mu``), so an audit taking ``_mu`` first and THEN
        pool._lock would be the classic ABBA inversion: the ledger
        would deadlock the engine exactly when it detects the drift it
        exists to report."""
        with self._mu:
            books = self._books.get(("host", id(pool)))  # type: ignore
        if books is None:
            return []
        with pool._lock:
            with self._mu:
                before = len(self.conservation_errors)
                self._check_host(books, pool, check_capacity=True)
                return self.conservation_errors[before:]

    def report(self) -> dict:
        """JSON-ready summary (chaos/bench artifacts)."""
        with self._mu:
            return {
                "kv_blocks_leaked_total": self.leaked_total,
                "ops_total": self.ops_total,
                "conservation_errors": list(self.conservation_errors),
                "books": {
                    bk.name: {"live": len(bk.rc),
                              "reported_leaks": sorted(bk.reported)}
                    for bk in self._books.values()
                },
            }


# ---------------------------------------------------------------------------
# metrics-contract: the runtime (value-dependent) half
# ---------------------------------------------------------------------------

_METRIC_NAME = re.compile(r"^[a-zA-Z0-9_]+$")


def audit_stats_pair(before: dict, after: dict) -> list[str]:
    """The ``metrics-contract`` rule's runtime half (the static half —
    name validity at lint time — is rules_metrics.py): given two engine
    ``stats()`` snapshots taken around any amount of work, return the
    contract violations.

    - a ``_total``-suffixed key is an OpenMetrics counter: it must be
      present in both snapshots and monotonically non-decreasing —
      scrapes rate() counters, and a "counter" that goes down silently
      corrupts every rate computed over it;
    - every numeric key (both snapshots) must render to a valid
      Prometheus name once the exporter splices ``kft_engine_<key>``.

    Empty list = contract holds.  Pin it in tests around real traffic:
    ``assert audit_stats_pair(s0, eng.stats()) == []``.
    """
    errors: list[str] = []
    for which, stats in (("before", before), ("after", after)):
        for k, v in stats.items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            if not _METRIC_NAME.match(str(k)):
                errors.append(
                    f"{which}: key `{k}` is not a valid Prometheus "
                    "name suffix (kft_engine_<key>)")
    for k, v0 in before.items():
        if not str(k).endswith("_total"):
            continue
        if isinstance(v0, bool) or not isinstance(v0, (int, float)):
            errors.append(f"counter `{k}` is not numeric: {v0!r}")
            continue
        if k not in after:
            errors.append(
                f"counter `{k}` vanished from the later snapshot — "
                "a disappearing series resets every rate() over it")
            continue
        v1 = after[k]
        if isinstance(v1, bool) or not isinstance(v1, (int, float)):
            errors.append(f"counter `{k}` became non-numeric: {v1!r}")
        elif v1 < v0:
            errors.append(
                f"counter `{k}` went DOWN across the audit pair "
                f"({v0} -> {v1}): `_total` claims monotonic counter "
                "semantics")
    return errors
