"""Dispatch-hygiene rules: the device queue must never drain.

``host-sync-in-dispatch``: on TPU the engine's throughput is the device
queue's occupancy (PAPERS.md: "Exploring the limits of Concurrency in ML
Training on Google TPUs"); one stray ``.item()`` / ``device_get`` /
``np.asarray`` on a device value inside the scheduler's dispatch path
serializes host and device and re-introduces the per-token round trip
the dispatch-ahead pipeline exists to hide.  Since ISSUE 18 the rule
walks the CROSS-MODULE call graph (:mod:`.callgraph`) from every
``*Engine`` class's scheduler roots (``_loop``/``_admit``/
``_process``...) — ``self._helper()`` through the MRO, ``from .x
import y`` helpers, ``self.store.write()`` through attribute typing —
and flags host-materialization calls in anything reachable, in
whatever file it lives.  On the same reachability it flags blocking
SOCKET I/O (``sendall``/``recv``/``create_connection``, ISSUE 8): live
KV migration streams block bytes between replicas, and a socket send on
the scheduler thread would stall every live request for a network round
trip (or forever, on a wedged peer) — the migrate path runs on worker
threads, the scheduler only services its mailbox.  ``*Allocator`` classes (the paged-KV
block economy, serving/paged.py) sit ON the dispatch path — every
admission and block-table assembly runs them between dispatches — so
ALL their methods are roots: block-table math must stay host-side
numpy, and a ``.item()`` on the free list can never ride along
undeclared.  The engine DOES need exactly one fetch
boundary (delivering sampled tokens) and host-side numpy scheduler math
is legitimate — those sites carry ``# analysis: ok host-sync-in-dispatch``
pragmas, which is the point: the boundary is *declared*, so a new
undeclared one fails tier-1.

``jit-in-loop``: constructing a jit (or a ``make_*_program`` /
``mesh_jit``) inside a loop body builds a fresh Python callable per
iteration — each jax.jit object carries its own trace cache, so this is
a guaranteed recompile treadmill.  Program construction belongs in cached
getters (the ``_build_programs`` pattern); only *calling* a cached
program in a loop is fine.  The cross-module half: an UNGUARDED
loop-body call into a helper whose effect set carries
``jit-unguarded`` (it constructs unconditionally, wherever it lives)
is the same treadmill wearing a function call as a disguise — guarded
call sites (the ``if key not in cache:`` miss path) and memoized
builders stay quiet.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from .astlint import Finding, LintContext, rule
from .callgraph import (
    HOST_SYNC_MATCHERS,
    LIFECYCLE_METHODS,
    ROOT_METHODS,  # noqa: F401  (re-export: rules_threads roots on it)
    _dotted,  # noqa: F401  (re-export: rules_locks/threads lexical names)
    get_graph,
    is_blocking_socket,
    is_program_construction,
    walk_skip_defs,  # noqa: F401  (re-export: rules_locks scans with it)
)

_is_program_construction = is_program_construction  # back-compat alias

#: KV-tier classes (ISSUE 12): any class named *Tier*/*Spill*/
#: *Hibernat* joins the dispatch-hygiene walk (KvSpillStore,
#: SessionHibernator-style orchestrators) — substring, not suffix,
#: because the tier vocabulary composes into names freely
_TIER_CLASS = re.compile(r"Tier|Spill|Hibernat")

#: scheduler-adjacent orchestration classes whose EVERY method is a
#: dispatch-path root.  The rationale per suffix family, accreted over
#: ISSUEs 8–17: paged-KV allocators run between dispatches on the
#: scheduler thread (block-table assembly, free-list pops, prefix
#: matching — host numpy only); traffic-plane admission classes
#: (``*TrafficPlane``/``*Admission``/``*Preemptor``) run token-bucket
#: and queue accounting on router/HTTP worker threads AND the engine's
#: admission_policy hook on the scheduler thread — either way a device
#: fetch or a blocking socket in QoS bookkeeping stalls every live
#: request; elastic-resize orchestration (``*Resizer``/``*Reshard``)
#: is rooted so new scheduler-adjacent classes never go unlinted (a
#: resizer's weight fetch is DELIBERATE off-scheduler blocking — each
#: such site carries a declaring pragma instead of silence), while the
#: reshard WIRE classes (ReshardServer/ReshardClient) follow the
#: KvMigrationServer convention — dedicated worker threads whose whole
#: job is socket I/O, never reachable from a dispatch loop — so suffix
#: matching leaves them out on purpose; KV TIER classes
#: (``*BlockPool`` + the _TIER_CLASS names) are rooted because
#: HostBlockPool's match/take run ON the scheduler thread at admission
#: and the spill/hibernate store's device fetches + file I/O are
#: deliberate, declared tier transitions (spill I/O never on the
#: scheduler; the mailbox seam is the only crossing); autoscaling
#: orchestration (``*Autoscaler``/``*Scaler``/``*Reaper``) senses
#: live-engine state every tick on the reconcile worker — sensing must
#: stay host-side stdlib, heavy actuation goes through the engines'
#: public cross-thread APIs; AOT program ARTIFACT classes
#: (``*ArtifactCache``/``*ProgramStore``) are rooted because artifact
#: load/publish is warmup-only by design and this root makes that
#: promise checkable — disk I/O creeping into cache bookkeeping would
#: put host work back on the dispatch path every time a program is
#: consulted.
ROOTED_SUFFIXES = ("Allocator", "TrafficPlane", "Admission",
                   "Preemptor", "Resizer", "Reshard",
                   "BlockPool", "Autoscaler", "Scaler",
                   "Reaper", "ArtifactCache", "ProgramStore")


def dispatch_roots(graph) -> list[str]:
    """Every dispatch-path root fqual in the context: ``*Engine``
    scheduler entry points (MRO-resolved, so an inherited ``_loop``
    roots the base-class method wherever it lives) plus ALL own methods
    of the rooted-suffix / tier classes."""
    roots: list[str] = []
    for (mod, cls), ci in graph.classes.items():
        if cls.endswith("Engine"):
            for m in ROOT_METHODS:
                fq = graph.method(mod, cls, m)
                if fq:
                    roots.append(fq)
        if cls.endswith(ROOTED_SUFFIXES) or _TIER_CLASS.search(cls):
            roots.extend(ci.methods.values())
    return roots


#: host-materialization + blocking-socket matchers, in the report order
#: the rule has always used.  The matchers themselves moved to
#: callgraph.py (the effect engine shares them); the LABELS are frozen
#: strings — finding identity depends on them, so a reword would
#: resurrect every pragma'd site as "new".
_HOST_SYNCS = HOST_SYNC_MATCHERS + (
    ("blocking socket I/O (`sendall`/`recv`/`create_connection` — "
     "migration streaming must run off-thread)", is_blocking_socket),
)


def _dispatch_reachable(graph, roots: list[str]) -> set[str]:
    """Reachability with the LIFECYCLE cut: the walk models the
    steady-state dispatch phase, so it never traverses INTO
    ``__init__``/``warmup``/``stop``/... — those run before the
    scheduler exists or after it joined (the same phase contract
    rules_threads encodes).  A root that IS lifecycle-named (a rooted
    suffix class's ``__init__``) still gets scanned — only transitive
    descent is cut."""
    seen: set[str] = set()
    todo = [r for r in roots if r in graph.funcs]
    while todo:
        fq = todo.pop()
        if fq in seen:
            continue
        seen.add(fq)
        for callee, _node, _g in graph.funcs[fq].edges:
            if callee in seen:
                continue
            bare = callee.split("::", 1)[1].rsplit(".", 1)[-1]
            if bare in LIFECYCLE_METHODS:
                continue
            todo.append(callee)
    return seen


@rule("host-sync-in-dispatch")
def host_sync_in_dispatch(ctx: LintContext) -> Iterable[Finding]:
    graph = get_graph(ctx)
    for fq in sorted(_dispatch_reachable(graph, dispatch_roots(graph))):
        fi = graph.funcs[fq]
        pf = ctx.files.get(fi.relpath)
        if pf is None:
            continue
        # fi.calls is the OWN body; nested defs are their own graph
        # nodes reached through the parent's pseudo-edge, so the old
        # full-subtree walk's coverage is preserved piecewise
        for call in fi.calls:
            for label, match in _HOST_SYNCS:
                if match(call):
                    f = ctx.finding(
                        pf, "host-sync-in-dispatch", call,
                        f"host sync {label} reachable from the "
                        "engine dispatch loop")
                    if f:
                        yield f
                    break


def _iter_loop_calls(node: ast.AST, children: dict,
                     guarded: bool = False) -> Iterable[tuple]:
    """(Call, guarded) pairs in a loop's own body: nested defs/lambdas
    skipped (they run later, if ever), ``if``/``try`` bodies marked
    guarded — the lexical shape of the cache-miss idiom."""
    for child in children.get(id(node), ()):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        down = guarded or isinstance(child, (ast.If, ast.Try, ast.IfExp))
        if isinstance(child, ast.Call):
            yield child, guarded
        yield from _iter_loop_calls(child, children, down)


@rule("jit-in-loop")
def jit_in_loop(ctx: LintContext) -> Iterable[Finding]:
    graph = get_graph(ctx)
    for pf in ctx.files.values():
        for loop in pf.of_type(ast.For, ast.While, ast.AsyncFor):
            # scan only this loop's own body (nested defs build programs
            # lazily when *called* — construction is not per-iteration)
            for node, guarded in _iter_loop_calls(loop, pf.children):
                if is_program_construction(node):
                    f = ctx.finding(
                        pf, "jit-in-loop", node,
                        "jit/program construction inside a loop body "
                        "(recompile treadmill — hoist into a cached "
                        "getter)")
                    if f:
                        yield f
                    continue
                if guarded:
                    # the `if key not in cache:` miss path — building
                    # once per novel key is the getter pattern, not a
                    # treadmill
                    continue
                if pf.relpath.startswith("scripts/"):
                    # bench/entry-point scripts construct per trial ON
                    # PURPOSE (cold-start and recompile measurements);
                    # the transitive check guards library code
                    continue
                for callee in graph.resolve_call(node):
                    if "jit-unguarded" in graph.effects(callee):
                        f = ctx.finding(
                            pf, "jit-in-loop", node,
                            "loop-body call reaches unguarded "
                            f"jit/program construction in `{callee}` "
                            "(recompile treadmill — guard the call or "
                            "hoist into a cached getter)")
                        if f:
                            yield f
                        break
